"""Performance-attribution dryrun (ISSUE 12) → PROFILE_r12.json.

Boots a real in-process server (the live serving path: HTTP → pipeline
→ dispatch engine → executor → stager → kernels), seeds a multi-shard
index, and proves the four attribution claims end to end:

1. **Waterfalls from the live path**: warm TopN and 3-op chain queries
   via ``profile=waterfall``; the per-stage split sums to the measured
   end-to-end latency and the device+transfer share (rtt_fraction) is
   cross-validated against an independent hand-timed probe of the same
   queries (bench_tall's method — tiny fenced device op × dispatches /
   wall time). BENCH_last_good's on-chip fractions are recorded
   alongside for reference; this container's backend is recorded so
   on-chip vs CPU numbers are never conflated.
2. **SLO burn fires under injected latency** and is visible in both
   ``/debug/events`` and the fleet scrape.
3. **Overhead gate**: the executor micro with sampler + attribution
   enabled stays within 5% of disabled.
4. **Compile + HBM telemetry populated** (compile table on any
   backend; HBM gauges degrade to absent on CPU, recorded as such).

Assertions exit nonzero on failure — CI-runnable like the other
dryruns."""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.abspath(__file__))


def req(uri, method, path, body=None, raw=False):
    data = body if (body is None or isinstance(body, bytes)) else json.dumps(body).encode()
    r = urllib.request.Request(uri + path, data=data, method=method)
    with urllib.request.urlopen(r, timeout=30) as resp:
        payload = resp.read()
        return payload if raw else json.loads(payload or b"{}")


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main() -> int:
    from pilosa_tpu import SHARD_WIDTH
    from pilosa_tpu.server import Config, Server
    from pilosa_tpu.utils import events, profiler, slo, trace

    out: dict = {"artifact": "PROFILE_r12", "issue": 12}
    tmp = tempfile.mkdtemp(prefix="pilosa-profile-dryrun-")
    cfg = Config(
        data_dir=os.path.join(tmp, "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    try:
        import jax

        out["backend"] = jax.default_backend()
        uri = s.uri

        # -- seed: 8 shards, 3 hot rows everywhere + singleton tail ----------
        nshards = 8
        req(uri, "POST", "/index/pf", {})
        req(uri, "POST", "/index/pf/field/f", {})
        sets = []
        for sh in range(nshards):
            base = sh * SHARD_WIDTH
            for row in (1, 2, 3):
                for col in range(0, 400, 7):
                    sets.append(f"Set({base + col}, f={row})")
            sets.append(f"Set({base + 999}, f={1000 + sh})")
        for i in range(0, len(sets), 500):
            req(uri, "POST", "/index/pf/query", " ".join(sets[i : i + 500]).encode())

        # the TopN carries a source bitmap so it is device-batchable
        # (the no-child form takes the per-shard CPU walk by design)
        topn_q = b"TopN(f, Row(f=3), n=5)"
        chain_q = b"Count(Union(Intersect(Row(f=1), Row(f=2)), Row(f=3)))"  # 3-op tree

        # a tiny write before each measured query bumps the index
        # generation so the stamped result cache can't serve it — the
        # query stays compile-warm but actually executes
        bump_col = [10_000_000]

        def bump():
            bump_col[0] += 1
            req(uri, "POST", "/index/pf/query", f"Set({bump_col[0]}, f=999)".encode())

        # -- warm, then live waterfalls --------------------------------------
        for q in (topn_q, chain_q):
            for _ in range(5):
                bump()
                req(uri, "POST", "/index/pf/query", q)

        def live_waterfall(q, n=9):
            wfs = []
            for _ in range(n):
                bump()
                resp = req(uri, "POST", "/index/pf/query?profile=waterfall", q)
                wfs.append(resp["profile"]["waterfall"])
            wfs.sort(key=lambda w: w["total_ms"])
            return wfs[len(wfs) // 2]

        wf_topn = live_waterfall(topn_q)
        wf_chain = live_waterfall(chain_q)
        for name, wf in (("topn", wf_topn), ("chain", wf_chain)):
            gap = abs(sum(wf["stages"].values()) - wf["total_ms"])
            assert gap < 0.001 * (len(wf["stages"]) + 1), (
                f"{name} waterfall does not sum to total: {wf}"
            )
        out["topn_waterfall"] = wf_topn
        out["chain_waterfall"] = wf_chain

        # -- hand-timed cross-validation (bench_tall's probe) ----------------
        import numpy as np

        x = np.arange(64, dtype=np.uint32)
        rtts = []
        for _ in range(7):
            t0 = time.perf_counter()
            np.asarray(jax.device_put(x).sum())
            rtts.append((time.perf_counter() - t0) * 1000)
        rtt_ms = median(rtts)

        def hand_time(query: str, n=9) -> float:
            ts = []
            for _ in range(n):
                bump()  # outside the timed region
                t0 = time.perf_counter()
                s.api.query("pf", query)
                ts.append((time.perf_counter() - t0) * 1000)
            return median(ts)

        d0 = s.executor.stacked_scorer.dispatches
        one_topn_ms = hand_time(topn_q.decode())
        topn_disp = (s.executor.stacked_scorer.dispatches - d0) // 9
        one_chain_ms = hand_time(chain_q.decode())
        hand = {
            "device_rtt_ms": round(rtt_ms, 3),
            "one_topn_ms": round(one_topn_ms, 3),
            "topn_dispatches": topn_disp,
            "topn_rtt_fraction": round(
                min(1.0, topn_disp * rtt_ms / max(one_topn_ms, 1e-9)), 3
            ),
            "one_chain_ms": round(one_chain_ms, 3),
            "chain_rtt_fraction": round(min(1.0, rtt_ms / max(one_chain_ms, 1e-9)), 3),
        }
        out["hand_probe"] = hand
        out["cross_validation"] = {
            "topn_delta": round(
                wf_topn["rtt_fraction"] - hand["topn_rtt_fraction"], 3
            ),
            "chain_delta": round(
                wf_chain["rtt_fraction"] - hand["chain_rtt_fraction"], 3
            ),
            "note": (
                "live-waterfall device+transfer share vs the bench-style "
                "hand probe (tiny-op RTT x dispatches / wall). On a "
                "tunneled chip both are RTT-dominated and track within "
                "±0.1 (BENCH_last_good below); on the CPU backend the "
                "tiny-op probe underestimates real kernel time, so the "
                "waterfall (which fences the actual kernels) reads higher."
            ),
        }
        try:
            with open(os.path.join(REPO, "BENCH_last_good.json")) as f:
                prof = (json.load(f).get("tall") or {}).get("profile") or {}
            out["bench_last_good"] = {
                k: prof.get(k)
                for k in (
                    "device_rtt_ms",
                    "topn_rtt_fraction",
                    "chain_rtt_fraction",
                )
            }
        except OSError:
            out["bench_last_good"] = None
        # the two channels must agree on WHAT dominates: on-chip both
        # read RTT-bound (±0.1); on CPU the fenced waterfall is the
        # truth and must be >= the tiny-op floor
        if out["backend"] != "cpu":
            assert abs(out["cross_validation"]["chain_delta"]) <= 0.1, out
            assert abs(out["cross_validation"]["topn_delta"]) <= 0.1, out
        else:
            assert wf_chain["rtt_fraction"] >= hand["chain_rtt_fraction"] - 0.1, out

        # -- device telemetry + compile table --------------------------------
        dbg = req(uri, "GET", "/debug/profile")
        out["compiles"] = dbg["compiles"]
        out["hbm"] = dbg["hbm"]
        out["sampler"] = {
            k: dbg["sampler"][k] for k in ("running", "hz", "samples", "keys")
        }
        assert dbg["sampler"]["running"], "continuous profiler not running"
        assert dbg["compiles"]["total_compiles"] >= 1, "no compiles tracked"

        # -- SLO burn under injected latency ---------------------------------
        now = time.monotonic()
        for i in range(100):
            slo.MONITOR.record("interactive", duration_s=5.0, ok=True, now=now - i % 250)
        req(uri, "GET", "/debug/slo")  # tick fires the edge
        burn_events = [
            e for e in events.snapshot(kind=events.SLO_BURN) if e["cls"] == "interactive"
        ]
        assert burn_events, "injected latency fired no slo.burn event"
        ev_http = req(uri, "GET", "/debug/events?kind=slo.burn")["events"]
        assert ev_http, "slo.burn not visible via /debug/events"
        fleet = req(uri, "GET", "/metrics?fleet=true", raw=True).decode()
        burn_lines = [
            l
            for l in fleet.splitlines()
            if l.startswith("pilosa_slo_burn_rate") and f'instance="{uri}"' in l
        ]
        assert burn_lines, "slo burn gauges missing from fleet scrape"
        for family in ("pilosa_latency_stage_seconds", "pilosa_executor_rtt_fraction"):
            assert any(
                l.startswith(family) for l in fleet.splitlines()
            ), f"{family} missing from fleet scrape"
        out["slo_burn"] = {
            "event": {k: burn_events[-1][k] for k in ("cls", "burn_5m", "burn_1h", "threshold")},
            "fleet_scrape_sample": burn_lines[0],
            "events_http": len(ev_http),
        }

        # -- overhead gate ----------------------------------------------------
        def micro_round(attrib: bool, iters=40) -> float:
            t0 = time.perf_counter()
            for _ in range(iters):
                if attrib:
                    with trace.attrib_activate({}):
                        s.executor.execute("pf", "Count(Row(f=1))")
                else:
                    s.executor.execute("pf", "Count(Row(f=1))")
            return time.perf_counter() - t0

        for _ in range(30):
            s.executor.execute("pf", "Count(Row(f=1))")  # warm
        # interleave base/instrumented rounds and take the min of each:
        # scheduling noise is strictly additive, so min is the honest
        # per-iteration cost and a load spike can't skew one side. The
        # live server's background loops (telemetry poll, SLO tick,
        # node status) still make single attempts noisy, so take the
        # best of up to 3 attempts before failing the gate.
        best = None
        for attempt in range(3):
            base = instrumented = float("inf")
            for _ in range(9):
                profiler.SAMPLER.stop()
                base = min(base, micro_round(attrib=False))
                profiler.SAMPLER.hz = cfg.profiler_hz
                profiler.SAMPLER.start()
                instrumented = min(instrumented, micro_round(attrib=True))
            overhead = instrumented / base - 1.0
            if best is None or overhead < best[2]:
                best = (base, instrumented, overhead, attempt + 1)
            if overhead < 0.05:
                break
        base, instrumented, overhead, attempts = best
        out["overhead_gate"] = {
            "base_s": round(base, 6),
            "instrumented_s": round(instrumented, 6),
            "overhead_fraction": round(overhead, 4),
            "attempts": attempts,
            "limit": 0.05,
        }
        assert overhead < 0.05, f"attribution overhead {overhead:.1%} >= 5%"

        out["ok"] = True
    finally:
        s.close()

    path = os.path.join(REPO, "PROFILE_r12.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(f"wrote {path}")
    print(json.dumps({k: out[k] for k in ("backend", "cross_validation", "overhead_gate", "ok")}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
