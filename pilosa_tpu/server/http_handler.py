"""HTTP handler (L6) — REST surface over the API (reference
http/handler.go).

Public routes mirror the reference's router (handler.go:188-231); the
wire format is JSON (the reference negotiates JSON or protobuf — JSON is
the canonical format here; see docs/API.md for shapes).
"""

from __future__ import annotations

import json
import re
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

from pilosa_tpu.core import Row
from pilosa_tpu.core.fragment import FragmentQuarantinedError
from pilosa_tpu.executor import ValCount
from pilosa_tpu.server import deadline as deadline_mod
from pilosa_tpu.server.api import API, APIError
from pilosa_tpu.server.deadline import DeadlineExceeded
from pilosa_tpu.server import pipeline as pipeline_mod
from pilosa_tpu.server.pipeline import (
    CLASS_BULK,
    CLASS_INTERACTIVE,
    CLASS_INTERNAL,
    Overloaded,
)
from pilosa_tpu.parallel.multihost import GangUnavailable
from pilosa_tpu.utils.errors import NotFoundError as ExecNotFound
from pilosa_tpu.utils import events, heat, metrics, privateproto, profiler, publicproto, slo, trace
from pilosa_tpu.utils.stats import NOP_STATS

# conservative write detector for coalescing/batching eligibility: any
# hit (even a false positive from a quoted key) just forfeits the
# optimization, never correctness
_WRITE_CALL_RE = re.compile(r"\b(?:Set\w*|Clear)\s*\(")


def _require(body: dict, *keys: str) -> None:
    """400 on missing request-body fields — a malformed client body
    must never surface as an internal KeyError."""
    missing = [k for k in keys if k not in body]
    if missing:
        raise APIError(
            f"missing required field(s): {', '.join(missing)}", status=400
        )


def _qreq(q: dict, key: str) -> str:
    """Required query parameter, 400 when absent."""
    try:
        return q[key][0]
    except (KeyError, IndexError):
        raise APIError(f"missing required query param: {key}", status=400)


def _decode_proto(fn, body: Optional[bytes]):
    """Protobuf request decode with 400-on-malformed semantics: a
    clipped or corrupt wire body must never execute partially (the
    reference's gogo-proto unmarshal errors map to http 400,
    http/handler.go marshalling errors)."""
    try:
        return fn(body or b"")
    except (ValueError, TypeError, AttributeError, UnicodeDecodeError) as e:
        # TypeError/AttributeError cover wire-type confusion (e.g. the
        # query field sent as a varint): still malformed input, still 400
        raise APIError(f"unmarshalling: {e}", status=400)


def encode_result(r: Any) -> Any:
    """Query result → JSON shape (reference QueryResponse encoding)."""
    if isinstance(r, Row):
        if r.keys:
            return {"attrs": r.attrs, "keys": r.keys}
        return {"attrs": r.attrs, "columns": [int(c) for c in r.columns()]}
    if isinstance(r, ValCount):
        return {"value": r.val, "count": r.count}
    return r


class Route:
    def __init__(self, method: str, pattern: str, fn: Callable) -> None:
        self.method = method
        self.re = re.compile("^" + pattern + "$")
        self.fn = fn


class Handler:
    """Routing table + request glue, served by ThreadingHTTPServer."""

    def __init__(
        self,
        api: API,
        logger=None,
        stats=NOP_STATS,
        long_query_time: float = 0.0,
        pipeline=None,
        default_timeout: float = 0.0,
        analytics_timeout: float = 0.0,
        ingest=None,
        tenancy=None,
    ) -> None:
        self.api = api
        self.logger = logger
        self.stats = stats
        self.long_query_time = long_query_time
        # serving pipeline (server/pipeline.py); None = direct execution
        # (bare handlers in tests, pipeline-enabled = false)
        self.pipeline = pipeline
        self.default_timeout = default_timeout
        # default deadline for analytic bulk queries when the client
        # sends none (config analytics-timeout; 0 = use default_timeout)
        self.analytics_timeout = analytics_timeout
        # durable ingest queue (server/ingest.py); None = waves apply
        # synchronously through the bulk class (ingest-enabled = false)
        self.ingest = ingest
        # multi-tenant QoS policy (server/tenancy.py); None/disabled =
        # single-tenant passthrough
        self.tenancy = tenancy
        a = api
        self.routes = [
            # public (reference handler.go:188-231)
            Route(
                "GET",
                r"/",
                lambda req: {
                    "message": "pilosa_tpu is running; see /schema, /status"
                },
            ),
            Route("POST", r"/index/(?P<index>[^/]+)/query", self.post_query),
            Route("GET", r"/schema", lambda req: {"indexes": a.schema()}),
            Route("GET", r"/status", lambda req: a.status()),
            Route("GET", r"/info", lambda req: a.info()),
            Route("GET", r"/version", lambda req: {"version": a.version()}),
            Route("GET", r"/index", lambda req: {"indexes": a.schema()}),
            Route("GET", r"/index/(?P<index>[^/]+)", self.get_index),
            Route("POST", r"/index/(?P<index>[^/]+)", self.post_index),
            Route("DELETE", r"/index/(?P<index>[^/]+)", self.delete_index),
            Route(
                "POST",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)",
                self.post_field,
            ),
            Route(
                "DELETE",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)",
                self.delete_field,
            ),
            Route(
                "POST",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import",
                self.post_import,
            ),
            Route(
                "POST",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import-value",
                self.post_import_value,
            ),
            Route(
                "POST",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/ingest",
                self.post_ingest,
            ),
            Route(
                "GET",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/views",
                self.get_views,
            ),
            Route(
                "DELETE",
                r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/view/(?P<view>[^/]+)",
                self.delete_view,
            ),
            Route("GET", r"/export", self.get_export),
            Route("POST", r"/recalculate-caches", self.post_recalculate_caches),
            Route("POST", r"/cluster/resize/set-coordinator", self.post_set_coordinator),
            Route("POST", r"/cluster/resize/remove-node", self.post_remove_node),
            Route("POST", r"/cluster/resize/abort", self.post_resize_abort),
            # internal (data plane between nodes)
            Route("POST", r"/internal/cluster/message", self.post_cluster_message),
            Route("GET", r"/internal/fragment/nodes", self.get_fragment_nodes),
            Route("GET", r"/internal/fragment/blocks", self.get_fragment_blocks),
            Route("GET", r"/internal/fragment/block/data", self.get_block_data),
            Route("POST", r"/internal/fragment/block/data", self.post_block_fixes),
            Route("GET", r"/internal/fragment/data", self.get_fragment_data),
            Route("POST", r"/internal/fragment/data", self.post_fragment_data),
            Route("GET", r"/internal/shards/max", lambda req: {"standard": a.max_shards()}),
            Route("GET", r"/internal/fragments", lambda req: a.fragment_inventory()),
            Route("POST", r"/internal/probe", self.post_probe),
            Route("POST", r"/internal/gang/apply", self.post_gang_apply),
            Route("POST", r"/internal/gang/rejoin", self.post_gang_rejoin),
            # fleet observability plane (ISSUE 10): follower span push,
            # fleet membership registration, per-gang registry pulls
            Route("POST", r"/internal/trace/push", self.post_trace_push),
            Route("POST", r"/internal/fleet/register", self.post_fleet_register),
            Route("GET", r"/internal/fleet/snapshots", self.get_fleet_snapshots),
            Route("GET", r"/internal/translate/data", self.get_translate_data),
            Route("GET", r"/internal/translate/stores", self.get_translate_stores),
            Route("POST", r"/internal/translate/keys", self.post_translate_keys),
            Route(
                "POST",
                r"/internal/index/(?P<index>[^/]+)/attr/diff",
                self.post_column_attr_diff,
            ),
            Route(
                "POST",
                r"/internal/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/attr/diff",
                self.post_row_attr_diff,
            ),
            Route("GET", r"/metrics", self.get_metrics),
            Route("GET", r"/debug/pipeline", self.get_debug_pipeline),
            Route("GET", r"/debug/ingest", self.get_debug_ingest),
            Route("GET", r"/debug/dispatch", self.get_debug_dispatch),
            Route("GET", r"/debug/fusion", self.get_debug_fusion),
            Route("GET", r"/debug/chaos", self.get_debug_chaos),
            Route("POST", r"/debug/chaos", self.post_debug_chaos),
            # data integrity (ISSUE 15): scrub introspection/trigger +
            # holder-level checksummed backup/restore
            Route("GET", r"/debug/scrub", self.get_debug_scrub),
            Route("POST", r"/debug/scrub", self.post_debug_scrub),
            Route("GET", r"/backup", self.get_backup),
            Route("POST", r"/restore", self.post_restore),
            Route("GET", r"/debug/multihost", self.get_debug_multihost),
            Route("GET", r"/debug/plancache", self.get_debug_plancache),
            Route("GET", r"/debug/vars", self.get_debug_vars),
            Route("GET", r"/debug/traces", self.get_debug_traces),
            Route("GET", r"/debug/events", self.get_debug_events),
            Route("GET", r"/debug/fleet", self.get_debug_fleet),
            # workload heat intelligence + forensics bundle (ISSUE 16)
            Route("GET", r"/debug/heat", self.get_debug_heat),
            Route("GET", r"/debug/bundle", self.get_debug_bundle),
            Route("GET", r"/internal/fleet/heat", self.get_fleet_heat),
            # performance attribution (ISSUE 12): latency waterfalls,
            # continuous profiler + compile/HBM telemetry, SLO burn
            Route("GET", r"/debug/latency", self.get_debug_latency),
            Route("GET", r"/debug/profile", self.get_debug_profile),
            Route("GET", r"/debug/slo", self.get_debug_slo),
            # multi-tenant QoS (ISSUE 19): per-tenant admission /
            # scheduling / HBM / SLO state in one snapshot
            Route("GET", r"/debug/tenancy", self.get_debug_tenancy),
            Route("GET", r"/debug/translate", self.get_debug_translate),
            # index (with and without trailing slash, as net/http/pprof
            # serves it) plus the thread-dump profile; unknown names 404
            Route("GET", r"/debug/pprof/?", self.get_debug_pprof),
            Route("GET", r"/debug/pprof/goroutine", self.get_debug_pprof),
        ]

    # -- route handlers --

    def _submit(
        self,
        cls,
        thunk,
        dl,
        signature=None,
        batch=None,
        trace_ctx=None,
        index="",
        nbytes=0,
    ):
        """Run ``thunk`` through the serving pipeline (admission,
        deadline, coalescing, batching) — or directly, deadline still
        honored, when no pipeline is wired. ``index`` is the tenant for
        per-tenant admission + weighted-fair scheduling; ``nbytes``
        charges the tenant's in-flight byte ledger for the request."""
        if self.pipeline is not None:
            return self.pipeline.submit(
                cls,
                thunk,
                deadline=dl,
                signature=signature,
                batch=batch,
                trace_ctx=trace_ctx,
                index=index,
                nbytes=nbytes,
            )
        with deadline_mod.activate(dl):
            return thunk()

    def post_query(self, req) -> dict:
        index = req.params["index"]
        q = req.query
        # protobuf content negotiation (reference handlePostQuery:406 +
        # internal/public.proto QueryRequest)
        if req.is_proto:
            pbreq = _decode_proto(publicproto.decode_query_request, req.body)
            body = pbreq["query"]
            shards = pbreq["shards"]
            remote = pbreq["remote"]
            exclude_row_attrs = pbreq["excludeRowAttrs"]
            exclude_columns = pbreq["excludeColumns"]
            column_attrs = pbreq["columnAttrs"]
        else:
            body = req.body.decode() if req.body else ""
            shards = None
            if "shards" in q:
                shards = [int(s) for s in _qreq(q, "shards").split(",") if s != ""]
            remote = q.get("remote", ["false"])[0] == "true"
            exclude_row_attrs = q.get("excludeRowAttrs", ["false"])[0] == "true"
            exclude_columns = q.get("excludeColumns", ["false"])[0] == "true"
            column_attrs = q.get("columnAttrs", ["false"])[0] == "true"
        # profile=true returns the span tree; profile=waterfall returns
        # the per-stage latency split from the attribution layer
        profile_raw = q.get("profile", ["false"])[0]
        profile = profile_raw == "true"
        waterfall = profile_raw == "waterfall"
        cache = q.get("cache", ["true"])[0] != "false"
        # W3C trace context ingress: a sampled traceparent makes this
        # request a leg of a distributed trace (api.query adopts the
        # id); malformed headers parse to None and never fail the query
        trace_ctx = trace.parse_traceparent(req.headers.get("traceparent"))
        # pipeline classification (pipeline.classify_query): remote legs
        # are internal traffic; analytic bulk queries (GroupBy /
        # Distinct / Percentile) run in the BULK class with their own
        # default deadline budget (analytics-timeout), so a panel burst
        # burns the bulk SLO instead of interactive p50; everything
        # else is interactive. Read-only queries coalesce (singleflight)
        # by CANONICAL plan signature (plan/canon.py) — argument-order-
        # permuted duplicates like Intersect(Row(a),Row(b)) vs
        # Intersect(Row(b),Row(a)) share one execution; unparseable
        # text falls back to the raw bytes so syntax errors still 400
        # individually. Plain whole-index reads additionally gang into
        # combined cross-request executions.
        cls = pipeline_mod.classify_query(body, remote)
        default_t = self.default_timeout
        if cls == CLASS_BULK and self.analytics_timeout > 0:
            default_t = self.analytics_timeout
        dl = deadline_mod.from_request(req.headers, q, default_t)
        signature = None
        batch = None
        # waterfall requests skip cross-request coalescing/batching like
        # profile: a follower served by a leader's execution would report
        # the LEADER's split, not its own
        if not remote and not profile and not waterfall and not _WRITE_CALL_RE.search(body):
            from pilosa_tpu.plan.canon import query_signature

            canon_sig = query_signature(body)
            signature = (
                "q",
                index,
                canon_sig if canon_sig is not None else body,
                tuple(shards) if shards is not None else None,
                exclude_row_attrs,
                exclude_columns,
                column_attrs,
                cache,
            )
            # sampled-trace requests stay out of cross-request batching
            # (a combined execution has no per-request span tree); they
            # still coalesce — the follower records a span link
            if (
                shards is None
                and not column_attrs
                and not (trace_ctx is not None and trace_ctx[2])
            ):
                batch = {
                    "key": (index, exclude_row_attrs, exclude_columns, cache),
                    "index": index,
                    "query": body,
                    "kwargs": {
                        "exclude_row_attrs": exclude_row_attrs,
                        "exclude_columns": exclude_columns,
                        "cache": cache,
                    },
                }

        def thunk():
            return self.api.query(
                index,
                body,
                shards=shards,
                remote=remote,
                exclude_row_attrs=exclude_row_attrs,
                exclude_columns=exclude_columns,
                column_attrs=column_attrs,
                profile=profile,
                cache=cache,
                trace_ctx=trace_ctx,
                waterfall=waterfall,
            )

        t0 = time.monotonic()
        try:
            resp = self._submit(
                cls,
                thunk,
                dl,
                signature=signature,
                batch=batch,
                trace_ctx=trace_ctx,
                index=index,
                nbytes=len(req.body) if req.body else 0,
            )
        except APIError as e:
            # client errors (4xx) don't burn error budget; 5xx does
            dur = time.monotonic() - t0
            slo.MONITOR.record(cls, dur, ok=e.status < 500)
            if self.tenancy is not None and cls != CLASS_INTERNAL:
                self.tenancy.observe(index, dur, ok=e.status < 500)
            raise
        except BaseException:
            # timeouts, sheds, internal failures all consume budget
            dur = time.monotonic() - t0
            slo.MONITOR.record(cls, dur, ok=False)
            if self.tenancy is not None and cls != CLASS_INTERNAL:
                self.tenancy.observe(index, dur, ok=False)
            raise
        dur = time.monotonic() - t0
        slo.MONITOR.record(cls, dur, ok=True)
        if self.tenancy is not None and cls != CLASS_INTERNAL:
            self.tenancy.observe(index, dur, ok=True)
        # always-on waterfall: api.query attaches the summary; pop it
        # (shared dicts from coalesced responses aggregate only once)
        wf_summary = resp.pop("_waterfall", None)
        if wf_summary is not None:
            profiler.WATERFALL.record_summary(cls, wf_summary, tenant=index)
        # slow-query logging (reference handler.go:257-261)
        if self.long_query_time and dur > self.long_query_time and self.logger:
            self.logger.printf("%.3fs SLOW QUERY %s %s", dur, index, body[:500])
            self.stats.count(metrics.SLOW_QUERY, 1)
        self.stats.with_tags(f"index:{index}").timing(metrics.QUERY_TIME, dur)
        out = {"results": [encode_result(r) for r in resp["results"]]}
        if "columnAttrs" in resp:
            out["columnAttrs"] = resp["columnAttrs"]
        if "profile" in resp:
            # JSON-only: the protobuf QueryResponse has no profile field
            out["profile"] = resp["profile"]
        if "spans" in resp:
            # remote-leg envelope: this process's serialized spans ride
            # back so the root process stitches one complete tree
            out["spans"] = resp["spans"]
        if req.accepts_proto:
            return RawResponse(
                publicproto.encode_query_response(
                    out["results"], out.get("columnAttrs")
                ),
                publicproto.CONTENT_TYPE,
            )
        return out

    def get_index(self, req) -> dict:
        for ischema in self.api.schema():
            if ischema["name"] == req.params["index"]:
                return ischema
        raise APIError(f"index not found: {req.params['index']}", status=404)

    def post_index(self, req) -> dict:
        body = json.loads(req.body or b"{}")
        opts = body.get("options", {})
        self.api.create_index(req.params["index"], keys=opts.get("keys", False))
        return {}

    def delete_index(self, req) -> dict:
        self.api.delete_index(req.params["index"])
        return {}

    def post_field(self, req) -> dict:
        body = json.loads(req.body or b"{}")
        self.api.create_field(
            req.params["index"], req.params["field"], body.get("options", {})
        )
        return {}

    def delete_field(self, req) -> dict:
        self.api.delete_field(req.params["index"], req.params["field"])
        return {}

    def post_import(self, req) -> dict:
        if req.is_proto:
            body = _decode_proto(publicproto.decode_import_request, req.body)
            # reference wire timestamps are unix-nanoseconds
            # (Go time.Unix(0, ts)); the API layer expects seconds
            if body.get("timestamps"):
                body["timestamps"] = [
                    t / 1e9 if t else None for t in body["timestamps"]
                ]
        else:
            body = json.loads(req.body or b"{}")
        dl = deadline_mod.from_request(req.headers, req.query, self.default_timeout)
        if body.get("local"):
            # owner-side leg of a routed import: internal traffic
            self._submit(
                CLASS_INTERNAL,
                lambda: self.api.import_bits_local(
                    req.params["index"],
                    req.params["field"],
                    body.get("rowIDs", []),
                    body.get("columnIDs", []),
                    timestamps=body.get("timestamps"),
                ),
                dl,
            )
            return self._import_ok(req)
        self._submit(
            CLASS_BULK,
            lambda: self.api.import_bits(
                req.params["index"],
                req.params["field"],
                body.get("rowIDs", []),
                body.get("columnIDs", []),
                timestamps=body.get("timestamps"),
                row_keys=body.get("rowKeys"),
                column_keys=body.get("columnKeys"),
            ),
            dl,
        )
        return self._import_ok(req)

    def _import_ok(self, req):
        if req.accepts_proto or req.is_proto:
            # empty ImportResponse message (reference handlePostImport)
            return RawResponse(b"", publicproto.CONTENT_TYPE)
        return {}

    def post_ingest(self, req) -> dict:
        """Durable streaming ingest (server/ingest.py): sets AND clears
        in one batch; blocks until the batch's write wave is
        group-committed (fsynced) — a 200 means the writes survive
        SIGKILL. Queue overflow answers 429 + Retry-After; a wave that
        cannot commit before the request deadline answers 504 (the
        write's outcome is then indeterminate)."""
        body = json.loads(req.body or b"{}")
        rows = body.get("rowIDs", [])
        cols = body.get("columnIDs", [])
        sets = body.get("sets")
        row_keys = body.get("rowKeys")
        column_keys = body.get("columnKeys")
        if row_keys or column_keys:
            # keyed ingest: resolve the whole batch to ids BEFORE the
            # queue sees it — write waves (and their routed local legs,
            # which never carry keys) are id-only, and the translate
            # assignments group-commit ahead of the wave's own fsync
            t_rows, t_cols = self.api.translate_ingest_keys(
                req.params["index"],
                req.params["field"],
                row_keys,
                column_keys,
            )
            if t_rows is not None:
                rows = t_rows
            if t_cols is not None:
                cols = t_cols
        dl = deadline_mod.from_request(req.headers, req.query, self.default_timeout)
        if body.get("local"):
            # owner-side leg of a routed wave: apply directly (the
            # leader already coalesced it; re-queueing would chain this
            # node's committer behind the caller's) — the group commit
            # below still fsyncs before the 200, so durability holds
            changed = self._submit(
                CLASS_INTERNAL,
                lambda: self.api.apply_write_wave_local(
                    req.params["index"], req.params["field"], rows, cols, sets
                ),
                dl,
            )
            return {"acked": len(rows), "changed": changed}
        if self.ingest is not None:
            # the queue is its own admission class — no pipeline leg,
            # but the request deadline still bounds the commit wait (a
            # stalled committer must not pin HTTP workers forever)
            acked = self.ingest.submit(
                req.params["index"],
                req.params["field"],
                rows,
                cols,
                sets,
                deadline=dl,
            )
            return {"acked": acked}
        changed = self._submit(
            CLASS_BULK,
            lambda: self.api.apply_write_wave(
                req.params["index"], req.params["field"], rows, cols, sets
            ),
            dl,
        )
        return {"acked": len(rows), "changed": changed}

    def get_debug_translate(self, req) -> dict:
        """Key-translation snapshot: per-store key counts and log
        bytes, minted/adopted/forward counters, reverse-LRU hit
        ratio."""
        return self.api.translate_debug()

    def get_debug_ingest(self, req) -> dict:
        """Ingest write-ahead queue snapshot: depth/limit, wave and
        acked/shed counters, last wave size + commit latency."""
        if self.ingest is None:
            return {"enabled": False}
        out = self.ingest.stats()
        out["enabled"] = True
        return out

    def post_import_value(self, req) -> dict:
        if req.is_proto:
            body = _decode_proto(publicproto.decode_import_value_request, req.body)
        else:
            body = json.loads(req.body or b"{}")
        dl = deadline_mod.from_request(req.headers, req.query, self.default_timeout)
        if body.get("local"):
            self._submit(
                CLASS_INTERNAL,
                lambda: self.api.import_values_local(
                    req.params["index"],
                    req.params["field"],
                    body.get("columnIDs", []),
                    body.get("values", []),
                ),
                dl,
            )
            return self._import_ok(req)
        self._submit(
            CLASS_BULK,
            lambda: self.api.import_values(
                req.params["index"],
                req.params["field"],
                body.get("columnIDs", []),
                body.get("values", []),
                column_keys=body.get("columnKeys"),
            ),
            dl,
        )
        return self._import_ok(req)

    def get_views(self, req) -> dict:
        return {"views": self.api.views(req.params["index"], req.params["field"])}

    def delete_view(self, req) -> dict:
        self.api.delete_view(
            req.params["index"], req.params["field"], req.params["view"]
        )
        return {}

    def get_export(self, req):
        q = req.query
        csv_bytes = self.api.export_csv(
            _qreq(q, "index"), _qreq(q, "field"), int(_qreq(q, "shard"))
        )
        return RawResponse(csv_bytes, "text/csv")

    def post_recalculate_caches(self, req) -> dict:
        self.api.recalculate_caches()
        return {}

    def post_set_coordinator(self, req) -> dict:
        body = json.loads(req.body or b"{}")
        self.api.set_coordinator(body.get("id", ""))
        return {}

    def post_remove_node(self, req) -> dict:
        body = json.loads(req.body or b"{}")
        self.api.remove_node(body.get("id", ""))
        return {}

    def post_resize_abort(self, req) -> dict:
        self.api.resize_abort()
        return {}

    def post_cluster_message(self, req) -> dict:
        if privateproto.CONTENT_TYPE in req.headers.get("content-type", ""):
            try:
                msg = privateproto.unmarshal_message(req.body or b"")
            except Exception as e:
                # any decode failure is malformed input (wire-type
                # confusion raises TypeError/AttributeError, not just
                # ValueError) — it must 400, never execute or 500
                raise APIError(f"unmarshaling message: {e}", 400)
        else:
            msg = json.loads(req.body or b"{}")
        self.api.cluster_message(msg)
        return {}

    def get_fragment_nodes(self, req) -> list:
        q = req.query
        return self.api.shard_nodes(_qreq(q, "index"), int(_qreq(q, "shard")))

    def get_fragment_blocks(self, req) -> dict:
        q = req.query
        return {
            "blocks": self.api.fragment_blocks(
                _qreq(q, "index"),
                _qreq(q, "field"),
                int(_qreq(q, "shard")),
                view=q.get("view", ["standard"])[0],
            )
        }

    def post_block_fixes(self, req) -> dict:
        """Anti-entropy view-aware block-merge push (see
        api.apply_block_fixes)."""
        body = json.loads(req.body or b"{}")
        _require(body, "index", "field", "shard")
        self.api.apply_block_fixes(
            body["index"],
            body["field"],
            body.get("view", "standard"),
            int(body["shard"]),
            body.get("rows", []),
            body.get("columns", []),
            body.get("clearRows", []),
            body.get("clearColumns", []),
        )
        return {}

    def get_block_data(self, req) -> dict:
        q = req.query
        return self.api.fragment_block_data(
            _qreq(q, "index"),
            _qreq(q, "field"),
            q.get("view", ["standard"])[0],
            int(_qreq(q, "shard")),
            int(_qreq(q, "block")),
        )

    def get_fragment_data(self, req):
        q = req.query
        data = self.api.marshal_fragment(
            _qreq(q, "index"),
            _qreq(q, "field"),
            q.get("view", ["standard"])[0],
            int(_qreq(q, "shard")),
        )
        return RawResponse(data, "application/octet-stream")

    def post_fragment_data(self, req) -> dict:
        q = req.query
        # resize/backup streaming: heavy internal data-plane work, so it
        # rides the internal admission queue
        self._submit(
            CLASS_INTERNAL,
            lambda: self.api.unmarshal_fragment(
                _qreq(q, "index"),
                _qreq(q, "field"),
                q.get("view", ["standard"])[0],
                int(_qreq(q, "shard")),
                req.body,
            ),
            deadline_mod.from_request(req.headers, q, self.default_timeout),
        )
        return {}

    def post_probe(self, req) -> dict:
        """SWIM ping-req relay: probe the named node on the caller's
        behalf and report whether it answered (indirect liveness;
        reference memberlist IndirectChecks)."""
        body = json.loads(req.body or b"{}")
        _require(body, "uri")
        return {"alive": self.api.probe_node(body["uri"])}

    def post_gang_apply(self, req) -> dict:
        """Replicated-mode gang replication: apply one epoch-stamped
        descriptor from the gang leader (409 on a stale epoch)."""
        body = json.loads(req.body or b"{}")
        _require(body, "kind")
        self.api.gang_apply(
            int(body["kind"]), body.get("payload") or {}, int(body.get("epoch", 0))
        )
        return {}

    def post_gang_rejoin(self, req) -> dict:
        """A re-staged follower announcing itself; the leader re-forms
        the gang around it and returns the new epoch."""
        body = json.loads(req.body or b"{}")
        _require(body, "uri")
        return self.api.gang_rejoin(body["uri"])

    def get_translate_data(self, req):
        q = req.query
        data = self.api.get_translate_data(
            int(q.get("offset", ["0"])[0]), q.get("store", [""])[0]
        )
        return RawResponse(data, "application/octet-stream")

    def get_translate_stores(self, req) -> list:
        """Durable translate stores + byte offsets (pull replication)."""
        return self.api.translate_stores()

    def post_translate_keys(self, req) -> dict:
        """Owner-side key minting for federated forwards: one id space
        per key partition across the cluster (pilosa_tpu/translate/)."""
        body = json.loads(req.body or b"{}")
        _require(body, "index")
        ids = self.api.translate_keys(
            body["index"], body.get("field", ""), body.get("keys", [])
        )
        return {"ids": ids}

    def post_column_attr_diff(self, req) -> dict:
        body = json.loads(req.body or b"{}")
        return {
            "attrs": self.api.column_attr_diff(
                req.params["index"], body.get("blocks", [])
            )
        }

    def post_row_attr_diff(self, req) -> dict:
        body = json.loads(req.body or b"{}")
        return {
            "attrs": self.api.row_attr_diff(
                req.params["index"], req.params["field"], body.get("blocks", [])
            )
        }

    def _expvar_snapshot(self) -> dict:
        """The server's in-process stats snapshot: prefer the always-kept
        ExpvarStatsClient (lit even when the configured sink is statsd),
        falling back to whatever snapshot the handler's stats offer."""
        server = getattr(self.api, "server", None)
        ev = getattr(server, "_expvar", None)
        if ev is not None:
            return ev.snapshot()
        if hasattr(self.stats, "snapshot"):
            return self.stats.snapshot()
        return {}

    def get_debug_vars(self, req) -> dict:
        out = self._expvar_snapshot()
        # process-global registry (executor routing, batcher, stager,
        # caches, device health, cluster fan-out)
        out["metrics"] = metrics.snapshot()
        health = getattr(self.api.executor, "health", None)
        if health is not None:
            out["device_health"] = {
                "healthy": health.healthy,
                "trips": health.trips,
                "restores": health.restores,
                "slow_calls": health.slow_calls,
                "saturations": health.saturations,
                "restore_failures": health.restore_failures,
            }
        return out

    def get_metrics(self, req):
        """Prometheus text exposition: the process-global registry
        merged with this server's expvar snapshot plus scrape-time
        freshness gauges (device health, HBM staging residency).
        ``?fleet=true`` on a fleet collector (gang/federation leader)
        returns the AGGREGATED view instead: every registered rank's
        registry snapshot, each sample tagged ``instance=<label>``."""
        if req.query.get("fleet", ["false"])[0] == "true":
            fleet = self._fleet()
            if fleet is None:
                raise APIError(
                    "fleet metrics need a fleet collector (server-attached "
                    "handler); this process has none",
                    status=400,
                )
            text = metrics.render_prometheus(
                registry=metrics.Registry(), instances=fleet.collect()
            )
            return RawResponse(
                text.encode(), "text/plain; version=0.0.4; charset=utf-8"
            )
        health = getattr(self.api.executor, "health", None)
        if health is not None:
            metrics.gauge(
                metrics.DEVICEHEALTH_HEALTHY, 1.0 if health.healthy else 0.0
            )
        stager = getattr(self.api.executor, "stager", None)
        if stager is not None:
            metrics.gauge(metrics.STAGER_BYTES, stager._bytes)
        # scrape-time freshness: uptime companion to build_info, and the
        # SLO gauges re-derived from the sample windows so the scrape
        # never reads a stale burn rate between server ticks
        srv = getattr(self.api, "server", None)
        started = getattr(srv, "started_at", None)
        if started:
            metrics.gauge(metrics.UPTIME_SECONDS, round(time.time() - started, 3))
        slo.MONITOR.tick()
        text = metrics.render_prometheus(
            extra_snapshots=[self._expvar_snapshot()]
        )
        return RawResponse(
            text.encode(), "text/plain; version=0.0.4; charset=utf-8"
        )

    def _fleet(self):
        """The server's fleet collector (server/fleet.py), or None on a
        bare handler."""
        return getattr(getattr(self.api, "server", None), "fleet", None)

    def get_debug_plancache(self, req) -> dict:
        """Plan result-cache snapshot: entries/bytes, hit ratio,
        invalidations, evictions, epoch (plan/cache.py)."""
        pc = getattr(self.api.executor, "plan_cache", None)
        if pc is None:
            return {"enabled": False}
        return pc.stats()

    def get_debug_multihost(self, req) -> dict:
        """Multihost gang snapshot: rank/world, degraded flag, queue
        depth, follower loop counters (parallel/multihost.py)."""
        mh = getattr(getattr(self.api, "server", None), "multihost", None)
        if mh is None:
            return {"enabled": False}
        out = mh.stats()
        out["enabled"] = True
        return out

    def get_debug_pipeline(self, req) -> dict:
        """Serving-pipeline snapshot: per-class queue depth/limit,
        busy workers, admissions, sheds, coalesce/batch counters."""
        if self.pipeline is None:
            return {"enabled": False}
        return self.pipeline.stats()

    def get_debug_dispatch(self, req) -> dict:
        """Continuous-batching dispatch engine snapshot: queue depth,
        in-flight waves, wave/dedup/fallback counters, device-idle
        fraction."""
        engine = getattr(self.api.executor, "dispatch_engine", None)
        if engine is None:
            return {"enabled": False}
        return engine.stats()

    def get_debug_fusion(self, req) -> dict:
        """Whole-query/wave fusion snapshot: fused launches, calls per
        launch, bytes returned, bypass reasons, compiled program count,
        and the device-resident plan cache (entries/bytes/hit ratio)."""
        fuser = getattr(self.api.executor, "fuser", None)
        if fuser is None:
            return {"enabled": False}
        return fuser.stats()

    def get_debug_chaos(self, req) -> dict:
        """Device-robustness snapshot: the HBM governor ledger, the
        OOM-recovery counters, health-gate trips, and which injected
        fault schedules are currently installed."""
        from pilosa_tpu.core import fragment as fragment_mod
        from pilosa_tpu.utils import chaos as chaos_mod

        ex = self.api.executor
        server = getattr(self.api, "server", None)
        gov = getattr(ex, "governor", None)
        oom = getattr(ex, "_oom", None)
        health = getattr(ex, "health", None)
        return {
            "enabled": bool(
                server is not None
                and getattr(server.config, "chaos_enabled", False)
            ),
            "governor": gov.stats() if gov is not None else None,
            "oom": oom.stats() if oom is not None else None,
            "health_trips": health.trips if health is not None else 0,
            "faults": {
                "storage": bool(fragment_mod.FAULTS),
                "device": bool(chaos_mod.FAULTS),
            },
        }

    def post_debug_chaos(self, req) -> dict:
        """Install or clear fault windows on a LIVE server — the chaos
        harness's window control. Body: ``{"storage": "<spec>",
        "device": "<spec>"}``; an empty/absent spec clears that family
        (distributed faults wrap the gang channel at boot, so they ride
        the ``distributed-faults`` knob, not this endpoint). Gated by
        ``chaos-enabled``: a production server must not expose a fault
        injector. Each transition journals ``chaos.window``."""
        server = getattr(self.api, "server", None)
        if server is None or not getattr(server.config, "chaos_enabled", False):
            raise APIError(
                "chaos endpoint disabled (chaos-enabled = false)", status=403
            )
        from pilosa_tpu.core import fragment as fragment_mod
        from pilosa_tpu.utils import chaos as chaos_mod

        body = json.loads(req.body or b"{}")
        storage = str(body.get("storage") or "")
        device = str(body.get("device") or "")
        try:
            fragment_mod.install_storage_faults(storage)
            chaos_mod.install_device_faults(device)
        except ValueError as e:
            raise APIError(str(e), status=400)
        installed = bool(storage or device)
        events.record(
            events.CHAOS_WINDOW,
            action="install" if installed else "clear",
            storage=storage,
            device=device,
        )
        return {"installed": installed, "storage": storage, "device": device}

    def get_debug_scrub(self, req) -> dict:
        """Background scrubber state: sweep counters, last-sweep timing,
        config, and the unrecoverable-fragment record. NOT chaos-gated —
        this is an operator health surface, not a fault injector."""
        scrubber = getattr(
            getattr(self.api, "server", None), "scrubber", None
        )
        if scrubber is None:
            raise APIError("no scrubber (server not running)", status=503)
        return scrubber.stats()

    def post_debug_scrub(self, req) -> dict:
        """Operator "scrub now": run one synchronous sweep and return
        its summary ({scanned, corrupt, repaired, unrecoverable}).
        Body ``{"index": "<name>"}`` scopes the sweep to one index;
        ``{"repair": false}`` detects and quarantines without pulling
        replica copies (damage survey before repair)."""
        scrubber = getattr(
            getattr(self.api, "server", None), "scrubber", None
        )
        if scrubber is None:
            raise APIError("no scrubber (server not running)", status=503)
        body = json.loads(req.body or b"{}")
        return scrubber.sweep(
            index=str(body.get("index") or ""),
            repair=body.get("repair"),
        )

    def get_backup(self, req):
        """Full-holder backup archive (tar): MANIFEST.json with per-entry
        blake2b checksums, schema.json, and every fragment's roaring
        bytes. ``pilosa_tpu backup`` streams this to a file."""
        return RawResponse(self.api.backup(), "application/x-tar")

    def post_restore(self, req) -> dict:
        """Restore a holder backup. The whole archive is verified
        against its manifest (and every fragment parsed) before any
        byte is applied; a tampered archive is refused with 400."""
        return self.api.restore(req.body)

    def get_debug_traces(self, req) -> dict:
        """Recent completed query traces (the tracer's ring buffer) as
        JSON span trees, newest last; stitched with any remote spans
        pushed for their trace ids. Filters: ``?trace_id=``,
        ``?min_ms=``, ``?gang=``."""
        q = req.query
        min_ms = q.get("min_ms", [None])[0]
        try:
            min_ms_f = float(min_ms) if min_ms is not None else None
        except ValueError:
            raise APIError(f"invalid min_ms: {min_ms!r}", status=400)
        return {
            "traces": trace.TRACER.recent(
                trace_id=q.get("trace_id", [None])[0],
                min_ms=min_ms_f,
                gang=q.get("gang", [None])[0],
            )
        }

    def get_debug_events(self, req) -> dict:
        """The lifecycle event journal (utils/events.py): gang state
        transitions, degrades, re-forms, retry exhaustion, profiler and
        SLO alerts — bounded, ordered by seq. Filters: ``?kind=``,
        ``?since=<seq>``, ``?limit=<n>`` (newest n after filtering)."""
        q = req.query
        try:
            since = int(q.get("since", ["0"])[0])
            limit = int(q.get("limit", ["0"])[0])
        except ValueError:
            raise APIError("invalid since/limit: must be an integer", status=400)
        return {
            "events": events.snapshot(
                kind=q.get("kind", [None])[0], since_seq=since, limit=limit
            )
        }

    def get_debug_latency(self, req) -> dict:
        """Latency waterfalls (ISSUE 12): the stage taxonomy, the live
        rtt_fraction EMA, recent per-query waterfalls, and the
        per-class/per-stage summaries from the metric registry.
        ``?limit=<n>`` bounds the recent ring."""
        q = req.query
        try:
            limit = int(q.get("limit", ["0"])[0])
        except ValueError:
            raise APIError("invalid limit: must be an integer", status=400)
        out = profiler.WATERFALL.snapshot(limit=limit)
        snap = metrics.snapshot()
        prefix = metrics.LATENCY_STAGE_SECONDS
        out["summary"] = {
            k: v
            for k, v in snap.items()
            # flat keys carry aggregation suffixes (.hist etc.)
            if k.split(";", 1)[0].startswith(prefix)
        }
        return out

    def get_debug_profile(self, req) -> dict:
        """Continuous-profiler surface: stack-sampler top frames,
        per-signature compile table, HBM telemetry, and on-demand
        ``jax.profiler`` capture control (``?capture=start&dir=<path>``
        / ``?capture=stop``). ``?top=<n>`` sizes the tables."""
        q = req.query
        try:
            top = int(q.get("top", ["25"])[0])
        except ValueError:
            raise APIError("invalid top: must be an integer", status=400)
        capture = q.get("capture", [None])[0]
        out: dict = {
            "sampler": profiler.SAMPLER.snapshot(top=top),
            "compiles": profiler.COMPILES.snapshot(top=top),
            "hbm": profiler.TELEMETRY.snapshot(),
            "capture": profiler.capture_status(),
        }
        if capture == "start":
            out["capture"] = profiler.start_capture(
                q.get("dir", ["/tmp/pilosa-profile"])[0]
            )
        elif capture == "stop":
            out["capture"] = profiler.stop_capture()
        elif capture is not None:
            raise APIError("capture must be start or stop", status=400)
        return out

    def get_debug_slo(self, req) -> dict:
        """SLO burn-rate snapshot: per-class objectives, 5m/1h burn
        rates, budget remaining, and firing state. Gauges refresh as a
        side effect, same as the scrape path."""
        slo.MONITOR.tick()
        return slo.MONITOR.snapshot()

    def get_debug_tenancy(self, req) -> dict:
        """Multi-tenant QoS snapshot (server/tenancy.py): per-tenant
        policy + bucket state, pipeline fairness counters, HBM
        attribution and quotas, latency waterfalls, heat rollup, and
        per-tenant SLO burn — the whole tenant story in one body."""
        from pilosa_tpu.server.tenancy import TENANT_SLO_PREFIX

        tn = self.tenancy
        out: dict = (
            tn.snapshot() if tn is not None else {"enabled": False, "tenants": {}}
        )
        if self.pipeline is not None:
            ps = self.pipeline.stats()
            out["pipeline"] = {
                "weighted_fair": ps.get("weighted_fair", False),
                "tenants": ps.get("tenants", {}),
            }
        gov = getattr(self.api.executor, "governor", None)
        if gov is not None:
            gs = gov.stats()
            out["hbm"] = {
                "index_quotas": gs.get("index_quotas", {}),
                "index_used": gs.get("index_used", {}),
            }
        engine = getattr(self.api.executor, "dispatch_engine", None)
        if engine is not None:
            out["dispatch"] = engine.stats().get("tenants", {})
        out["waterfalls"] = profiler.WATERFALL.tenant_waterfalls()
        if heat.LEDGER.enabled:
            out["heat"] = heat.tenant_rollup(
                heat.LEDGER.snapshot().get("cells", [])
            )
        # per-tenant SLO burn state (tenant:<index> classes in the
        # shared monitor)
        slo.MONITOR.tick()
        snap = slo.MONITOR.snapshot()
        out["slo"] = {
            cls[len(TENANT_SLO_PREFIX):]: st
            for cls, st in snap.get("classes", {}).items()
            if cls.startswith(TENANT_SLO_PREFIX)
        }
        return out

    def get_debug_fleet(self, req) -> dict:
        """Fleet collector membership + scrape health (JSON twin of
        ``/metrics?fleet=true``)."""
        fleet = self._fleet()
        if fleet is None:
            return {"enabled": False}
        out = fleet.debug()
        out["enabled"] = True
        return out

    def post_trace_push(self, req) -> dict:
        """Gang followers push their replay span dicts here (the
        collective plane is one-way, so spans ride this HTTP side
        channel); ``recent()``/``stitched()`` merge them at read time."""
        body = json.loads(req.body or b"{}")
        _require(body, "trace_id", "spans")
        spans = body["spans"] or []
        trace.TRACER.graft_remote(body["trace_id"], spans)
        if spans:
            metrics.count(metrics.TRACE_REMOTE_SPANS, len(spans), source="push")
        return {}

    def post_fleet_register(self, req) -> dict:
        """A gang member announcing its scrape endpoint to its leader's
        fleet collector."""
        body = json.loads(req.body or b"{}")
        _require(body, "uri")
        fleet = self._fleet()
        if fleet is None:
            return {"registered": False}
        fleet.register(
            body["uri"],
            rank=int(body.get("rank", -1)),
            gang=body.get("gang", ""),
        )
        return {"registered": True}

    def get_fleet_snapshots(self, req) -> dict:
        """Gang-local registry snapshots: this process plus every member
        registered with its collector — what a federation leader pulls
        from peer gang leaders to build the fleet view."""
        fleet = self._fleet()
        if fleet is None:
            return {"snapshots": []}
        return {"snapshots": fleet.gang_snapshots()}

    def get_debug_heat(self, req) -> dict:
        """Workload heat ledger (utils/heat.py): per-(index, field,
        shard) read/write/staging counters, decayed EWMA scores, and
        placement-skew stats. Filters: ``?index=``, ``?dim=`` (ranking
        dimension — ``heat`` or a raw counter), ``?top=<k>``.
        ``?fleet=true`` on a fleet collector returns the MERGED view:
        every reachable instance's cells summed, skew recomputed over
        the whole fleet."""
        q = req.query
        dim = q.get("dim", ["heat"])[0]
        index = q.get("index", [""])[0]
        try:
            top = int(q.get("top", ["10"])[0])
        except ValueError:
            raise APIError("invalid top: must be an integer", status=400)
        try:
            if q.get("fleet", ["false"])[0] == "true":
                fleet = self._fleet()
                if fleet is None:
                    raise APIError(
                        "fleet heat needs a fleet collector (server-attached "
                        "handler); this process has none",
                        status=400,
                    )
                pairs = fleet.collect_heat()
                if index:
                    pairs = [
                        (
                            label,
                            {
                                **snap,
                                "cells": [
                                    c
                                    for c in snap.get("cells", [])
                                    if c.get("index") == index
                                ],
                            },
                        )
                        for label, snap in pairs
                    ]
                out = heat.merge_fleet(pairs, dim=dim, top_k=top)
                out["fleet"] = True
                return out
            return heat.LEDGER.snapshot(index=index, dim=dim, top_k=top)
        except ValueError as e:
            raise APIError(str(e), status=400)

    def get_fleet_heat(self, req) -> dict:
        """Gang-local heat snapshots: this process plus every member
        registered with its collector — the heat-ledger leg of the
        fleet telemetry plane."""
        fleet = self._fleet()
        if fleet is None:
            return {"heat": [["", heat.LEDGER.snapshot()]]}
        return {"heat": fleet.gang_heat()}

    def get_debug_bundle(self, req):
        """Incident forensics bundle: ONE deterministic tar (fixed
        entry metadata, sorted names, blake2b-128 manifest — the
        backup archive's idiom) capturing config, status, metrics,
        recent traces, the events tail, the heat snapshot, and
        governor/dispatch/fusion stats. ``pilosa_tpu debug-bundle``
        streams it to a file."""
        import hashlib
        import io
        import tarfile

        srv = getattr(self.api, "server", None)
        entries: dict = {}

        def put_json(name: str, obj) -> None:
            entries[name] = json.dumps(
                obj, indent=2, sort_keys=True, default=str
            ).encode()

        if srv is not None and getattr(srv, "config", None) is not None:
            entries["config.toml"] = srv.config.to_toml().encode()
        put_json("status.json", self.api.status())
        entries["metrics.txt"] = metrics.render_prometheus(
            extra_snapshots=[self._expvar_snapshot()]
        ).encode()
        put_json("vars.json", self.get_debug_vars(req))
        put_json("traces.json", {"traces": trace.TRACER.recent()})
        put_json("events.json", {"events": events.snapshot(limit=500)})
        put_json("heat.json", heat.LEDGER.snapshot())
        put_json("dispatch.json", self.get_debug_dispatch(req))
        put_json("fusion.json", self.get_debug_fusion(req))
        put_json("chaos.json", self.get_debug_chaos(req))
        manifest = {
            "entries": {
                n: hashlib.blake2b(b, digest_size=16).hexdigest()
                for n, b in sorted(entries.items())
            }
        }
        entries["MANIFEST.json"] = json.dumps(
            manifest, indent=2, sort_keys=True
        ).encode()
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w") as tw:
            for name in sorted(entries):
                blob = entries[name]
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                info.mode = 0o600
                info.mtime = 0
                tw.addfile(info, io.BytesIO(blob))
        return RawResponse(out.getvalue(), "application/x-tar")

    def get_debug_pprof(self, req):
        """Live thread stack dump — the CPython analog of the reference's
        net/http/pprof mount (http/handler.go:195): profiling text an
        operator can curl from a wedged server."""
        import sys
        import threading as _t

        names = {t.ident: t.name for t in _t.enumerate()}
        lines = []
        for ident, frame in sys._current_frames().items():
            lines.append(f"goroutine-analog {names.get(ident, '?')} [{ident}]:")
            lines.extend(
                line.rstrip() for line in traceback.format_stack(frame)
            )
            lines.append("")
        return RawResponse("\n".join(lines).encode(), "text/plain; charset=utf-8")

    # -- dispatch --

    def handle(
        self,
        method: str,
        path: str,
        query: dict,
        body: bytes,
        headers: Optional[dict] = None,
    ):
        for route in self.routes:
            if route.method != method:
                continue
            m = route.re.match(path)
            if m:
                req = Request(m.groupdict(), query, body, headers)
                return route.fn(req)
        raise APIError(f"no route for {method} {path}", status=404)


class Request:
    def __init__(
        self, params: dict, query: dict, body: bytes, headers: Optional[dict] = None
    ) -> None:
        self.params = params
        self.query = query
        self.body = body
        self.headers = {k.lower(): v for k, v in (headers or {}).items()}

    @property
    def is_proto(self) -> bool:
        return publicproto.CONTENT_TYPE in self.headers.get("content-type", "")

    @property
    def accepts_proto(self) -> bool:
        return publicproto.CONTENT_TYPE in self.headers.get("accept", "")


class RawResponse:
    def __init__(self, data: bytes, content_type: str) -> None:
        self.data = data
        self.content_type = content_type


def make_http_server(handler: Handler, host: str = "127.0.0.1", port: int = 0):
    """Build a ThreadingHTTPServer around the routing table."""

    class _Req(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # TCP_NODELAY: without it, a keep-alive client pays the
        # Nagle + delayed-ACK interaction (~40 ms) on EVERY small
        # response — measured 23 qps vs 1,300+ on this loopback. The
        # reference's Go net/http sets it by default.
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # silence default stderr logging
            if handler.logger:
                handler.logger.debugf(fmt, *args)

        def _run(self, method: str):
            parsed = urlparse(self.path)
            body = b""
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                body = self.rfile.read(length)
            extra_headers = []
            try:
                result = handler.handle(
                    method,
                    parsed.path,
                    parse_qs(parsed.query),
                    body,
                    headers=dict(self.headers),
                )
                if isinstance(result, RawResponse):
                    payload = result.data
                    ctype = result.content_type
                else:
                    payload = json.dumps(result).encode()
                    ctype = "application/json"
                self.send_response(200)
            except Overloaded as e:
                # tenant-throttled (429: only THIS tenant must back
                # off) vs genuinely overloaded (503: class queue full /
                # draining — retry against another node); both carry
                # Retry-After so well-behaved clients come back instead
                # of hammering an overloaded server
                payload, ctype = self._error_payload(str(e))
                extra_headers.append(
                    ("Retry-After", str(max(1, round(e.retry_after))))
                )
                self.send_response(e.status)
            except GangUnavailable as e:
                # multihost gang dead (follower loss): bounded clean
                # failure — the runtime already degraded to the local
                # mesh, so a retry executes locally
                payload, ctype = self._error_payload(str(e))
                extra_headers.append(
                    ("Retry-After", str(max(1, round(e.retry_after))))
                )
                self.send_response(e.status)
            except DeadlineExceeded as e:
                # the request's deadline passed; work was cancelled at a
                # stage boundary — 504, like a gateway timeout
                payload, ctype = self._error_payload(str(e))
                self.send_response(504)
            except FragmentQuarantinedError as e:
                # corrupt fragment under repair: clean 503 + Retry-After
                # (never a wrong answer) — by the time a well-behaved
                # client retries, scrub has usually pulled a replica copy
                payload, ctype = self._error_payload(str(e))
                extra_headers.append(
                    ("Retry-After", str(max(1, round(e.retry_after))))
                )
                self.send_response(e.status)
            except APIError as e:
                payload, ctype = self._error_payload(str(e))
                self.send_response(e.status)
            except ExecNotFound as e:
                # the executor's typed missing-index/field/bsiGroup
                # error — the reference maps exactly those to 404
                # (successResponse.check, http/handler.go:285-310)
                payload, ctype = self._error_payload(str(e).strip("'\""))
                self.send_response(404)
            except KeyError as e:
                # any untyped KeyError is an internal bug (or a missing
                # request field that slipped past _require): a logged
                # 500, never an invisible not-found
                traceback.print_exc()
                payload, ctype = self._error_payload(
                    f"internal error: {str(e).strip(chr(39))}"
                )
                self.send_response(500)
            except ValueError as e:
                # bad user input (parse-adjacent arg errors, malformed
                # bodies) — 400, like the reference's BadRequest family.
                # A ValueError can also be an internal bug surfacing
                # through this catch; keep the trace reachable without
                # spamming logs on every client typo: debugf always,
                # full traceback when verbose.
                # format_exc is not free — only pay it when debugf
                # will actually emit (verbose logger)
                if handler.logger is not None and getattr(
                    handler.logger, "verbose", False
                ):
                    handler.logger.debugf(
                        "400 %s %s: %s\n%s",
                        method,
                        parsed.path,
                        e,
                        traceback.format_exc(),
                    )
                payload, ctype = self._error_payload(str(e))
                self.send_response(400)
            except Exception as e:  # panic recovery (reference ServeHTTP:239-276)
                traceback.print_exc()
                payload, ctype = self._error_payload(f"internal error: {e}")
                self.send_response(500)
            for name, value in extra_headers:
                self.send_header(name, value)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _error_payload(self, msg: str):
            # Only the query route speaks protobuf errors: clients
            # unmarshal a QueryResponse{Err} there (reference
            # http/error.go). Import/admin routes get plain text, like
            # the reference's http.Error calls (handlePostImport etc.)
            # — a proto ImportResponse has no error field to carry msg.
            # The check below matches exactly the /index/{index}/query
            # route shape — a FIELD named "query"
            # (/index/i/field/query) must not match.
            parts = [p for p in urlparse(self.path).path.split("/") if p]
            is_query = (
                len(parts) == 3 and parts[0] == "index" and parts[2] == "query"
            )
            wants_proto = publicproto.CONTENT_TYPE in (
                self.headers.get("Accept") or ""
            ) or publicproto.CONTENT_TYPE in (self.headers.get("Content-Type") or "")
            if is_query and wants_proto:
                return (
                    publicproto.encode_query_response([], err=msg),
                    publicproto.CONTENT_TYPE,
                )
            if wants_proto:
                return (msg + "\n").encode(), "text/plain; charset=utf-8"
            return json.dumps({"error": msg}).encode(), "application/json"

        def do_GET(self):
            self._run("GET")

        def do_POST(self):
            self._run("POST")

        def do_DELETE(self):
            self._run("DELETE")

    class _Srv(ThreadingHTTPServer):
        # socketserver's default listen backlog is 5: under a closed-loop
        # client fleet (each request a fresh connection) the SYN queue
        # overflows and the kernel RSTs connections before the pipeline
        # can even shed them politely. The pipeline is the admission
        # layer — the transport backlog just needs to be deep enough to
        # hand every arrival to it.
        request_queue_size = 128

    return _Srv((host, port), _Req)
