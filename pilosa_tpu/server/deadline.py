"""Request deadlines — the cancellation seam of the serving pipeline.

A deadline is set once at admission (from the ``X-Request-Deadline``
header, the ``timeout`` query parameter, or the server's configured
default) and propagates through ``API.query`` into ``Executor.execute``
and the per-shard map via a contextvar, the same pattern the span
tracer uses. Work is cancelled at STAGE BOUNDARIES — before the parse,
before the executor body, before each shard's map leg — rather than
preempted mid-kernel: an expired request stops consuming the worker
pool at the next check instead of computing a result nobody will read.

Like the tracer's span, the deadline does not follow work into thread
pools automatically; pool submitters capture ``current()`` and re-enter
it in the worker via ``activate(dl)``.

This module is deliberately self-contained (stdlib only): the executor
(L4) reaches up into it lazily, and a module-level import of anything
from the server package would recreate the server→executor import
cycle.
"""

from __future__ import annotations

import contextvars
import math
import time
from typing import Optional

_current: contextvars.ContextVar[Optional["Deadline"]] = contextvars.ContextVar(
    "pilosa_tpu_deadline", default=None
)


class DeadlineExceeded(Exception):
    """Raised at a stage boundary once the request's deadline passed.
    ``stage`` names where the work was cancelled (a trace-stage name);
    the HTTP layer maps this to 504."""

    def __init__(self, stage: str = "", message: str = "") -> None:
        self.stage = stage
        super().__init__(
            message or f"deadline exceeded at {stage or 'admission'}"
        )


class Deadline:
    """An absolute point on the monotonic clock. Immutable; cheap to
    check (one ``time.monotonic()`` compare per ``check``)."""

    __slots__ = ("at",)

    def __init__(self, at: float) -> None:
        self.at = at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def check(self, stage: str) -> None:
        """Raise DeadlineExceeded if the deadline has passed. The
        per-stage cancellation point: call at the top of each unit of
        work, never inside one."""
        if time.monotonic() >= self.at:
            from pilosa_tpu.utils import metrics

            metrics.count(metrics.PIPELINE_DEADLINE_EXPIRED, stage=stage)
            raise DeadlineExceeded(stage)


def current() -> Optional[Deadline]:
    """The active request deadline of this thread/context, or None."""
    return _current.get()


class _Activation:
    __slots__ = ("_dl", "_token")

    def __init__(self, dl: Optional[Deadline]) -> None:
        self._dl = dl
        self._token = None

    def __enter__(self) -> Optional[Deadline]:
        if self._dl is not None:
            self._token = _current.set(self._dl)
        return self._dl

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _current.reset(self._token)
        return False


def activate(dl: Optional[Deadline]) -> _Activation:
    """Context manager installing ``dl`` as the current deadline
    (no-op for None) — used by pipeline workers and pool submitters to
    carry the deadline across threads."""
    return _Activation(dl)


def from_request(
    headers: dict, query: dict, default_timeout: float = 0.0
) -> Optional[Deadline]:
    """Deadline for one HTTP request, or None when unbounded.

    Precedence: ``timeout`` query parameter (relative seconds) >
    ``X-Request-Deadline`` header (absolute unix-epoch seconds, the
    convention proxies forward unchanged across hops) > the server's
    ``pipeline-default-timeout``. Malformed values raise ValueError —
    the HTTP layer maps that to 400; silently ignoring a typo'd
    deadline would run the request unbounded."""
    tq = query.get("timeout")
    if tq:
        try:
            seconds = float(tq[0])
        except (TypeError, ValueError):
            raise ValueError(f"invalid timeout parameter: {tq[0]!r}")
        if not math.isfinite(seconds) or seconds <= 0:
            raise ValueError(f"timeout must be a positive number: {tq[0]!r}")
        return Deadline.after(seconds)
    hd = headers.get("x-request-deadline", "")
    if hd:
        try:
            epoch = float(hd)
        except (TypeError, ValueError):
            raise ValueError(f"invalid X-Request-Deadline header: {hd!r}")
        if not math.isfinite(epoch):
            raise ValueError(f"invalid X-Request-Deadline header: {hd!r}")
        # translate wall-clock to this process's monotonic clock once,
        # at admission; an already-past deadline still admits and then
        # cancels at the first stage boundary (one consistent path)
        return Deadline.after(epoch - time.time())
    if default_timeout > 0:
        return Deadline.after(default_timeout)
    return None
