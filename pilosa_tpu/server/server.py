"""Server runtime (L7) — wires holder/executor/API/HTTP + background
loops (reference server.go / server/server.go Command).

Single-node mode runs with cluster=None (the reference's
``cluster.disabled`` static mode); the cluster layer plugs in through
the same seams the reference uses: a broadcaster (send_sync/send_async),
a message receiver, and the executor's cluster hook.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from pilosa_tpu.core import Holder
from pilosa_tpu.executor import DeviceStager, Executor
from pilosa_tpu.executor.hbm import HbmGovernor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.config import Config
from pilosa_tpu.server.http_handler import Handler, make_http_server
from pilosa_tpu import __version__
from pilosa_tpu.utils.attrstore import new_attr_store
from pilosa_tpu.utils.diagnostics import DiagnosticsCollector
from pilosa_tpu.utils.logger import NOP_LOGGER, StandardLogger
from pilosa_tpu.utils import (
    events,
    heat,
    logger as logger_mod,
    metrics,
    profiler,
    slo,
    telemetry_export,
    trace,
)
from pilosa_tpu.utils.gcnotify import GCNotifier
from pilosa_tpu.utils.stats import (
    ExpvarStatsClient,
    MultiStatsClient,
    NOP_STATS,
    StatsDClient,
)
from pilosa_tpu.translate import Translator


def _host_resolves_to_local(host: str, bind_host: str) -> bool:
    """True when ``host`` DNS-resolves to the address this server is
    bound to. With a specific bind IP the check is exact; with a
    wildcard bind (0.0.0.0 / ::) the host must resolve to one of this
    machine's own addresses. Resolution failures are False — an
    unresolvable advertised name can't be proven to be us."""
    import socket

    host = host.strip("[]")
    try:
        remote = {ai[4][0] for ai in socket.getaddrinfo(host, None)}
    except OSError:
        return False
    bind_host = bind_host.strip("[]")
    if bind_host not in ("", "0.0.0.0", "::"):
        try:
            local = {ai[4][0] for ai in socket.getaddrinfo(bind_host, None)}
        except OSError:
            local = {bind_host}
        return bool(remote & local)
    # wildcard bind: gather this machine's interface addresses
    local = {"127.0.0.1", "::1"}
    try:
        local.update(
            ai[4][0] for ai in socket.getaddrinfo(socket.gethostname(), None)
        )
    except OSError:
        pass
    return bool(remote & local)


class Server:
    def __init__(self, config: Optional[Config] = None, cluster=None) -> None:
        # entry point for every serving deployment: make JAX_PLATFORMS
        # win over the image's sitecustomize backend pinning
        from pilosa_tpu.utils.jaxplatform import bootstrap

        bootstrap()
        self.config = config or Config()
        data_dir = os.path.expanduser(self.config.data_dir)
        self.logger = (
            StandardLogger(verbose=self.config.verbose)
            if self.config.log_path != "nop"
            else NOP_LOGGER
        )
        # reference server/server.go:353-364 (expvar/statsd/none selection;
        # unknown names error there too). An in-process ExpvarStatsClient
        # is ALWAYS kept so /debug/vars and /metrics have a snapshot:
        # with the statsd sink, stats fan out to both.
        self._expvar = ExpvarStatsClient()
        if self.config.metric == "expvar":
            self.stats = self._expvar
        elif self.config.metric == "statsd":
            self.stats = MultiStatsClient(
                self._expvar, StatsDClient(host=self.config.metric_host)
            )
        elif self.config.metric in ("none", "nop", ""):
            self.stats = NOP_STATS
        else:
            raise ValueError(f"invalid metric service: {self.config.metric!r}")
        # tracer knobs (process-global tracer: the last server configured
        # in-process wins — one server per process in any real deployment)
        tracer = trace.TRACER
        tracer.sample_rate = self.config.trace_sample_rate
        tracer.slow_threshold = self.config.slow_query_time
        if self.config.slow_query_time > 0:
            import json as _json

            logger = self.logger

            def _log_slow(tree: dict) -> None:
                logger.printf(
                    "%.3fs SLOW QUERY trace %s",
                    tree.get("duration_ms", 0.0) / 1000.0,
                    _json.dumps(tree),
                )

            tracer.on_slow = _log_slow
        else:
            tracer.on_slow = None
        # workload heat ledger knobs (process-global like the tracer)
        heat.LEDGER.configure(
            self.config.heat_enabled, self.config.heat_decay_halflife
        )
        # durable event journal backing: the ring becomes a
        # write-through cache over segments in journal-dir (default
        # <data-dir>/.events); 0 bytes keeps the ring-only journal
        if self.config.journal_max_bytes > 0:
            events.JOURNAL.open_backing(
                self.config.journal_dir or os.path.join(data_dir, ".events"),
                self.config.journal_max_bytes,
            )
        # telemetry export pipeline: with no sink configured this is
        # None and the journal/tracer taps stay unset — the disabled
        # hot path pays one is-not-None branch, no allocations
        self.exporter = telemetry_export.build_exporter(
            path=self.config.export_path,
            url=self.config.export_url,
            queue_max=self.config.export_queue,
            interval=self.config.export_interval,
            metrics_fn=metrics.snapshot,
        )
        if self.exporter is not None:
            events.JOURNAL.on_record = self.exporter.tap_event
            trace.TRACER.on_export = self.exporter.tap_span
        # only hook gc.callbacks when someone consumes the counter
        self.gc_notifier = GCNotifier() if self.stats is not NOP_STATS else None
        self.holder = Holder(
            data_dir,
            new_attr_store=new_attr_store,
            broadcaster=self._broadcast_create_shard,
        )
        # key translation (ISSUE 20, pilosa_tpu/translate/): partitioned
        # durable key↔id stores under <data>/translate; ownership,
        # forwarding and replication are wired in open() once the
        # listener (and so this node's own URI) is known
        self.translate_store = Translator(
            os.path.join(data_dir, "translate"),
            partitions=self.config.translate_partitions,
            cache_bytes=self.config.translate_cache_bytes,
        )
        self.cluster = cluster
        # multihost serving (parallel/multihost.py): bootstrap the
        # jax.distributed runtime BEFORE the mesh is built, so
        # jax.devices() below is the GLOBAL device set spanning every
        # process. Rank 0 is the serving leader; followers replay.
        self.multihost = None
        self._mh_rank, self._mh_world = 0, 1
        if self.config.distributed_enabled:
            from pilosa_tpu.parallel import multihost as multihost_mod

            self._mh_rank, self._mh_world = multihost_mod.initialize_distributed(
                self.config.distributed_coordinator,
                self.config.distributed_num_processes,
                self.config.distributed_process_id,
                use_gloo=self.config.distributed_gloo,
            )
            self.logger.printf(
                "multihost: rank %d/%d initialized",
                self._mh_rank,
                self._mh_world,
            )
        self.mesh = self._build_mesh()
        self.stager = DeviceStager(
            budget_bytes=self.config.stager_budget_bytes,
            mesh=self.mesh,
            delta_enabled=self.config.stager_delta_enabled,
            delta_max_ratio=self.config.stager_delta_max_ratio,
            tier1_max_bytes=self.config.tier1_max_bytes,
            compressed_min_ratio=self.config.compressed_upload_min_ratio,
        )
        # the delta log capacity rides on the fragment class (fragments
        # are created deep inside the holder tree; a process-wide
        # default is the right scope for a process-wide stager)
        from pilosa_tpu.core import fragment as fragment_mod

        fragment_mod.DELTA_LOG_MAX = self.config.stager_delta_log_max
        # bulk-import cliff threshold + storage fault injection are
        # process-wide for the same reason
        fragment_mod.DELTA_MAX_BATCH = self.config.ingest_delta_max_batch
        fragment_mod.install_storage_faults(self.config.storage_faults)
        # device fault injection (utils/chaos.py) is process-wide for
        # the same reason; the chaos endpoint re-installs at runtime
        from pilosa_tpu.utils import chaos as chaos_mod

        chaos_mod.install_device_faults(self.config.device_faults)
        # serving deployments get the device health gate: a wedged
        # accelerator (hung tunnel/PJRT call) degrades reads to the CPU
        # roaring path instead of hanging them, and a background probe
        # restores the device path when it answers again
        health = None
        if self.config.distributed_enabled:
            # gang determinism: the health guard runs calls through a
            # worker pool with per-call timeouts — a rank-0-only trip
            # or pool-timeout would change which collectives execute
            # and deadlock the mesh. The gang's own deadline fencing
            # (dispatch timeout → degrade-to-local-mesh) is the
            # recovery story in distributed mode.
            pass
        elif self.config.device_policy != "never" and self.config.device_timeout > 0:
            from pilosa_tpu.executor.devicehealth import DeviceHealth

            health = DeviceHealth(
                timeout_s=self.config.device_timeout, logger=self.logger
            )
        # plan result cache (plan/cache.py): generation-stamped cross-
        # request result cache; the executor consults it around call
        # dispatch and the planner substitutes cached subtrees
        self.plan_cache = None
        if self.config.plan_cache_enabled:
            from pilosa_tpu.plan.cache import PlanCache

            self.plan_cache = PlanCache(
                max_bytes=self.config.plan_cache_max_bytes,
                min_cost=self.config.plan_cache_min_cost,
            )
        self.executor = Executor(
            self.holder,
            cluster=cluster,
            stager=self.stager,
            device_policy=self.config.device_policy,
            translate_store=self.translate_store,
            max_writes_per_request=self.config.max_writes_per_request,
            mesh=self.mesh,
            health=health,
            auto_min_containers=(
                self.config.auto_device_min_containers
                if self.config.auto_device_min_containers > 0
                else None
            ),
            plan_cache=self.plan_cache,
            dispatch_enabled=self.config.dispatch_enabled,
            dispatch_max_wave=self.config.dispatch_max_wave,
            dispatch_max_inflight=self.config.dispatch_max_inflight,
            dispatch_stage_ahead=self.config.dispatch_stage_ahead,
            prefetch_enabled=self.config.prefetch_enabled,
            prefetch_depth=self.config.prefetch_depth,
            fusion_enabled=self.config.fusion_enabled,
            fusion_max_calls=self.config.fusion_max_calls,
            plan_cache_device_bytes=self.config.plan_cache_device_bytes,
            governor=HbmGovernor(budget_bytes=self.config.hbm_budget_bytes),
            analytics_max_groups=self.config.analytics_max_groups,
        )
        self.api = API(self.holder, self.executor, cluster=cluster, server=self)
        # federation (parallel/federation.py): epoch adopted from the
        # gang leader at rejoin; -1 = never joined, every epoch-stamped
        # apply is refused until the leader's state push lands
        self.gang_epoch = -1
        self._gang_apply_fn = None
        if self.config.distributed_enabled:
            from pilosa_tpu.parallel.multihost import (
                MultiHostRuntime,
                make_apply_fn,
            )

            self.multihost = MultiHostRuntime(
                rank=self._mh_rank,
                world=self._mh_world,
                apply_fn=make_apply_fn(self),
                frame_bytes=self.config.distributed_frame_bytes,
                idle_interval=self.config.distributed_idle_interval,
                dispatch_timeout=self.config.distributed_dispatch_timeout,
                leader_timeout=self.config.distributed_leader_timeout,
                on_degrade=self._degrade_to_local_mesh,
                logger=self.logger,
                faults=self.config.distributed_faults,
            )
            # the executor routes every non-remote query through the
            # gang on the leader; followers re-enter execute() from the
            # worker loop with the in-gang flag set
            self.executor.gang = self.multihost
        elif self.config.federation_leader:
            # restarted gang leader: the old collective plane died with
            # its peers (a poisoned gloo context cannot be rebuilt
            # in-process), so come back replicated-solo — DEGRADED until
            # a follower rejoins through /internal/gang/rejoin
            from pilosa_tpu.parallel.multihost import (
                MultiHostRuntime,
                make_apply_fn,
            )

            self.multihost = MultiHostRuntime.replicated(
                apply_fn=make_apply_fn(self),
                dispatch_timeout=self.config.distributed_dispatch_timeout,
                logger=self.logger,
            )
            self.executor.gang = self.multihost
        # fleet identity (ISSUE 10): stamp every trace root and journal
        # event with this process's gang/rank, and give log records the
        # live epoch. Standalone servers keep empty tags — span meta
        # stays exactly what the caller passed.
        ident: dict = {}
        if self.config.distributed_coordinator:
            ident["gang"] = self.config.distributed_coordinator
        if self.config.distributed_enabled:
            ident["rank"] = self._mh_rank
        if ident:
            trace.TRACER.tags = dict(ident)
            events.JOURNAL.tags = dict(ident)
        if self.multihost is not None:
            _mh = self.multihost

            logger_mod.set_context_provider(
                lambda: {
                    "gang": self.config.distributed_coordinator or "",
                    "rank": self._mh_rank,
                    "epoch": _mh.epoch,
                }
            )
        # fleet telemetry collector (server/fleet.py): every server owns
        # one; only a gang/federation leader accumulates members
        from pilosa_tpu.server.fleet import FleetCollector

        self.fleet = FleetCollector(self)
        # multi-tenant QoS (server/tenancy.py): per-index admission
        # buckets, weighted-fair scheduling, HBM quotas, per-tenant
        # SLOs. Disabled (zero-cost passthrough) when no tenant-* knob
        # is configured — the single-tenant default stays bit-identical
        from pilosa_tpu.server.tenancy import TenancyManager

        self.tenancy = TenancyManager(
            weights=self.config.tenant_weights,
            qps=self.config.tenant_qps,
            hbm_quota=self.config.tenant_hbm_quota,
            inflight_bytes=self.config.tenant_inflight_bytes,
            objectives=self.config.tenant_objectives,
        )
        if self.tenancy.enabled and (
            self.tenancy.hbm_quotas() or self.tenancy.default_hbm_quota
        ):
            self.executor.governor.set_index_quotas(
                self.tenancy.hbm_quotas(),
                default=self.tenancy.default_hbm_quota,
            )
        # serving pipeline (server/pipeline.py): every query/import
        # request flows through bounded per-class admission queues with
        # deadline scheduling, singleflight coalescing, and
        # cross-request batching into the executor's scorers
        self.pipeline = None
        if self.config.pipeline_enabled:
            from pilosa_tpu.server.pipeline import (
                QueryPipeline,
                make_query_combiner,
            )

            self.pipeline = QueryPipeline(
                workers={
                    "interactive": self.config.pipeline_interactive_workers,
                    "bulk": self.config.pipeline_bulk_workers,
                    "internal": self.config.pipeline_internal_workers,
                },
                queue_limits={
                    "interactive": self.config.pipeline_interactive_queue,
                    "bulk": self.config.pipeline_bulk_queue,
                    "internal": self.config.pipeline_internal_queue,
                },
                combine_fn=make_query_combiner(self.api),
                batch_max=self.config.pipeline_batch_max,
                batch_window=self.config.pipeline_batch_window,
                shed_retry_after=self.config.pipeline_shed_retry_after,
                drain_timeout=self.config.pipeline_drain_timeout,
                # with the dispatch engine on, cross-request combining
                # belongs to the engine (which also handles
                # heterogeneous plans); pipeline workers hand items off
                # one at a time instead of gang-batching them
                dispatch_handoff=(
                    self.executor.dispatch_engine is not None
                ),
                tenancy=self.tenancy,
            )
        # durable ingest queue (server/ingest.py): its own admission
        # class beside interactive/bulk — bounded write-ahead queue,
        # group-committed write waves, acks only after fsync
        self.ingest = None
        if self.config.ingest_enabled:
            from pilosa_tpu.server.ingest import IngestQueue

            self.ingest = IngestQueue(
                self.api,
                queue_limit=self.config.ingest_queue_limit,
                wave_max=self.config.ingest_wave_max,
                wave_interval=self.config.ingest_wave_interval,
                retry_after=self.config.ingest_retry_after,
            )
        self.handler = Handler(
            self.api,
            logger=self.logger,
            stats=self.stats,
            long_query_time=self.config.cluster.long_query_time,
            pipeline=self.pipeline,
            default_timeout=self.config.pipeline_default_timeout,
            analytics_timeout=self.config.analytics_timeout,
            ingest=self.ingest,
            tenancy=self.tenancy,
        )
        self.diagnostics = DiagnosticsCollector(
            host=getattr(self.config, "diagnostics_host", ""),
            version=__version__,
            logger=self.logger,
        )
        from pilosa_tpu.server.scrub import Scrubber

        self.scrubber = Scrubber(self)
        self.httpd = None
        self._serve_thread: Optional[threading.Thread] = None
        self.node_id: str = ""
        self._closed = threading.Event()
        # memoized translate-primary resolution (see translate_primary)
        # (value, monotonic-expiry-or-None); see translate_primary
        self._translate_primary_cache: Optional[tuple] = None
        # memoized key-space ownership: (index, field, partition) ->
        # (owner uri or "", monotonic expiry); see _translate_owner
        self._translate_owner_cache: dict = {}

    def _build_mesh(self):
        """Resolve config.mesh_devices into a jax Mesh over the shard
        axis (None = single-device execution). Accepts an int count or
        "all"; more devices requested than visible is an error — a
        silent clamp would hide a misconfigured slice."""
        if self.config.distributed_enabled:
            # distributed serving: one GLOBAL mesh over every process's
            # devices — the whole point; mesh_devices is ignored (a
            # partial global mesh would strand follower devices)
            import jax

            from pilosa_tpu.parallel.spmd import make_mesh

            devices = jax.devices()
            mesh = make_mesh(devices)
            self.logger.printf(
                "multihost SPMD mesh: %d global devices over %d processes",
                len(devices),
                self._mh_world,
            )
            return mesh
        want = self.config.mesh_devices
        if isinstance(want, str):
            want = want.strip().lower()
            if want in ("", "0", "none"):
                return None
            if want != "all":
                want = int(want)
        if want in (0, 1):
            return None
        if isinstance(want, int) and want < 0:
            raise ValueError(f"mesh_devices must be >= 0, got {want}")
        import jax

        from pilosa_tpu.parallel.spmd import make_mesh

        devices = jax.devices()
        if want == "all":
            want = len(devices)
        if want > len(devices):
            raise ValueError(
                f"mesh_devices={want} but only {len(devices)} devices visible"
            )
        mesh = make_mesh(devices[:want])
        self.logger.printf("SPMD mesh: %d devices over shard axis", want)
        return mesh

    def _degrade_to_local_mesh(self) -> None:
        """Multihost failure path: the gang is dead (follower loss),
        so the global mesh can never complete another collective. Hand
        the executor a mesh over THIS process's own devices (or none,
        single-device) and fresh staging — serving continues locally,
        reads stay correct (every rank holds the full replayed state),
        capacity shrinks to one host.

        On the CPU backend the local mesh is skipped entirely: CPU
        cross-device collectives ride the same gloo context the dead
        gang poisoned (observed: post-degrade local psum fails with
        'Gloo all-reduce failed: Connection reset by peer'), so the
        degraded executor runs the collective-free single-device
        batched path. Real TPU deployments keep a local ICI mesh."""
        import jax

        from pilosa_tpu.parallel.spmd import make_mesh

        local = jax.local_devices()
        mesh = (
            make_mesh(local)
            if len(local) > 1 and jax.default_backend() != "cpu"
            else None
        )
        stager = DeviceStager(
            budget_bytes=self.config.stager_budget_bytes,
            mesh=mesh,
            delta_enabled=self.config.stager_delta_enabled,
            delta_max_ratio=self.config.stager_delta_max_ratio,
            tier1_max_bytes=self.config.tier1_max_bytes,
            compressed_min_ratio=self.config.compressed_upload_min_ratio,
        )
        ex = self.executor
        if self.multihost is None or not self.multihost.federated:
            # PR 5 single-plane semantics: the gang is gone for good.
            # A FEDERATED runtime keeps the gang attached — it re-enters
            # service replicated-solo and reform() needs the hook chain.
            ex.gang = None
        with ex._spmd_mu:
            ex._spmd_kernels = {}
        ex.mesh = mesh
        ex.stager = stager
        # scorer queues may hold work aimed at dead global arrays, and
        # results computed on the dead gang epoch must not be served
        # (resets the new stager too — a no-op on a fresh instance)
        ex._on_device_restore()
        self.stager = stager
        self.mesh = mesh
        self.logger.printf(
            "multihost degraded: serving on local mesh (%d devices)",
            len(local),
        )

    def serve_follower(self) -> str:
        """Run the multihost follower worker loop on the calling thread
        until the leader's poison pill (clean shutdown) or leader loss
        (deadline-fenced abort). Returns the stop reason."""
        if self.multihost is None:
            raise RuntimeError("serve_follower requires distributed-enabled")
        return self.multihost.serve_follower()

    # -- lifecycle (reference Server.Open:312) --

    def open(self) -> None:
        tls = self.config.tls
        if bool(tls.certificate_path) != bool(tls.certificate_key_path):
            # half-configured TLS must not silently serve plaintext
            raise ValueError(
                "TLS misconfigured: both certificate-path and "
                "certificate-key-path are required"
            )
        self._set_file_limit()
        self.logger.printf(
            "pilosa_tpu %s starting, data=%s", __version__, self.holder.path
        )
        self.holder.open()
        self.node_id = self.holder.load_node_id()
        # HTTP up first: join/resize messages must be receivable before
        # the cluster attaches (reference SetupNetworking before Open).
        self.httpd = make_http_server(
            self.handler, self.config.host, self.config.port
        )
        if self.config.tls.enabled:
            # TLS on the listener (reference server/server.go:166-240:
            # getListener wraps with tls.NewListener from the config's
            # certificate paths)
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(
                os.path.expanduser(self.config.tls.certificate_path),
                os.path.expanduser(self.config.tls.certificate_key_path),
            )
            self.httpd.socket = ctx.wrap_socket(self.httpd.socket, server_side=True)
        # wire key-translation ownership + forwarding BEFORE serving: a
        # keyed write arriving in the startup window would otherwise
        # mint locally and permanently diverge the cluster id space
        self._wire_translate_plane()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._serve_thread.start()
        self.logger.printf(
            "pilosa_tpu server listening on %s://%s:%d", self.scheme, *self.address()
        )
        # build_info gauge: one constant-1 sample whose labels identify
        # this process in a fleet scrape (version, backend, gang, rank)
        import jax

        metrics.gauge(
            metrics.BUILD_INFO,
            1.0,
            version=__version__,
            jax=jax.__version__,
            backend=jax.default_backend(),
            pid=str(os.getpid()),
            gang=self.config.distributed_coordinator or "",
            rank=str(self._mh_rank),
            leader=str(self._mh_rank == 0).lower(),
        )
        # performance attribution plane (ISSUE 12): uptime/start-time
        # gauges for fleet restart detection, SLO objectives from
        # config, and the always-on samplers. All of it degrades to
        # no-ops when the knobs disable it — serving never depends on
        # the observers.
        self.started_at = time.time()
        metrics.gauge(metrics.PROCESS_START_TIME_SECONDS, round(self.started_at, 3))
        metrics.gauge(metrics.UPTIME_SECONDS, 0.0)
        slo.MONITOR.configure(
            objectives=slo.parse_objectives(self.config.slo_objectives),
            burn_threshold=self.config.slo_burn_threshold,
        )
        # per-tenant SLOs ride the same monitor as tenant:<index>
        # classes — one tick, one scrape (server/tenancy.py); tenants
        # covered only by the "*" default register lazily at first query
        if self.tenancy.enabled:
            slo.MONITOR.merge(self.tenancy.slo_objectives())
        profiler.TELEMETRY.watermark_pct = self.config.hbm_watermark_pct
        stager = self.stager

        def _stager_probe() -> tuple[int, int]:
            return stager._bytes, stager.budget_bytes

        profiler.TELEMETRY.stager_probe = _stager_probe
        profiler.TELEMETRY.start()
        if self.exporter is not None:
            self.exporter.start()
        if self.config.profiler_hz > 0:
            profiler.SAMPLER.hz = self.config.profiler_hz
            profiler.SAMPLER.start()
        if self.cluster is None and not self.config.cluster.disabled:
            if self.config.distributed_enabled and self._mh_rank != 0:
                # federation: the cluster plane runs on gang LEADERS
                # only — a follower's holder is a replica of its
                # leader's, reachable through the leader
                self.logger.printf(
                    "federation: rank %d leaves the cluster plane to its "
                    "gang leader",
                    self._mh_rank,
                )
            else:
                if self.config.distributed_enabled:
                    self.logger.printf(
                        "federation: gang leader joins the cluster plane "
                        "(sharded gang federation)"
                    )
                self.cluster = self._build_cluster()
        if self.cluster is not None:
            self.executor.cluster = self.cluster
            self.api.cluster = self.cluster
            self.cluster.attach_server(self)
            if self.multihost is not None:
                # compose the planes: gang-replaying local executor,
                # replication + epoch-fence + state-gossip hooks
                from pilosa_tpu.parallel import federation

                federation.wire(self)
        if self.config.federation_rejoin:
            # restarted follower: announce to the gang leader off-thread
            # (the leader's schema/fragment push needs OUR listener)
            from pilosa_tpu.parallel import federation

            federation.start_rejoin(self)
        if self.multihost is not None and self._mh_rank == 0:
            # leader-URI handshake: followers learn where to push replay
            # spans and register their scrape endpoints (gang-only — the
            # cluster plane's peer leaders announce their own)
            try:
                self._gang_message({"type": "leader-uri", "uri": self.uri})
            except Exception as e:
                self.logger.printf("leader-uri broadcast failed: %s", e)
        # measure the device-policy crossover for THIS deployment
        # (dispatch RTT / per-container CPU cost) unless the operator
        # pinned one via config or env — measured beats guessed
        # (AUTOTUNE.json; executor/autotune.py). Non-blocking: serving
        # starts on the default and adopts the measurement when it
        # lands; a wedged tunnel can't stall startup.
        if (
            self.config.device_policy == "auto"
            and self.config.auto_device_min_containers <= 0
            and not os.environ.get("PILOSA_AUTO_DEVICE_MIN_CONTAINERS")
            # gang determinism: a per-rank MEASURED crossover would make
            # ranks disagree on device-vs-CPU routing — one rank enters
            # a collective the other skips, and the mesh deadlocks. In
            # distributed mode the crossover must be config-pinned
            # (auto-device-min-containers) or the shared default.
            and self.multihost is None
        ):
            from pilosa_tpu.executor.autotune import autotune_executor

            autotune_executor(self.executor, logger=self.logger)
        # startup node-status sync runs SYNCHRONOUSLY before open()
        # returns (memberlist's join-time full state sync): a restarted
        # node must know its live peers' schema + maxShards the moment
        # it serves, or cross-shard counts collapse to local shards
        # until the periodic exchange. Peers that are still down are
        # skipped — their own boot-time push heals the other direction.
        if (
            self.cluster is not None
            and len(self.cluster.nodes) > 1
            and self.config.cluster.status_interval > 0
        ):
            try:
                self.cluster.push_node_status(sync=True)
                self.cluster.pull_node_status()
            except Exception as e:
                self.logger.printf("startup node-status sync error: %s", e)
        self._start_background_loops()

    def _normalize_host_uri(self, h: str) -> str:
        """host[:port] or URI → canonical URI string: missing scheme
        defaults to this server's, missing port to the reference's
        10101 (utils/uri.py; reference uri.go:82-264). Canonicalizing
        here kills the bind-vs-advertise bug class where equivalent
        spellings fail string comparison. An address the strict parser
        rejects (uppercase/underscore hostnames the reference's
        hostRegexp also rejects) falls back to the legacy
        scheme-prefix form with a warning — a weird-but-working
        config must not become a boot crash."""
        from pilosa_tpu.utils.uri import URI, URIError

        try:
            return URI.from_address(h, default_scheme=self.scheme).normalize()
        except URIError:
            self.logger.printf(
                "address %r does not parse as a URI (reference uri.go "
                "host rules); using it verbatim", h
            )
            return h if h.startswith("http") else f"{self.scheme}://{h}"

    def _is_self(self, uri_str: str) -> bool:
        """Does this address name this server's listener? Compares
        scheme/host/port through URI equivalence (localhost spellings,
        default ports), then — for a bind-vs-advertise hostname/IP
        mismatch — through DNS: same port and the advertised host
        resolves to this server's bound IP (or to any local interface
        when bound to a wildcard). DNS results are config-controlled,
        unlike a request's Host header, so this cannot be spoofed by
        a client."""
        from pilosa_tpu.utils.uri import URI, URIError, same_endpoint

        if same_endpoint(uri_str, self.uri, default_scheme=self.scheme):
            return True
        try:
            other = URI.from_address(uri_str, default_scheme=self.scheme)
        except URIError:
            return False
        host, port = self.address()
        if other.port != port:
            return False
        return _host_resolves_to_local(other.host, bind_host=host)

    def translate_primary(self) -> str:
        """URI of the cluster's ONE id-minting translate store — this
        node replicates from (and forwards new keys to) it unless it IS
        it. Resolution: explicit translate-primary-url > the coordinator
        (join mode) > the first static host. Config-only, so it resolves
        before the listener starts. Deterministic across nodes — every
        node agrees without extra config. Empty = self is primary (or
        no cluster).

        The answer is MEMOIZED after the listener is bound: resolution
        can consult DNS (``_is_self``), and re-resolving on every
        forwarded mint would put blocking getaddrinfo calls on the
        keyed-write hot path. The SELF answer ("") is final; a
        NON-empty answer is cached with a TTL, because it may be the
        product of a transient resolver failure at boot (containers) —
        pinning it forever would leave the true primary 409ing every
        keyed write until restart."""
        cached = self._translate_primary_cache
        if cached is not None:
            value, expires = cached
            if expires is None or time.monotonic() < expires:
                return value
        out = self._resolve_translate_primary()
        if self.httpd is not None:  # port known → answer is cacheable
            self._translate_primary_cache = (
                out,
                None if out == "" else time.monotonic() + 60.0,
            )
        return out

    def _resolve_translate_primary(self) -> str:
        explicit = self.config.translate_primary_url
        if explicit:
            p = self._normalize_host_uri(explicit)
            return "" if self._is_self(p) else p
        cc = self.config.cluster
        if cc.disabled:
            return ""
        if cc.hosts:
            p = self._normalize_host_uri(cc.hosts[0])
            return "" if self._is_self(p) else p
        if cc.coordinator:
            return ""
        if cc.coordinator_host:
            p = self._normalize_host_uri(cc.coordinator_host)
            # same self-detection as the other branches: a node whose
            # coordinator_host names ITSELF under an alternate spelling
            # must not forward-and-409 its own keyed writes
            return "" if self._is_self(p) else p
        return ""

    def _translate_owner(self, index: str, field: str, partition: int) -> str:
        """Owning node's URI for one key space ("" = this node owns it).
        Explicit ``translate-primary-url`` is the legacy override — one
        node owns everything; otherwise each column partition / row
        space lands on a cluster node by jump hash over the sorted
        member list, so every node computes the same owner with no
        coordinator. Memoized with a TTL: resolution consults DNS
        (``_is_self``), which must stay off the keyed-write hot path,
        but membership can change, so a cached answer may not outlive
        the TTL."""
        key = (index, field, partition)
        cached = self._translate_owner_cache.get(key)
        if cached is not None and time.monotonic() < cached[1]:
            return cached[0]
        explicit = self.config.translate_primary_url
        if explicit:
            p = self._normalize_host_uri(explicit)
            out = "" if self._is_self(p) else p
        else:
            cl = self.cluster
            if cl is None or len(cl.nodes) <= 1:
                out = ""
            else:
                from pilosa_tpu.parallel.hashing import fnv64a, jump_hash

                nodes = cl.nodes  # kept sorted by node id
                i = jump_hash(
                    fnv64a(f"{index}/{field}/{partition}".encode()), len(nodes)
                )
                uri = self._normalize_host_uri(nodes[i].uri)
                out = "" if self._is_self(uri) else uri
        if self.httpd is not None:  # port known → answer is cacheable
            self._translate_owner_cache[key] = (out, time.monotonic() + 60.0)
        return out

    def _wire_translate_plane(self) -> None:
        """Wire the translate subsystem's server seams: ownership
        (jump-hash partitioned, or the legacy single primary), minting
        forwards over InternalClient, and assignment push replication
        over the existing gang-descriptor + cluster message planes."""
        ts = self.translate_store
        from pilosa_tpu.parallel.client import InternalClient

        client = InternalClient(ssl_context=self.client_ssl_context())
        ts.owner_resolver = self._translate_owner

        def forward_to(uri, index, field, keys):
            return client.translate_keys(uri, index, field, keys)

        def on_assign(index, field, keys, ids):
            # locally-minted assignments ride the same broadcast plane
            # as schema ops (gang descriptors + cluster messages); the
            # per-store pull loop below is the catch-up backstop
            self.send_async(
                {
                    "type": "translate-keys",
                    "index": index,
                    "field": field,
                    "keys": list(keys),
                    "ids": [int(i) for i in ids],
                }
            )

        ts.forward_to = forward_to
        ts.on_assign = on_assign

    def _set_file_limit(self) -> None:
        """Raise RLIMIT_NOFILE toward the reference's 262,144 target
        (holder.setFileLimit, holder.go:40,470) — one mmapped file per
        fragment adds up. Best-effort: capped at the hard limit."""
        try:
            import resource

            target = 262_144
            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            want = min(target, hard) if hard != resource.RLIM_INFINITY else target
            if soft < want:
                resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
                self.logger.printf("raised open-file limit to %d", want)
        except (ImportError, ValueError, OSError) as e:
            self.logger.printf("could not raise file limit: %s", e)

    def _start_background_loops(self) -> None:
        """reference server.go: monitorAntiEntropy:400, monitorRuntime:683,
        monitorDiagnostics:633."""

        def cache_flush_loop():
            # reference monitorCacheFlush (holder.go:425): persist every
            # OPENED fragment's TopN cache periodically so a crash loses
            # at most one interval of ranking state. Never-touched lazy
            # fragments have nothing new to flush.
            interval = self.config.cache_flush_interval
            if interval <= 0:
                return
            while not self._closed.wait(interval):
                try:
                    for idx in list(self.holder.indexes.values()):
                        for fld in list(idx.fields.values()):
                            for view in list(fld.views.values()):
                                for frag in list(view.fragments.values()):
                                    if frag._open:
                                        frag.flush_cache()
                except Exception as e:
                    self.logger.printf("cache flush error: %s", e)

        def anti_entropy_loop():
            interval = self.config.anti_entropy_interval
            if interval <= 0:
                return
            while not self._closed.wait(interval):
                try:
                    if self.cluster is not None:
                        t0 = time.monotonic()
                        self.cluster.sync_holder()
                        self.stats.histogram(
                            metrics.ANTI_ENTROPY_SECONDS, time.monotonic() - t0
                        )
                except Exception as e:
                    # a silently dead syncer is an availability bug:
                    # count + journal so the failure is fleet-visible
                    self.stats.count(metrics.ANTI_ENTROPY_ERRORS)
                    events.record(events.ANTI_ENTROPY_ERROR, error=str(e))
                    self.logger.printf("anti-entropy sync error: %s", e)

        def scrub_loop():
            # background data-integrity sweep (server/scrub.py) — sleep
            # first so boot-time opens (which verify digests themselves)
            # aren't doubled, then sweep on the interval
            interval = self.scrubber.interval
            if interval <= 0:
                return
            while not self._closed.wait(interval):
                try:
                    self.scrubber.sweep()
                except Exception as e:
                    self.logger.printf("scrub sweep error: %s", e)

        def runtime_monitor_loop():
            import gc

            while not self._closed.wait(10.0):
                try:
                    import resource

                    usage = resource.getrusage(resource.RUSAGE_SELF)
                    self.stats.gauge(metrics.MAX_RSS_KB, usage.ru_maxrss)
                    self.stats.gauge(metrics.THREADS, threading.active_count())
                    counts = gc.get_count()
                    self.stats.gauge(metrics.GC_GEN0, counts[0])
                    cycles = (
                        self.gc_notifier.poll() if self.gc_notifier else 0
                    )
                    if cycles:
                        # reference server.go:702-704 via gcnotify
                        self.stats.count(metrics.GARBAGE_COLLECTION, cycles)
                    self.stats.gauge(metrics.OPEN_FRAGMENTS, self._count_fragments())
                except Exception:
                    pass

        def diagnostics_loop():
            if self.diagnostics.host == "":
                return
            while not self._closed.wait(3600.0):
                self.diagnostics.enrich_with_os_info()
                self.diagnostics.enrich_with_schema(self.holder)
                self.diagnostics.flush()

        def translate_replication_loop():
            # pull catch-up for key assignments: every peer's stores
            # are polled from a per-(peer, store) byte offset and raw
            # CRC frames are applied locally (by-key idempotent). This
            # is the backstop under the broadcast push (translate-keys
            # messages) — a node that missed a broadcast converges
            # here. Offsets are in-memory only: logs are append-only,
            # so a restart just re-pulls from 0 and applies no-ops.
            from pilosa_tpu.parallel.client import ClientError, InternalClient

            client = InternalClient(ssl_context=self.client_ssl_context())
            ts = self.translate_store
            offsets: dict = {}
            self_uris: dict = {}
            while not self._closed.wait(1.0):
                uris = []
                if self.cluster is not None and len(self.cluster.nodes) > 1:
                    for n in self.cluster.nodes:
                        u = n.uri
                        if u not in self_uris:
                            self_uris[u] = self._is_self(
                                self._normalize_host_uri(u)
                            )
                        if not self_uris[u]:
                            uris.append(u)
                else:
                    legacy = self.translate_primary()
                    if legacy:
                        uris.append(legacy)
                for uri in uris:
                    try:
                        for entry in client.translate_stores(uri):
                            name = entry.get("name", "")
                            off = offsets.get((uri, name), 0)
                            if int(entry.get("offset", 0)) <= off:
                                continue
                            data = client.translate_data(uri, off, store=name)
                            if data:
                                offsets[(uri, name)] = off + ts.apply_frames(
                                    data
                                )
                    except (ClientError, ValueError):
                        pass

        def liveness_loop():
            # reference memberlist probing (gossip/gossip.go:431-494):
            # mark unresponsive peers SUSPECT → DOWN so query planning
            # fails over before paying a timeout
            interval = self.config.cluster.probe_interval
            if interval <= 0:
                return
            while not self._closed.wait(interval):
                try:
                    if self.cluster is not None and len(self.cluster.nodes) > 1:
                        self.cluster.probe_nodes()
                except Exception as e:
                    self.logger.printf("liveness probe error: %s", e)

        def slo_tick_loop():
            # evaluate burn-rate windows even when nobody scrapes: the
            # journal event (events.SLO_BURN) must fire on wall-clock,
            # not on observer traffic. Also refreshes the uptime gauge
            # so a scrape between ticks is at most 5s stale.
            while not self._closed.wait(5.0):
                try:
                    metrics.gauge(
                        metrics.UPTIME_SECONDS,
                        round(time.time() - self.started_at, 3),
                    )
                    slo.MONITOR.tick()
                except Exception as e:
                    self.logger.printf("slo tick error: %s", e)

        def node_status_loop():
            # reference periodic NodeStatus push/pull (server.go:565-630)
            interval = self.config.cluster.status_interval
            if interval <= 0:
                return
            # (the join-time full state sync runs synchronously in
            # open() — see there; this loop is only the periodic drift
            # healer, reference server.go:565-630)
            while not self._closed.wait(interval):
                try:
                    if self.cluster is not None and len(self.cluster.nodes) > 1:
                        self.cluster.push_node_status()
                except Exception as e:
                    self.logger.printf("node-status push error: %s", e)

        for fn in (
            cache_flush_loop,
            anti_entropy_loop,
            scrub_loop,
            runtime_monitor_loop,
            diagnostics_loop,
            translate_replication_loop,
            liveness_loop,
            slo_tick_loop,
            node_status_loop,
        ):
            threading.Thread(target=fn, daemon=True).start()

    def _count_fragments(self) -> int:
        n = 0
        for idx in self.holder.indexes.values():
            for f in idx.fields.values():
                for v in f.views.values():
                    n += len(v.fragments)
        return n

    def _build_cluster(self):
        from pilosa_tpu.parallel.cluster import Cluster
        from pilosa_tpu.parallel.node import Node

        cc = self.config.cluster
        data_dir = os.path.expanduser(self.config.data_dir)
        topology_path = os.path.join(data_dir, ".topology")
        ssl_ctx = self.client_ssl_context()
        scheme = self.scheme
        if cc.hosts:
            # Static topology: node identity = URI so every node derives
            # the identical ring (the reference's cluster-disabled mode
            # generalised to N fixed hosts).
            cluster = Cluster(
                node_id=self.uri,
                uri=self.uri,
                replica_n=cc.replicas,
                static=True,
                coordinator=cc.coordinator,
                topology_path=topology_path,
                logger=self.logger,
                probe_timeout=cc.probe_timeout,
                down_after=cc.down_after,
                ssl_context=ssl_ctx,
            )
            cluster.set_nodes(
                [Node(id=self._normalize_host_uri(h), uri=self._normalize_host_uri(h))
                 for h in cc.hosts]
            )
            return cluster
        return Cluster(
            node_id=self.node_id,
            uri=self.uri,
            replica_n=cc.replicas,
            static=False,
            coordinator=cc.coordinator,
            coordinator_uri=(
                self._normalize_host_uri(cc.coordinator_host)
                if cc.coordinator_host
                else None
            ),
            topology_path=topology_path,
            logger=self.logger,
            probe_timeout=cc.probe_timeout,
            down_after=cc.down_after,
            ssl_context=ssl_ctx,
        )

    def address(self) -> tuple[str, int]:
        if self.httpd is None:
            return (self.config.host, self.config.port)
        return self.httpd.server_address[:2]

    @property
    def scheme(self) -> str:
        return "https" if self.config.tls.enabled else "http"

    @property
    def uri(self) -> str:
        host, port = self.address()
        return f"{self.scheme}://{host}:{port}"

    def client_ssl_context(self):
        """SSL context for node-to-node clients; honors skip-verify
        (reference http/client.go transport from TLS config)."""
        if not self.config.tls.enabled:
            return None
        import ssl

        ctx = ssl.create_default_context()
        if self.config.tls.skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def close(self) -> None:
        self._closed.set()
        # drain the ingest queue to durability first: every queued wave
        # group-commits and its submitters ack before we take down the
        # layers a wave needs (new submits answer 503)
        if self.ingest is not None:
            self.ingest.close()
        # graceful drain FIRST: stop admitting (new requests get 503),
        # complete queued + in-flight work within the drain budget, so
        # a restart loses nothing the server had accepted and could
        # still finish
        if self.pipeline is not None:
            clean = self.pipeline.close()
            if not clean:
                self.logger.printf(
                    "pipeline drain timed out after %.1fs; remaining work failed 503",
                    self.config.pipeline_drain_timeout,
                )
        # after the pipeline drained (no new gang work), poison the
        # follower loops so every rank exits cleanly
        if self.multihost is not None:
            self.multihost.close()
        if self.gc_notifier is not None:
            self.gc_notifier.close()
        # observer planes stop after the workers they observe
        profiler.SAMPLER.stop()
        profiler.TELEMETRY.stop()
        if self.exporter is not None:
            # detach the taps before the final flush so late producers
            # can't race a closed queue, then flush-on-close (compare
            # the bound method's receiver: ``x.m is x.m`` is False)
            if getattr(events.JOURNAL.on_record, "__self__", None) is self.exporter:
                events.JOURNAL.on_record = None
            if getattr(trace.TRACER.on_export, "__self__", None) is self.exporter:
                trace.TRACER.on_export = None
            self.exporter.close()
        events.JOURNAL.close_backing()
        self.stats.close()
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
        if self.cluster is not None:
            self.cluster.close()
        self.executor.close()
        self.holder.close()
        self.translate_store.close()

    # -- broadcaster seam (reference broadcast.go:27-31) --

    def _broadcast_create_shard(self, index: str, shard: int) -> None:
        """New max shard appeared locally → tell the cluster (reference
        view.go:216-247 CreateShardMessage)."""
        self.send_async({"type": "create-shard", "index": index, "shard": shard})

    def _gang_message(self, msg: dict) -> bool:
        """Replicate a broadcast message to the multihost gang: schema
        ops and shard announcements must reach follower holders the
        same way cluster peers get them. No-op inside a gang replay
        (followers apply the op themselves) and after degrade. Returns
        True when the message WAS gang-dispatched — the replay applies
        it locally, so the caller must not apply it again."""
        mh = self.multihost
        if mh is not None and mh.should_dispatch():
            from pilosa_tpu.parallel.multihost import Descriptor, KIND_MESSAGE

            mh.dispatch(Descriptor(KIND_MESSAGE, msg))
            return True
        return False

    def send_sync(self, msg: dict) -> None:
        self._gang_message(msg)
        if self.cluster is not None:
            self.cluster.send_sync(msg)

    def send_async(self, msg: dict) -> None:
        self._gang_message(msg)
        if self.cluster is not None:
            self.cluster.send_async(msg)

    def send_to(self, node, msg: dict) -> None:
        if self.cluster is not None:
            self.cluster.send_to(node, msg)

    # -- federation (parallel/federation.py) --

    def gang_apply(self, kind: int, payload: dict, epoch: int) -> None:
        """Replicated-mode follower: apply one descriptor pushed by the
        gang leader. The epoch is the staleness fence — a LOWER epoch
        is a pre-re-form descriptor (a stale leader thread, a delayed
        frame) and must never land on post-re-form state (409, the
        sender rejoins). A HIGHER epoch is adopted: the leader only
        replicates to followers it just re-staged, and the bump may
        race the rejoin response that carries it."""
        from pilosa_tpu.server.api import APIError

        if epoch < self.gang_epoch:
            raise APIError(
                f"gang epoch mismatch: have {self.gang_epoch}, got {epoch} "
                "— stale descriptor refused, sender must re-form",
                status=409,
            )
        if epoch > self.gang_epoch:
            self.logger.printf(
                "gang epoch %d -> %d (leader re-formed)", self.gang_epoch, epoch
            )
            self.gang_epoch = epoch
        if self._gang_apply_fn is None:
            from pilosa_tpu.parallel.multihost import make_apply_fn

            self._gang_apply_fn = make_apply_fn(self)
        self._gang_apply_fn(kind, payload)

    def gang_rejoin(self, follower_uri: str) -> dict:
        """Gang leader: re-form around a re-staged follower (anti-
        entropy catch-up, schema + fragment push, epoch bump, ACTIVE)."""
        from pilosa_tpu.parallel import federation

        return federation.handle_rejoin(self, follower_uri)

    # -- message application (reference Server.ReceiveMessage:435-517) --

    def receive_message(self, msg: dict) -> None:
        # a message arriving from a cluster PEER replays through the
        # gang first, so this gang's followers see the same schema ops
        # its leader does; the replay re-enters here with the in-gang
        # flag set and falls through to the local apply below
        if self._gang_message(msg):
            return
        typ = msg.get("type")
        if typ == "create-index":
            self.holder.create_index_if_not_exists(
                msg["index"], msg.get("keys", False)
            )
        elif typ == "delete-index":
            try:
                self.holder.delete_index(msg["index"])
            except ValueError:
                pass
        elif typ == "create-field":
            from pilosa_tpu.core.field import FieldOptions

            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.create_field_if_not_exists(
                    msg["field"], FieldOptions.from_dict(msg.get("options", {}))
                )
        elif typ == "delete-field":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                try:
                    idx.delete_field(msg["field"])
                except ValueError:
                    pass
        elif typ == "create-shard":
            idx = self.holder.index(msg["index"])
            if idx is not None:
                idx.set_remote_max_shard(msg["shard"])
        elif typ == "recalculate-caches":
            for idx in self.holder.indexes.values():
                for f in idx.fields.values():
                    for v in f.views.values():
                        for frag in v.fragments.values():
                            frag.cache.recalculate()
        elif typ == "schema":
            self.holder.apply_schema(msg.get("schema", []))
        elif typ == "translate-keys":
            # push replication of key→id assignments minted elsewhere:
            # adopt durably (by-key idempotent; never re-broadcast)
            try:
                self.translate_store.adopt(
                    msg["index"],
                    msg.get("field", ""),
                    msg.get("keys", []),
                    msg.get("ids", []),
                )
            except (ValueError, KeyError, IndexError) as e:
                self.logger.printf("translate-keys apply error: %s", e)
        elif typ == "leader-uri":
            # gang replay of the leader's boot-time handshake: followers
            # adopt the push target and register with the leader's fleet
            # collector; the leader (and peer leaders) ignore it
            if self.multihost is not None and self._mh_rank != 0:
                self.multihost.leader_uri = msg.get("uri", "")
                self._register_with_leader()
        elif self.cluster is not None:
            self.cluster.receive_message(msg)

    def _register_with_leader(self) -> None:
        """Best-effort, off-thread: the gang apply loop must not block
        on an HTTP round-trip back to the leader."""
        mh = self.multihost
        if mh is None or not mh.leader_uri:
            return
        target = mh.leader_uri

        def _go():
            try:
                from pilosa_tpu.parallel.client import InternalClient

                InternalClient(
                    timeout=5.0, ssl_context=self.client_ssl_context()
                ).fleet_register(
                    target,
                    self.uri,
                    rank=self._mh_rank,
                    gang=self.config.distributed_coordinator or "",
                )
            except Exception as e:
                self.logger.printf("fleet register with %s failed: %s", target, e)

        threading.Thread(target=_go, name="fleet-register", daemon=True).start()
