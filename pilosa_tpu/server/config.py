"""Server configuration (reference server/config.go).

Three-tier precedence (CLI flags > env PILOSA_TPU_* > TOML file) is
implemented in the CLI layer; this module is the canonical option set.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib


@dataclass
class ClusterConfig:
    disabled: bool = True  # single-node static cluster by default
    coordinator: bool = False
    # coordinator address a joining node announces to (the analog of the
    # reference's gossip seed)
    coordinator_host: str = ""
    replicas: int = 1
    hosts: list[str] = field(default_factory=list)
    long_query_time: float = 0.0
    # liveness probing (reference gossip probe/suspicion tunables,
    # gossip/gossip.go:431-494); 0 disables the probe loop
    probe_interval: float = 2.0
    probe_timeout: float = 2.0
    down_after: int = 3  # consecutive probe failures → DOWN
    # periodic NodeStatus (schema + maxShards) exchange (reference
    # server.go:565-630); 0 disables
    status_interval: float = 60.0


@dataclass
class TLSConfig:
    """reference server/config.go:42-143 TLS block + server.go:166-240."""

    certificate_path: str = ""
    certificate_key_path: str = ""
    skip_verify: bool = False  # clients skip peer verification

    @property
    def enabled(self) -> bool:
        return bool(self.certificate_path and self.certificate_key_path)


@dataclass
class Config:
    data_dir: str = "~/.pilosa_tpu"
    bind: str = "localhost:10101"
    max_writes_per_request: int = 5000
    log_path: str = ""
    verbose: bool = False
    # TPU execution
    device_policy: str = "auto"  # never | auto | always
    stager_budget_bytes: int = 8 << 30
    # incremental delta staging (snapshot + delta model): on a fragment
    # generation bump the stager patches resident HBM blocks with
    # scatter-update kernels instead of rebuilding + re-uploading them
    stager_delta_enabled: bool = True
    # full-rebuild crossover: a delta batch touching more than this
    # fraction of a staged block's words re-stages instead (the scatter
    # stops winning once it rewrites much of the block)
    stager_delta_max_ratio: float = 0.25
    # per-fragment delta log capacity (single-bit mutations kept since
    # the oldest replayable snapshot); staged entries older than the
    # truncation floor full-rebuild on next use
    stager_delta_log_max: int = 4096
    # device health gate: reads slower than this fall back to the CPU
    # roaring path and gate the device off until a probe answers
    # (executor/devicehealth.py); 0 disables the gate. The default
    # clears a cold first-query compile (~40 s) with margin.
    device_timeout: float = 120.0
    # auto-policy crossover, in estimated touched containers (see
    # AUTOTUNE.json): default assumes a co-located chip; raise to
    # ~3700 behind a high-RTT tunnel. 0 = keep the executor default.
    auto_device_min_containers: int = 0
    # SPMD: number of local devices to mesh the shard axis over.
    # 0/1 = single-device; >1 builds a jax.sharding.Mesh and the
    # executor lowers multi-shard Count/Sum/TopN through ICI
    # collectives (parallel/spmd.py); "all" = every visible device
    mesh_devices: int | str = 0
    # multihost serving (parallel/multihost.py): jax.distributed
    # bootstrap + gang-dispatched SPMD execution over ONE global mesh
    # spanning processes. Rank 0 serves HTTP; follower ranks run the
    # gang worker loop and replay every state-bearing operation.
    distributed_enabled: bool = False
    # jax.distributed coordinator "host:port"; every rank must name the
    # same address (rank 0 hosts the coordination service)
    distributed_coordinator: str = ""
    # this rank's process id (0 = leader) and the total process count;
    # -1/0 fall back to the PILOSA_TPU_MH_* env the launcher sets
    distributed_process_id: int = -1
    distributed_num_processes: int = 0
    # select the gloo CPU collective implementation (required for
    # cross-process collectives on the CPU backend; irrelevant — and
    # skipped if the knob doesn't exist — on real multi-host TPU)
    distributed_gloo: bool = True
    # gang control-channel frame size in bytes (one broadcast per frame;
    # large imports span multiple frames)
    distributed_frame_bytes: int = 65536
    # leader idle-tick interval (seconds): keeps follower loops fed and
    # measures broadcast latency while the gang is idle; 0 disables
    distributed_idle_interval: float = 2.0
    # gang-death verdict: a dispatch (or idle tick) not completing
    # within this many seconds degrades the runtime to the local mesh
    # and fails the request 503
    distributed_dispatch_timeout: float = 30.0
    # follower-side bound on leader silence before the worker loop
    # aborts cleanly instead of waiting forever
    distributed_leader_timeout: float = 120.0
    # fault injection on the gang control channel (tests/dryruns only):
    # "drop_every=N,dup_every=N,delay=S,after=N" — see
    # multihost.FaultSpec; "" disables
    distributed_faults: str = ""
    # federation (parallel/federation.py): composing the gang plane
    # with the cluster plane. A federated deployment sets cluster.hosts
    # to the gang LEADER URIs; each leader is one cluster node owning
    # its gang's shard range.
    # rejoin target: a restarted follower boots non-distributed with
    # this set to its gang leader's URI, re-stages holder state from
    # the leader, and announces itself for re-formation; "" disables
    federation_rejoin: str = ""
    # restarted gang LEADER: boot non-distributed but keep the gang
    # plane alive in replicated-solo mode (DEGRADED until a follower
    # rejoins) so the node re-enters the federation without a working
    # collective plane — the dead peers poisoned the old one
    federation_leader: bool = False
    # upper bound (seconds) for one re-formation pass (fragment
    # re-sync + epoch bump + ACTIVE); used by operators/harnesses as
    # the recovery budget and by the rejoin boot path as its sync
    # deadline
    federation_reform_budget: float = 30.0
    # cross-gang RPC retry policy (parallel/client.py): transient
    # transport failures / 503s retry with capped exponential backoff
    # + jitter, bounded by the request deadline
    client_retries: int = 2
    client_retry_backoff: float = 0.05
    # cluster
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    # TLS on the listener + internal client (reference server.go:166-240)
    tls: TLSConfig = field(default_factory=TLSConfig)
    anti_entropy_interval: float = 600.0  # reference server.go:238 (10m)
    cache_flush_interval: float = 60.0  # reference holder.go:37 (1m)
    metric: str = "expvar"  # expvar | statsd | none
    metric_host: str = "127.0.0.1:8125"  # statsd UDP address
    # observability (utils/trace.py): fraction of queries traced into
    # the /debug/traces ring buffer (0 = off; profile=true always traces)
    trace_sample_rate: float = 0.0
    # seconds; > 0 traces EVERY query and logs the full span tree of any
    # query over the threshold (0 = off). Complementary to
    # cluster.long-query-time, which logs only the query text.
    slow_query_time: float = 0.0
    # serving pipeline (server/pipeline.py): the admission/scheduling
    # layer between HTTP and the executor. Per-class bounded queues +
    # dedicated worker pools; a full queue sheds 429 + Retry-After.
    pipeline_enabled: bool = True
    pipeline_interactive_workers: int = 8
    pipeline_bulk_workers: int = 2
    pipeline_internal_workers: int = 8
    pipeline_interactive_queue: int = 64
    pipeline_bulk_queue: int = 16
    pipeline_internal_queue: int = 128
    # cross-request batching: max homogeneous queued queries combined
    # into one executor call (1 disables), and an OPTIONAL artificial
    # wait (seconds) for peers — 0 (default) batches purely from
    # backlog, so an uncontended query pays no added latency
    pipeline_batch_max: int = 16
    pipeline_batch_window: float = 0.0
    # default per-request deadline in seconds when the client sends
    # neither a `timeout` param nor an X-Request-Deadline header
    # (0 = unbounded)
    pipeline_default_timeout: float = 0.0
    # Retry-After seconds on a 429 shed
    pipeline_shed_retry_after: float = 1.0
    # graceful-drain budget at shutdown: queued + in-flight work gets
    # this long to complete before being failed 503
    pipeline_drain_timeout: float = 10.0
    # durable streaming ingest (server/ingest.py): bounded write-ahead
    # queue coalescing mutations into group-committed write waves (one
    # fsync + one generation bump + one gang frame per wave). Acked
    # writes survive SIGKILL; queue overflow sheds 429 + Retry-After.
    ingest_enabled: bool = True
    # max pending mutations (bits, not requests) before submits shed
    ingest_queue_limit: int = 8192
    # max mutations coalesced into one write wave
    ingest_wave_max: int = 2048
    # coalesce window (seconds) the committer waits before sealing a
    # wave — bounds write-visibility staleness alongside commit latency
    ingest_wave_interval: float = 0.002
    # Retry-After seconds on an ingest queue-full 429
    ingest_retry_after: float = 0.25
    # bulk-import cliff threshold: import_block_pairs / bulk_import
    # batches at or under this many bits apply through the batched
    # delta path (one generation bump, delta log extended) instead of
    # resetting the delta log and forcing a full re-stage
    ingest_delta_max_batch: int = 512
    # storage fault injection (tests/dryruns only, core/fragment.py):
    # "fsync_fail_every=N,torn_at=N,enospc_after=N,corrupt_at=K,
    # bitrot=N,snapshot_kill=pre|post" — see fragment.StorageFaultSpec;
    # "" disables
    storage_faults: str = ""
    # background integrity scrubber (server/scrub.py): a low-priority
    # loop re-verifying owned fragments at rest — snapshot digest,
    # op-log CRC walk, and (scrub-deep) in-memory blocks vs an on-disk
    # re-read. Corrupt fragments quarantine (reads 503) and repair from
    # a healthy replica. 0 disables the loop; /debug/scrub still works.
    scrub_interval: float = 300.0
    # sleep between fragments within a sweep — bounds the scrubber's
    # IO/CPU share so it never competes with serving
    scrub_throttle: float = 0.05
    # include the expensive deep check (full file re-read + block
    # checksum compare against live memory) in every sweep
    scrub_deep: bool = True
    # repair quarantined fragments automatically from a healthy replica
    # (federated/replicated clusters); off leaves them quarantined for
    # operator action
    scrub_repair: bool = True
    # continuous-batching dispatch engine (executor/dispatch.py): the
    # async executor↔device boundary. Callers submit futures; a
    # persistent loop admits queued queries into in-flight waves grouped
    # by canonical plan signature, so heterogeneous plans coexist in one
    # wave and wave N+1 stages while wave N executes.
    dispatch_enabled: bool = True
    # max queries admitted into one wave
    dispatch_max_wave: int = 16
    # concurrent waves in flight (double/triple buffering depth)
    dispatch_max_inflight: int = 2
    # how many waves ahead the stager prefetches operand rows (0 = off)
    dispatch_stage_ahead: int = 1
    # tiered block staging (executor/tiering.py): host-RAM byte budget
    # for T1, the compressed roaring-container tier between device LRU
    # (T0) and the mmapped fragment (T2). A T0 miss that hits T1 skips
    # the fragment walk; admission is cost-modeled (heat × rebuild cost
    # per byte). 0 disables the tier.
    tier1_max_bytes: int = 256 << 20
    # plan-driven speculative prefetch: the dispatch engine hands queued
    # waves' plans to a scheduler that promotes their Row blocks
    # T1/T2 → T0 ahead of compute, with used-vs-evicted accuracy
    # accounting (replaces the thunk-based advisory warm)
    prefetch_enabled: bool = True
    # how many waves ahead the prefetcher looks in the dispatch queue
    prefetch_depth: int = 2
    # compressed-upload crossover: when a block's dense bytes are at
    # least this multiple of its container payload bytes, the payloads
    # cross the wire and a device kernel expands them to packed words
    # (ops.expand_blocks); 0 always uploads dense
    compressed_upload_min_ratio: float = 4.0
    # plan result cache (plan/cache.py): generation-stamped cross-request
    # result cache between parsing and execution. Entries are keyed by
    # canonical plan hash + shard set and validated against fragment
    # generations, so every write path invalidates exactly — no TTLs.
    plan_cache_enabled: bool = True
    # LRU byte budget for cached results (per-shard row segments +
    # scalars); 0 effectively disables storage
    plan_cache_max_bytes: int = 256 << 20
    # minimum build cost (seconds) for a result to be stored: filters
    # out sub-threshold queries whose recompute is cheaper than the
    # cache bookkeeping. 0 caches everything.
    plan_cache_min_cost: float = 0.0
    # whole-query / wave fusion (executor/fusion.py): multi-call read
    # queries lower to ONE jitted device program per plan signature so
    # intermediates never leave HBM — one host↔device round trip per
    # query (or per combined dispatch wave) instead of one per call
    fusion_enabled: bool = True
    # calls above this per query fall back to per-call execution (each
    # distinct call mix compiles its own fused program; bounding the
    # mix bounds compile-cache growth)
    fusion_max_calls: int = 64
    # device-resident analytics (executor/analytics.py): cap on the
    # cross-product group count K of one GroupBy panel — a panel whose
    # dims multiply past this fails with a clear error instead of
    # allocating an unbounded [K, shards·words] device transient
    analytics_max_groups: int = 10000
    # default per-request deadline (seconds) for analytic queries
    # (GroupBy / Distinct / Percentile) when the client sends neither a
    # `timeout` param nor an X-Request-Deadline header — they run in
    # the BULK pipeline class with its own SLO objective, so they get
    # their own budget instead of pipeline-default-timeout (0 =
    # unbounded, same convention)
    analytics_timeout: float = 10.0
    # HBM byte budget for the device-resident plan cache: __cached
    # subtree bitmap stacks pinned on device so repeated subtrees stop
    # re-uploading. 0 disables (host plan cache still works)
    plan_cache_device_bytes: int = 64 << 20
    # global HBM budget for the governor ledger (executor/hbm.py):
    # every device-resident tenant (stager blocks, device plan cache,
    # batcher pad scratch, fused-launch transients) reserves against
    # ONE byte budget. 0 = the sum of the tenant shares (each subsystem
    # capped at its own knob, as before); > 0 pins the global total
    # BELOW that sum — the fix for the budgets jointly overcommitting
    # the chip
    hbm_budget_bytes: int = 0
    # device fault injection (tests/dryruns only, utils/chaos.py):
    # "oom_every=N,stall_every=N,stall_s=S,poison_every=N,after=K" —
    # see chaos.DeviceFaultSpec; "" disables
    device_faults: str = ""
    # gate for the runtime chaos-window endpoint (POST /debug/chaos):
    # installs/clears storage+device+distributed fault schedules on a
    # LIVE server. Off by default — a production server must not expose
    # a fault injector
    chaos_enabled: bool = False
    # performance attribution (utils/profiler.py, utils/slo.py):
    # continuous thread-stack sampler frequency in Hz (0 disables)
    profiler_hz: float = 10.0
    # HBM occupancy fraction above which the device-telemetry poller
    # journals a profiler.hbm_watermark event (edge-triggered)
    hbm_watermark_pct: float = 0.9
    # per-class SLOs: "cls=latency_ms@availability_target,..." — a query
    # is good when it succeeds within latency_ms; burn rate is measured
    # against 1 - target over 5m/1h windows
    slo_objectives: str = "interactive=250@0.999,bulk=2000@0.99,internal=500@0.999"
    # burn-rate alert threshold (fires when BOTH windows exceed it);
    # 14.4 = the SRE-workbook fast-burn page (budget gone in ~2 days)
    slo_burn_threshold: float = 14.4
    # workload heat ledger (utils/heat.py): per-(index, field, shard)
    # read/write/staging accounting behind GET /debug/heat; the hooks
    # collapse to one branch per shard when disabled
    heat_enabled: bool = True
    # EWMA half-life (seconds) for the per-cell heat score decay
    heat_decay_halflife: float = 300.0
    # durable event journal (utils/events.py): directory for the
    # segmented append-only backing; "" defaults to <data-dir>/.events
    # when journal-max-bytes > 0
    journal_dir: str = ""
    # on-disk retention budget in bytes across journal segments;
    # 0 disables the durable backing (in-memory ring only)
    journal_max_bytes: int = 8 << 20
    # telemetry export pipeline (utils/telemetry_export.py): JSONL file
    # sink path and/or OTLP-compatible HTTP/JSON endpoint URL; both
    # empty = exporter not started (zero hot-path cost)
    export_path: str = ""
    export_url: str = ""
    # background flush interval (seconds) and bounded queue depth; a
    # full queue DROPS (counted) rather than blocking producers
    export_interval: float = 5.0
    export_queue: int = 1024
    # multi-tenant QoS (server/tenancy.py) — the index is the tenant.
    # All five default to "" = tenancy disabled: single-tenant servers
    # keep the exact FIFO/unlimited behavior, bit-for-bit.
    # "index=weight,..." relative weighted-fair shares; "*" sets the
    # default for unlisted tenants (1.0 when absent)
    tenant_weights: str = ""
    # "index=qps,..." admission token-bucket rates; "*" sets a default
    # scaled by each tenant's weight; 0/absent = unlimited
    tenant_qps: str = ""
    # "index=bytes,..." HBM-domain byte quotas enforced by the governor
    # (stager + device plan cache attribution); "*" = default quota
    tenant_hbm_quota: str = ""
    # "index=bytes,..." in-flight request-byte caps (admission ledger)
    tenant_inflight_bytes: str = ""
    # "index=latency_ms@target,..." per-tenant SLOs, monitored as
    # tenant:<index> classes next to the per-class set; "*" lazily
    # registers every tenant at first query
    tenant_objectives: str = ""
    # opt-in diagnostics phone-home endpoint (reference diagnostics.go);
    # empty = disabled
    diagnostics_host: str = ""
    # translate-store primary (reference TranslateFile primary/replica
    # streaming, translate.go:259-310). LEGACY override: when set, that
    # one node owns every key space. Unset (the default), ownership is
    # partitioned — each column-key partition / row space is owned by
    # the jump-hash-selected cluster node (pilosa_tpu/translate/).
    translate_primary_url: str = ""
    # key translation (ISSUE 20, pilosa_tpu/translate/): column-key
    # partition count per index (fixed for the life of the data dir —
    # ids encode their partition) and the byte budget of the hot
    # id→key reverse-translation LRU
    translate_partitions: int = 16
    translate_cache_bytes: int = 1 << 20

    @property
    def host(self) -> str:
        return self.bind.rsplit(":", 1)[0] or "localhost"

    @property
    def port(self) -> int:
        parts = self.bind.rsplit(":", 1)
        return int(parts[1]) if len(parts) == 2 and parts[1] else 10101

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "Config":
        cfg = cls()
        for k, v in raw.items():
            key = k.replace("-", "_")
            if key == "cluster" and isinstance(v, dict):
                for ck, cv in v.items():
                    cattr = ck.replace("-", "_")
                    if hasattr(cfg.cluster, cattr):
                        setattr(cfg.cluster, cattr, cv)
            elif key == "tls" and isinstance(v, dict):
                for tk, tv in v.items():
                    tattr = tk.replace("-", "_")
                    if hasattr(cfg.tls, tattr):
                        setattr(cfg.tls, tattr, tv)
            elif hasattr(cfg, key):
                setattr(cfg, key, v)
            else:
                raise ValueError(f"unknown config key: {k}")
        return cfg

    def apply_env(self, env=None) -> None:
        """PILOSA_TPU_* environment overrides (reference PILOSA_* env)."""
        env = env if env is not None else os.environ
        for f in dataclasses.fields(self):
            if f.name in ("cluster", "tls"):
                continue
            key = "PILOSA_TPU_" + f.name.upper()
            if key in env:
                v: object = env[key]
                if f.type in ("int",):
                    v = int(v)  # type: ignore[arg-type]
                elif f.type in ("float",):
                    v = float(v)  # type: ignore[arg-type]
                elif f.type in ("bool",):
                    v = str(v).lower() in ("1", "true", "yes")
                setattr(self, f.name, v)

    def to_toml(self) -> str:
        lines = [
            f'data-dir = "{self.data_dir}"',
            f'bind = "{self.bind}"',
            f"max-writes-per-request = {self.max_writes_per_request}",
            f'device-policy = "{self.device_policy}"',
            f"stager-delta-enabled = {'true' if self.stager_delta_enabled else 'false'}",
            f"stager-delta-max-ratio = {self.stager_delta_max_ratio}",
            f"stager-delta-log-max = {self.stager_delta_log_max}",
            f"mesh-devices = {self.mesh_devices!r}"
            if isinstance(self.mesh_devices, str)
            else f"mesh-devices = {self.mesh_devices}",
            f"distributed-enabled = {'true' if self.distributed_enabled else 'false'}",
            f'distributed-coordinator = "{self.distributed_coordinator}"',
            f"distributed-num-processes = {self.distributed_num_processes}",
            f"distributed-dispatch-timeout = {self.distributed_dispatch_timeout}",
            f'federation-rejoin = "{self.federation_rejoin}"',
            f"federation-leader = {'true' if self.federation_leader else 'false'}",
            f"federation-reform-budget = {self.federation_reform_budget}",
            f"client-retries = {self.client_retries}",
            f"client-retry-backoff = {self.client_retry_backoff}",
            f'metric = "{self.metric}"',
            f"trace-sample-rate = {self.trace_sample_rate}",
            f"slow-query-time = {self.slow_query_time}",
            f"anti-entropy-interval = {self.anti_entropy_interval}",
            f"pipeline-enabled = {'true' if self.pipeline_enabled else 'false'}",
            f"pipeline-interactive-workers = {self.pipeline_interactive_workers}",
            f"pipeline-interactive-queue = {self.pipeline_interactive_queue}",
            f"pipeline-batch-max = {self.pipeline_batch_max}",
            f"pipeline-default-timeout = {self.pipeline_default_timeout}",
            f"pipeline-drain-timeout = {self.pipeline_drain_timeout}",
            f"ingest-enabled = {'true' if self.ingest_enabled else 'false'}",
            f"ingest-queue-limit = {self.ingest_queue_limit}",
            f"ingest-wave-max = {self.ingest_wave_max}",
            f"ingest-wave-interval = {self.ingest_wave_interval}",
            f"ingest-retry-after = {self.ingest_retry_after}",
            f"ingest-delta-max-batch = {self.ingest_delta_max_batch}",
            f'storage-faults = "{self.storage_faults}"',
            f"scrub-interval = {self.scrub_interval}",
            f"scrub-throttle = {self.scrub_throttle}",
            f"scrub-deep = {'true' if self.scrub_deep else 'false'}",
            f"scrub-repair = {'true' if self.scrub_repair else 'false'}",
            f"dispatch-enabled = {'true' if self.dispatch_enabled else 'false'}",
            f"dispatch-max-wave = {self.dispatch_max_wave}",
            f"dispatch-max-inflight = {self.dispatch_max_inflight}",
            f"dispatch-stage-ahead = {self.dispatch_stage_ahead}",
            f"tier1-max-bytes = {self.tier1_max_bytes}",
            f"prefetch-enabled = {'true' if self.prefetch_enabled else 'false'}",
            f"prefetch-depth = {self.prefetch_depth}",
            f"compressed-upload-min-ratio = {self.compressed_upload_min_ratio}",
            f"plan-cache-enabled = {'true' if self.plan_cache_enabled else 'false'}",
            f"plan-cache-max-bytes = {self.plan_cache_max_bytes}",
            f"plan-cache-min-cost = {self.plan_cache_min_cost}",
            f"fusion-enabled = {'true' if self.fusion_enabled else 'false'}",
            f"fusion-max-calls = {self.fusion_max_calls}",
            f"analytics-max-groups = {self.analytics_max_groups}",
            f"analytics-timeout = {self.analytics_timeout}",
            f"plan-cache-device-bytes = {self.plan_cache_device_bytes}",
            f"hbm-budget-bytes = {self.hbm_budget_bytes}",
            f'device-faults = "{self.device_faults}"',
            f"chaos-enabled = {'true' if self.chaos_enabled else 'false'}",
            f"profiler-hz = {self.profiler_hz}",
            f"hbm-watermark-pct = {self.hbm_watermark_pct}",
            f'slo-objectives = "{self.slo_objectives}"',
            f"slo-burn-threshold = {self.slo_burn_threshold}",
            f'tenant-weights = "{self.tenant_weights}"',
            f'tenant-qps = "{self.tenant_qps}"',
            f'tenant-hbm-quota = "{self.tenant_hbm_quota}"',
            f'tenant-inflight-bytes = "{self.tenant_inflight_bytes}"',
            f'tenant-objectives = "{self.tenant_objectives}"',
            f"heat-enabled = {'true' if self.heat_enabled else 'false'}",
            f"heat-decay-halflife = {self.heat_decay_halflife}",
            f'journal-dir = "{self.journal_dir}"',
            f"journal-max-bytes = {self.journal_max_bytes}",
            f'export-path = "{self.export_path}"',
            f'export-url = "{self.export_url}"',
            f"export-interval = {self.export_interval}",
            f"export-queue = {self.export_queue}",
            "",
            "[cluster]",
            f"disabled = {'true' if self.cluster.disabled else 'false'}",
            f"coordinator = {'true' if self.cluster.coordinator else 'false'}",
            f"replicas = {self.cluster.replicas}",
            f"hosts = {self.cluster.hosts!r}",
            f"long-query-time = {self.cluster.long_query_time}",
            f"probe-interval = {self.cluster.probe_interval}",
            f"probe-timeout = {self.cluster.probe_timeout}",
            f"down-after = {self.cluster.down_after}",
            f"status-interval = {self.cluster.status_interval}",
            "",
            "[tls]",
            f'certificate-path = "{self.tls.certificate_path}"',
            f'certificate-key-path = "{self.tls.certificate_key_path}"',
            f"skip-verify = {'true' if self.tls.skip_verify else 'false'}",
        ]
        return "\n".join(lines) + "\n"
