"""Durable streaming ingest: write-ahead queue + group commit (ISSUE 11).

The interactive path mutates one bit at a time — one op-log append, one
gang broadcast, one plan-cache/stager invalidation per bit — and bulk
imports sit at the other extreme, resetting the delta log and forcing a
full re-stage. This module is the middle the roadmap called out:
streaming writes that are batched, backpressured, durable, and
recoverable.

Submitters enqueue mutations into a bounded queue (its own admission
class beside interactive/bulk — overflow is a 429 + Retry-After, never
an unbounded buffer) and block until their wave is durable. A single
committer thread coalesces the queue into **write waves**; per wave and
per touched fragment the commit is:

  * one length-framed, checksummed OP_BATCH group-commit append +
    ONE fsync to the fragment op log (roaring/bitmap.py wire format),
  * one generation bump, so the plan cache and device stager
    invalidate once and absorb the whole wave as a single coalesced
    scatter (ops/delta.py),
  * one gang descriptor (KIND_WRITE_WAVE) across the collective plane,
    so a thousand sets replay on followers as a single frame and reach
    rejoined followers through the existing anti-entropy catch-up.

The ack contract: ``submit()`` returning means the mutation's wave was
group-committed and fsynced — it survives SIGKILL (fragment ``open()``
truncates any torn trailing record and replays the intact prefix, so
every acknowledged write is recovered). A raised error means the wave
was NOT acknowledged and left no in-memory mutation (the fragment logs
before it applies), so retrying it is safe and re-logs the identical
ops; a ``DeadlineExceeded`` is the one indeterminate outcome — the
wave may still commit after the caller stopped waiting.

Staleness is bounded by the coalesce window (``ingest-wave-interval``)
plus one wave's commit latency — readers on this node see a wave the
moment it commits (same-process holder), gang followers after the
descriptor applies.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from pilosa_tpu.server.deadline import DeadlineExceeded
from pilosa_tpu.server.pipeline import Overloaded
from pilosa_tpu.utils import events, metrics


class _Batch:
    """One submitter's mutations, acked as part of a wave."""

    __slots__ = ("index", "field", "rows", "cols", "sets", "done", "error")

    def __init__(self, index, field, rows, cols, sets) -> None:
        self.index = index
        self.field = field
        self.rows = rows
        self.cols = cols
        self.sets = sets
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class IngestQueue:
    """Bounded write-ahead queue coalescing mutations into group-committed
    write waves (one fsync + one generation bump + one gang frame per
    wave, not per bit)."""

    def __init__(
        self,
        api,
        queue_limit: int = 8192,
        wave_max: int = 2048,
        wave_interval: float = 0.002,
        retry_after: float = 0.25,
    ) -> None:
        self.api = api
        self.queue_limit = queue_limit
        self.wave_max = wave_max
        self.wave_interval = wave_interval
        self.retry_after = retry_after
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._queue: list[_Batch] = []
        self._depth = 0  # pending mutations (not batches)
        self._closed = False
        # counters for /debug/ingest (metrics carry the histories;
        # these are the cheap point-in-time snapshot)
        self._waves = 0
        self._acked = 0
        self._shed = 0
        self._nacked = 0
        self._last_wave_size = 0
        self._last_commit_seconds = 0.0
        self._thread = threading.Thread(
            target=self._run, name="ingest-committer", daemon=True
        )
        self._thread.start()

    # -- submitter side -----------------------------------------------------

    def submit(
        self, index: str, field: str, row_ids, column_ids, sets=None, deadline=None
    ) -> int:
        """Enqueue mutations and block until their wave is durable
        (group commit fsynced + gang-dispatched). Returns the number of
        acknowledged mutations. Raises ``Overloaded`` (429) when the
        queue is full, (503) when draining; re-raises the wave's commit
        error when the wave could not be made durable. ``deadline`` (a
        ``server.deadline.Deadline``) bounds the wait: when the wave
        has not committed in time, ``DeadlineExceeded`` (504) is raised
        — the write's outcome is then INDETERMINATE (its wave may still
        commit after the caller gave up), like any timed-out write."""
        rows = [int(r) for r in row_ids]
        cols = [int(c) for c in column_ids]
        if len(rows) != len(cols):
            raise ValueError("row_ids and column_ids length mismatch")
        if sets is None:
            flags = [True] * len(rows)
        else:
            flags = [bool(s) for s in sets]
            if len(flags) != len(rows):
                raise ValueError("sets length mismatch")
        if not rows:
            return 0
        n = len(rows)
        b = _Batch(index, field, rows, cols, flags)
        with self._cv:
            if deadline is not None and deadline.expired():
                raise DeadlineExceeded("ingest-admission")
            if self._closed:
                raise Overloaded("ingest queue draining", status=503)
            if self._depth + n > self.queue_limit:
                self._shed += n
                metrics.count(metrics.INGEST_SHEDS, n)
                events.record(
                    events.INGEST_SHED, index=index, field=field, n=n,
                    depth=self._depth,
                )
                # 429 (not the pipeline's queue-full 503): ingest
                # backpressure is flow control on THIS producer — back
                # off and resend; the server is not otherwise unhealthy
                raise Overloaded(
                    "ingest queue full", retry_after=self.retry_after,
                    status=429,
                )
            self._queue.append(b)
            self._depth += n
            metrics.gauge(metrics.INGEST_QUEUE_DEPTH, self._depth)
            self._cv.notify()
        if deadline is None:
            b.done.wait()
        elif not b.done.wait(timeout=max(0.0, deadline.remaining())):
            # the batch stays queued and its wave may still commit —
            # the caller's 504 means "outcome unknown", not "nacked"
            raise DeadlineExceeded(
                "ingest-commit", "ingest wave did not commit before the deadline"
            )
        if b.error is not None:
            if isinstance(b.error, OSError):
                # a storage-layer commit failure (fsync EIO, ENOSPC,
                # torn append) nacked the whole wave BEFORE apply: the
                # write did not happen, repair already re-opened the
                # log, and a retry is safe — that is a 503, not a 500
                raise Overloaded(
                    f"write wave aborted: {b.error}", status=503
                ) from b.error
            raise b.error
        return n

    # -- committer side -----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue and self._closed:
                    return
            # coalesce window: let concurrent submitters pile into the
            # wave before it commits (group commit amortizes the fsync)
            if self.wave_interval > 0:
                time.sleep(self.wave_interval)
            with self._cv:
                wave: list[_Batch] = []
                size = 0
                while self._queue and (not wave or size < self.wave_max):
                    b = self._queue.pop(0)
                    wave.append(b)
                    size += len(b.rows)
                self._depth -= size
            # NOTHING outside _commit_wave's own guards may kill this
            # thread: a dead committer leaves every submitter blocked
            # on done.wait() and wedges all future ingest. Unexpected
            # errors nack the wave instead.
            try:
                metrics.gauge(metrics.INGEST_QUEUE_DEPTH, self._depth)
                self._commit_wave(wave, size)
            except BaseException as e:
                for b in wave:
                    if b.error is None:
                        b.error = e
                    b.done.set()

    def _commit_wave(self, wave: list[_Batch], size: int) -> None:
        t0 = time.monotonic()
        try:
            # group by (index, field): one apply — one op-log group
            # commit per touched fragment, one generation bump, one
            # gang frame
            groups: dict[tuple[str, str], list[_Batch]] = {}
            for b in wave:
                groups.setdefault((b.index, b.field), []).append(b)
            acked = 0
            failed = 0
            for (index, field), batches in sorted(groups.items()):
                rows: list[int] = []
                cols: list[int] = []
                flags: list[bool] = []
                for b in batches:
                    rows += b.rows
                    cols += b.cols
                    flags += b.sets
                try:
                    self.api.apply_write_wave(index, field, rows, cols, flags)
                except BaseException as e:  # nack the group, keep committing
                    for b in batches:
                        b.error = e
                    failed += len(rows)
                else:
                    acked += len(rows)
            dt = time.monotonic() - t0
            with self._mu:
                self._waves += 1
                self._acked += acked
                self._nacked += failed
                self._last_wave_size = size
                self._last_commit_seconds = dt
            metrics.observe(metrics.INGEST_WAVE_SIZE, size)
            metrics.observe(metrics.INGEST_WAVE_COMMIT_SECONDS, dt)
            if acked:
                metrics.count(metrics.INGEST_ACKED, acked)
            events.record(
                events.INGEST_WAVE,
                size=size,
                groups=len(groups),
                acked=acked,
                nacked=failed,
                seconds=round(dt, 6),
            )
        except BaseException as e:
            # errors land BEFORE the finally wakes the waiters — a
            # batch whose group never applied must not read as acked
            for b in wave:
                if b.error is None:
                    b.error = e
            raise
        finally:
            # submitters block on done.wait() with no other wake-up:
            # every batch MUST resolve even when metrics/journal code
            # above raises
            for b in wave:
                b.done.set()

    # -- lifecycle / introspection ------------------------------------------

    def close(self) -> None:
        """Stop admitting, drain queued waves to durability, join."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=30.0)

    def stats(self) -> dict:
        with self._mu:
            return {
                "depth": self._depth,
                "queueLimit": self.queue_limit,
                "waveMax": self.wave_max,
                "waveIntervalSeconds": self.wave_interval,
                "waves": self._waves,
                "acked": self._acked,
                "nacked": self._nacked,
                "shed": self._shed,
                "lastWaveSize": self._last_wave_size,
                "lastCommitSeconds": self._last_commit_seconds,
                "draining": self._closed,
            }
