"""Serving pipeline — the scheduler between the HTTP transport and the
executor.

The round-5 measurement was blunt: the TPU kernel sustains thousands of
queries per second but the serving path delivered ~120, because every
request went straight from an unbounded ``ThreadingHTTPServer`` thread
into ``Executor.execute`` with no queue, no deadlines, and no overload
behavior. Inference-serving systems close this gap with a scheduling
layer in exactly this position (Clipper-style adaptive batching,
Orca-style continuous batching); this module is that layer:

* **Bounded admission, per class.** Requests are classed ``interactive``
  (user queries), ``bulk`` (imports), or ``internal`` (node-to-node
  legs of distributed queries/imports), each with its own bounded queue
  and dedicated worker pool — a flood of user queries cannot starve the
  cluster data plane, and a bulk import cannot starve reads. A full
  queue sheds the request immediately with ``Overloaded`` (HTTP 503 +
  ``Retry-After`` — the server as a whole is out of capacity; distinct
  from the per-tenant 429 below) instead of piling up threads until
  the process falls over.
* **Per-tenant admission + weighted-fair scheduling** (ISSUE 19). With
  a ``TenancyManager`` attached (server/tenancy.py), ``submit`` first
  charges the request's *index* against that tenant's token bucket —
  an exhausted tenant is refused with ``TenantThrottled`` (HTTP 429 +
  its own ``Retry-After``) while everyone else proceeds — and each
  class queue dequeues weighted-fair across tenants (virtual-time WFQ:
  an entry's virtual finish time advances its tenant's clock by
  ``1/weight``, the queue pops minimum finish time), so a tenant's
  burst queues behind its own weight instead of the whole fleet.
  Deadline expiry and shed semantics are unchanged; without tenancy
  (the single-tenant default) the queue is plain FIFO.
* **Deadline scheduling.** Each entry carries its request deadline
  (server/deadline.py); work whose deadline passed while queued is
  cancelled at dequeue — before the parse, the executor, or any shard
  map runs — so an overloaded server spends its workers only on
  requests that can still be answered in time.
* **Singleflight coalescing.** Equivalent concurrent read-only queries
  (same index and options, same CANONICAL plan hash — plan/canon.py,
  wired in by the HTTP handler's signature) execute ONCE; duplicates
  attach to the in-flight leader and share its result without consuming
  a queue slot or a worker. Keying on the canonical hash instead of raw
  text means argument-order-permuted spellings of one query —
  ``Intersect(Row(a), Row(b))`` vs ``Intersect(Row(b), Row(a))`` —
  coalesce too.
* **Cross-request batching.** When the queue backs up, a worker drains
  every queued entry with the same batch key (same index + options,
  read-only) in one gang and executes them as a single combined
  multi-call query. The executor fans the combined calls through its
  read pool, where the continuous ``BatchedScorer`` (and, when enabled,
  the chain-batch gate) coalesces them into batched kernel launches —
  extending the batching that previously only helped within one HTTP
  request to the whole queue. There is no artificial wait window by
  default (``pipeline-batch-window`` can add one): like the scorer,
  batch width self-tunes to the backlog.
* **Graceful drain.** ``close()`` stops admission (503), completes
  queued + in-flight work within ``drain`` seconds, and fails whatever
  remains — a restart loses no accepted work it had time to finish.

Observability: every decision lands in the process-global metric
registry (queue depth/wait, sheds, coalesce hits, batch width, deadline
expiries — docs/administration.md §Metric reference) and in the
``/debug/pipeline`` snapshot.
"""

from __future__ import annotations

import heapq
import re
import threading
import time
from typing import Any, Callable, Optional

from pilosa_tpu.analysis.locks import OrderedLock
from pilosa_tpu.server import deadline as deadline_mod
from pilosa_tpu.server.deadline import Deadline, DeadlineExceeded
from pilosa_tpu.utils import metrics, trace

CLASS_INTERACTIVE = "interactive"
CLASS_BULK = "bulk"
CLASS_INTERNAL = "internal"
CLASSES = (CLASS_INTERACTIVE, CLASS_BULK, CLASS_INTERNAL)

# analytic bulk-query detector (executor/analytics.py): like the write
# detector in the HTTP layer, a false positive from a quoted key only
# reroutes the request to a stricter class, never breaks it
_ANALYTIC_CALL_RE = re.compile(r"\b(?:GroupBy|Distinct|Percentile)\s*\(")


def classify_query(body: str, remote: bool) -> str:
    """Pipeline class for one /query body. Remote legs of distributed
    queries are internal traffic (their own queue — a user-query flood
    must not shed the cluster data plane). Analytic bulk queries
    (GroupBy / Distinct / Percentile) route to the BULK class: a
    dashboard's panel burst then queues behind the bulk workers and
    burns the bulk SLO budget instead of interactive p50. Everything
    else is interactive."""
    if remote:
        return CLASS_INTERNAL
    if body and _ANALYTIC_CALL_RE.search(body):
        return CLASS_BULK
    return CLASS_INTERACTIVE


class Overloaded(Exception):
    """Admission refused. ``status`` 503 for genuine overload (class
    queue full, server draining or shut down — retry after
    ``retry_after`` seconds, ideally against another node) or 429 for
    a per-tenant refusal (``TenantThrottled``, server/tenancy.py —
    only that tenant must back off)."""

    def __init__(self, message: str, retry_after: float = 1.0, status: int = 503) -> None:
        super().__init__(message)
        self.retry_after = retry_after
        self.status = status


# wait time (seconds) the current pipeline worker's entry spent queued;
# API.query backfills it as a `pipeline.wait` span on the root trace
_entry_wait: "threading.local" = threading.local()


def current_queue_wait() -> float:
    return getattr(_entry_wait, "value", 0.0)


class _Entry:
    __slots__ = (
        "cls",
        "thunk",
        "signature",
        "batch_key",
        "batch_payload",
        "deadline",
        "event",
        "result",
        "error",
        "t_enq",
        "wait_s",
        "trace_ctx",
        "index",
        "seq",
        "vstart",
        "vft",
        "skip",
    )

    def __init__(
        self,
        cls: str,
        thunk: Callable[[], Any],
        signature=None,
        batch_key=None,
        batch_payload=None,
        deadline: Optional[Deadline] = None,
        trace_ctx: Optional[tuple] = None,
        index: str = "",
    ) -> None:
        self.cls = cls
        self.thunk = thunk
        self.signature = signature
        self.batch_key = batch_key
        self.batch_payload = batch_payload
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.t_enq = 0.0
        self.wait_s = 0.0
        # distributed trace context (utils/trace.py tuple): carried so
        # a coalesced follower can link the leader's trace
        self.trace_ctx = trace_ctx
        # the tenant (ISSUE 19): per-tenant counters + WFQ scheduling
        self.index = index
        # _TenantFairQueue bookkeeping: arrival order, virtual
        # start/finish time, and the lazy-removal marker
        self.seq = 0
        self.vstart = 0.0
        self.vft = 0.0
        self.skip = False


class _TenantFairQueue:
    """Virtual-time weighted-fair queue over ``_Entry.index`` with the
    small deque-ish surface the workers use (append / popleft / remove
    / len / iteration in dequeue order).

    Classic WFQ collapsed to unit cost per entry: an arriving entry's
    virtual start is ``max(V, finish[tenant])``, its finish is
    ``start + 1/weight``, and ``popleft`` returns the minimum finish
    time — over any backlogged window each tenant dequeues in
    proportion to its weight, and an idle tenant re-enters at the
    current virtual time V (no banked credit, no starvation). With no
    ``weight_fn`` (the single-tenant default) every entry gets finish
    0 and the seq tie-break makes the queue exactly FIFO — bit-for-bit
    the pre-tenancy order. Callers hold the pipeline lock."""

    __slots__ = ("weight_fn", "_heap", "_len", "_seq", "_vtime", "_finish", "_nq")

    def __init__(self, weight_fn: Optional[Callable[[str], float]] = None) -> None:
        self.weight_fn = weight_fn
        self._heap: list[tuple[float, int, _Entry]] = []
        self._len = 0
        self._seq = 0
        self._vtime = 0.0
        # tenant -> virtual finish of its latest queued entry
        self._finish: dict[str, float] = {}
        # tenant -> live queued entries (prunes _finish when it can)
        self._nq: dict[str, int] = {}

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        """Live entries in dequeue order — the batch-collection scans
        (_dequeue_gang / _collect_window) see the same order popleft
        would produce."""
        live = sorted(t for t in self._heap if not t[2].skip)
        return iter(e for _, _, e in live)

    def append(self, e: _Entry) -> None:
        e.seq = self._seq
        self._seq += 1
        if self.weight_fn is not None:
            t = e.index
            try:
                w = float(self.weight_fn(t) or 1.0)
            except Exception:
                w = 1.0
            start = max(self._vtime, self._finish.get(t, 0.0))
            e.vstart = start
            e.vft = start + 1.0 / max(1e-3, w)
            self._finish[t] = e.vft
            self._nq[t] = self._nq.get(t, 0) + 1
        heapq.heappush(self._heap, (e.vft, e.seq, e))
        self._len += 1

    def popleft(self) -> _Entry:
        while self._heap:
            _, _, e = heapq.heappop(self._heap)
            if e.skip:
                continue
            self._drop(e)
            # virtual time advances to the dequeued entry's start: a
            # tenant arriving later starts from here, not from zero
            if e.vstart > self._vtime:
                self._vtime = e.vstart
            return e
        raise IndexError("pop from an empty _TenantFairQueue")

    def remove(self, e: _Entry) -> None:
        """Lazy removal: mark; the heap tuple is discarded at pop."""
        if e.skip:
            raise ValueError("entry not in queue")
        e.skip = True
        self._drop(e)

    def _drop(self, e: _Entry) -> None:
        self._len -= 1
        if self.weight_fn is None:
            return
        t = e.index
        n = self._nq.get(t, 1) - 1
        if n > 0:
            self._nq[t] = n
        else:
            self._nq.pop(t, None)
            # the finish stamp only matters while it is ahead of V
            # (recent credit); once V caught up it is dead weight
            if self._finish.get(t, 0.0) <= self._vtime:
                self._finish.pop(t, None)
        if len(self._finish) > 2 * len(self._nq) + 64:
            for k in [
                k
                for k, f in self._finish.items()
                if f <= self._vtime and k not in self._nq
            ]:
                del self._finish[k]


class _ClassQueue:
    """One bounded admission queue + its dedicated workers."""

    __slots__ = (
        "name",
        "limit",
        "workers",
        "q",
        "busy",
        "admitted",
        "sheds",
        "completed",
    )

    def __init__(
        self,
        name: str,
        limit: int,
        workers: int,
        weight_fn: Optional[Callable[[str], float]] = None,
    ) -> None:
        self.name = name
        self.limit = limit
        self.workers = workers
        self.q = _TenantFairQueue(weight_fn)
        self.busy = 0
        self.admitted = 0
        self.sheds = 0
        self.completed = 0


def make_query_combiner(api) -> Callable:
    """Gang executor for homogeneous read-only queries: concatenate the
    members' PQL (PQL is whitespace-separated calls), run ONE
    ``api.query``, and split the results back by each member's call
    count. The combined call list flows through the executor's
    concurrent read pool, where the batched scorers coalesce the
    members' kernel work into single launches — cross-request batching
    through entirely existing machinery. Any error falls back to
    per-entry execution (the pipeline worker handles that), so a bad
    member can never fail its gang-mates."""
    from pilosa_tpu.pql import parse

    def combine(entries: list[_Entry]) -> list[dict]:
        p = entries[0].batch_payload
        texts = [e.batch_payload["query"] for e in entries]
        # per-member call counts; also surfaces a syntax error BEFORE
        # the combined execution so the fallback gives it a proper 400
        counts = [len(parse(t).calls) for t in texts]
        resp = api.query(p["index"], " ".join(texts), **p["kwargs"])
        results = resp["results"]
        out, off = [], 0
        for n in counts:
            out.append({"results": results[off : off + n]})
            off += n
        return out

    return combine


class QueryPipeline:
    """The scheduler. ``submit`` blocks the calling (HTTP) thread until
    its entry is executed by a class worker, shed, or expired — the
    transport thread still writes the response, but execution
    concurrency and queue growth are bounded here."""

    def __init__(
        self,
        workers: Optional[dict[str, int]] = None,
        queue_limits: Optional[dict[str, int]] = None,
        combine_fn: Optional[Callable] = None,
        batch_max: int = 16,
        batch_window: float = 0.0,
        shed_retry_after: float = 1.0,
        drain_timeout: float = 10.0,
        dispatch_handoff: bool = False,
        tenancy=None,
    ) -> None:
        workers = workers or {}
        queue_limits = queue_limits or {}
        defaults_w = {CLASS_INTERACTIVE: 8, CLASS_BULK: 2, CLASS_INTERNAL: 8}
        defaults_q = {CLASS_INTERACTIVE: 64, CLASS_BULK: 16, CLASS_INTERNAL: 128}
        self._mu = OrderedLock("pipeline.mu")
        self._cond = threading.Condition(self._mu)
        # server/tenancy.py TenancyManager (duck-typed: weight / admit /
        # release). None or a disabled manager keeps the pre-tenancy
        # fast path: FIFO queues, no admission charge, no extra lock.
        self.tenancy = tenancy
        weight_fn = (
            tenancy.weight
            if tenancy is not None and getattr(tenancy, "enabled", False)
            else None
        )
        self._classes = {
            c: _ClassQueue(
                c,
                max(1, int(queue_limits.get(c, defaults_q[c]))),
                max(1, int(workers.get(c, defaults_w[c]))),
                weight_fn=weight_fn,
            )
            for c in CLASSES
        }
        self.combine_fn = combine_fn
        self.batch_max = max(1, int(batch_max))
        self.batch_window = float(batch_window)
        self.shed_retry_after = float(shed_retry_after)
        self.drain_timeout = float(drain_timeout)
        # when the executor's continuous-batching dispatch engine owns
        # cross-request combining (it groups heterogeneous plans by
        # canonical signature per wave), workers hand entries off one at
        # a time instead of gang-batching identical queries here —
        # otherwise both layers would contend for the same backlog
        self.dispatch_handoff = bool(dispatch_handoff)
        self._closing = False
        # signature -> leader entry (singleflight)
        self._inflight: dict = {}
        # cross-class counters (ints under _mu; snapshot is consistent)
        self.coalesce_hits = 0
        self.batches = 0
        self.batched_entries = 0
        self.expired = 0
        # per-tenant counters (ISSUE 19 satellite: under mixed load the
        # lumped counters above are misleading — /debug/pipeline and
        # /debug/tenancy break them out by index). Keyed by index, ""
        # excluded (direct submit callers with no tenant context).
        self.tenant_counters: dict[str, dict[str, int]] = {}
        self._threads: list[threading.Thread] = []
        for c, cq in self._classes.items():
            for i in range(cq.workers):
                t = threading.Thread(
                    target=self._worker, args=(cq,), name=f"pipeline-{c}-{i}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)

    # -- admission -----------------------------------------------------------

    def submit(
        self,
        cls: str,
        thunk: Callable[[], Any],
        deadline: Optional[Deadline] = None,
        signature=None,
        batch: Optional[dict] = None,
        trace_ctx: Optional[tuple] = None,
        index: str = "",
        nbytes: int = 0,
    ) -> Any:
        """Run ``thunk`` through the pipeline and return its result.
        Raises Overloaded (shed / draining / tenant-throttled),
        DeadlineExceeded, or whatever the thunk raised. ``index`` is
        the tenant; ``nbytes`` its in-flight byte charge (the request
        body size — released when submit returns)."""
        tenancy = self.tenancy
        charged = False
        if tenancy is not None:
            # per-tenant token bucket BEFORE the shared queue: raises
            # TenantThrottled (429 + the tenant's own Retry-After)
            try:
                tenancy.admit(index, cls, nbytes)
            except Overloaded:
                if index:
                    with self._mu:
                        self._tenant_counter(index)["throttled"] += 1
                raise
            charged = True
        try:
            return self._submit_admitted(
                cls, thunk, deadline, signature, batch, trace_ctx, index
            )
        finally:
            if charged:
                tenancy.release(index, cls, nbytes)

    def _tenant_counter(self, index: str) -> dict[str, int]:
        """Per-tenant counter row; caller holds _mu."""
        d = self.tenant_counters.get(index)
        if d is None:
            d = self.tenant_counters[index] = {
                "admitted": 0,
                "sheds": 0,
                "throttled": 0,
                "expired": 0,
                "completed": 0,
                "coalesce_hits": 0,
            }
        return d

    def _submit_admitted(
        self,
        cls: str,
        thunk: Callable[[], Any],
        deadline: Optional[Deadline],
        signature,
        batch: Optional[dict],
        trace_ctx: Optional[tuple],
        index: str,
    ) -> Any:
        cq = self._classes[cls]
        entry = _Entry(
            cls,
            thunk,
            signature=signature,
            batch_key=batch["key"] if batch else None,
            batch_payload=batch,
            deadline=deadline,
            trace_ctx=trace_ctx,
            index=index,
        )
        leader: Optional[_Entry] = None
        with self._mu:
            if self._closing:
                raise Overloaded("server is draining", status=503)
            if signature is not None:
                leader = self._inflight.get(signature)
                if leader is not None:
                    # duplicate of an in-flight query: attach, consume
                    # no queue slot, no worker
                    self.coalesce_hits += 1
                    metrics.count(metrics.PIPELINE_COALESCE_HITS)
                    if index:
                        self._tenant_counter(index)["coalesce_hits"] += 1
                else:
                    self._inflight[signature] = entry
            if leader is None:
                if len(cq.q) >= cq.limit:
                    cq.sheds += 1
                    metrics.count(metrics.PIPELINE_SHEDS, cls=cls)
                    if index:
                        self._tenant_counter(index)["sheds"] += 1
                        metrics.count(
                            metrics.TENANT_SHEDS, tenant=index, cls=cls
                        )
                    if signature is not None:
                        self._inflight.pop(signature, None)
                    # 503, not 429: the CLASS queue is full — the server
                    # (not one tenant) is out of capacity, and internal
                    # retry policy treats 503 as retryable-elsewhere
                    raise Overloaded(
                        f"{cls} admission queue full "
                        f"({len(cq.q)}/{cq.limit}); retry later",
                        retry_after=self.shed_retry_after,
                        status=503,
                    )
                entry.t_enq = time.monotonic()
                cq.q.append(entry)
                cq.admitted += 1
                metrics.count(metrics.PIPELINE_ADMITTED, cls=cls)
                if index:
                    self._tenant_counter(index)["admitted"] += 1
                    metrics.count(
                        metrics.TENANT_ADMITTED, tenant=index, cls=cls
                    )
                metrics.gauge(metrics.PIPELINE_QUEUE_DEPTH, len(cq.q), cls=cls)
                self._cond.notify_all()
        if leader is not None and trace_ctx is not None and trace_ctx[2]:
            # singleflight made this request a follower: it never
            # executes, so its trace gets a point entry span-linking
            # the leader's execution (outside _mu — the tracer has its
            # own lock and link recording must not extend admission)
            lctx = leader.trace_ctx
            trace.record_link(
                metrics.STAGE_PIPELINE_COALESCE,
                trace_ctx,
                lctx if lctx is not None else ("", ""),
                cls=cls,
                leader_traced=bool(lctx is not None and lctx[2]),
            )
        # wait OUTSIDE the lock (workers need it to make progress)
        return self._await(leader if leader is not None else entry, deadline)

    def _await(self, entry: _Entry, dl: Optional[Deadline]):
        """Block until ``entry`` resolves; a waiter whose own deadline
        passes first stops waiting (its queued work is skipped by the
        worker's dequeue-time check; a follower simply detaches)."""
        if dl is None:
            entry.event.wait()
        else:
            while not entry.event.is_set():
                rem = dl.remaining()
                if rem <= 0:
                    dl.check("admission")  # raises (and counts)
                entry.event.wait(timeout=min(rem, 0.5))
        if entry.error is not None:
            raise entry.error
        return entry.result

    # -- workers -------------------------------------------------------------

    def _worker(self, cq: _ClassQueue) -> None:
        while True:
            with self._mu:
                while not cq.q and not self._closing:
                    self._cond.wait()
                if not cq.q:
                    return  # closing and drained
                gang = self._dequeue_gang(cq)
                cq.busy += len(gang)
                metrics.gauge(metrics.PIPELINE_QUEUE_DEPTH, len(cq.q), cls=cq.name)
            try:
                self._run_gang(cq, gang)
            finally:
                with self._mu:
                    cq.busy -= len(gang)
                    cq.completed += len(gang)
                    for e in gang:
                        if e.index:
                            self._tenant_counter(e.index)["completed"] += 1

    def _dequeue_gang(self, cq: _ClassQueue) -> list[_Entry]:
        """Pop the head entry plus every queued peer sharing its batch
        key (up to batch_max) — the backlog IS the batching window.
        The batch key carries the index, so a gang is always a single
        tenant's work. Caller holds the lock."""
        head = cq.q.popleft()
        gang = [head]
        if (
            self.dispatch_handoff
            or head.batch_key is None
            or self.batch_max < 2
            or not self.combine_fn
        ):
            return gang
        if cq.q:
            took = [e for e in cq.q if e.batch_key == head.batch_key]
            for e in took[: self.batch_max - 1]:
                cq.q.remove(e)
                gang.append(e)
        return gang

    def _collect_window(self, cq: _ClassQueue, gang: list[_Entry]) -> list[_Entry]:
        """Optional artificial batching window: wait up to
        ``batch_window`` for same-key arrivals before executing. Off by
        default (0) — the continuous design needs no wait under load
        and a lone query must not pay latency for an empty queue."""
        if self.batch_window <= 0 or len(gang) >= self.batch_max:
            return gang
        stop = time.monotonic() + self.batch_window
        key = gang[0].batch_key
        while time.monotonic() < stop and len(gang) < self.batch_max:
            with self._mu:
                took = [e for e in cq.q if e.batch_key == key]
                for e in took[: self.batch_max - len(gang)]:
                    cq.q.remove(e)
                    gang.append(e)
            if len(gang) >= self.batch_max:
                break
            time.sleep(min(0.0005, self.batch_window))
        return gang

    def _run_gang(self, cq: _ClassQueue, gang: list[_Entry]) -> None:
        if gang and gang[0].batch_key is not None:
            gang = self._collect_window(cq, gang)
        now = time.monotonic()
        live: list[_Entry] = []
        for e in gang:
            e.wait_s = now - e.t_enq
            metrics.observe(metrics.PIPELINE_WAIT_SECONDS, e.wait_s, cls=cq.name)
            if e.index:
                metrics.observe(
                    metrics.TENANT_QUEUE_WAIT_SECONDS,
                    e.wait_s,
                    tenant=e.index,
                    cls=cq.name,
                )
            if e.deadline is not None and e.deadline.expired():
                # expired while queued: cancel BEFORE any parse/executor
                # work (its waiter already raised or will immediately)
                with self._mu:
                    self.expired += 1
                    if e.index:
                        self._tenant_counter(e.index)["expired"] += 1
                metrics.count(metrics.PIPELINE_DEADLINE_EXPIRED, stage="queue")
                self._finish(e, error=DeadlineExceeded("queue"))
                continue
            live.append(e)
        if not live:
            return
        if len(live) >= 2 and self.combine_fn is not None:
            with self._mu:
                self.batches += 1
                self.batched_entries += len(live)
            metrics.count(metrics.PIPELINE_BATCHES)
            metrics.observe(metrics.PIPELINE_BATCH_WIDTH, len(live))
            dls = [e.deadline for e in live if e.deadline is not None]
            gang_dl = min(dls, key=lambda d: d.at) if dls else None
            try:
                with deadline_mod.activate(gang_dl):
                    results = self.combine_fn(live)
                for e, r in zip(live, results):
                    self._finish(e, result=r)
                return
            except BaseException:
                # combined execution failed (one bad member, deadline,
                # anything): fall back to per-entry execution so each
                # member gets ITS OWN outcome
                pass
        for e in live:
            self._run_one(e)

    def _run_one(self, e: _Entry) -> None:
        if e.deadline is not None and e.deadline.expired():
            with self._mu:
                self.expired += 1
                if e.index:
                    self._tenant_counter(e.index)["expired"] += 1
            metrics.count(metrics.PIPELINE_DEADLINE_EXPIRED, stage="queue")
            self._finish(e, error=DeadlineExceeded("queue"))
            return
        _entry_wait.value = e.wait_s
        try:
            with deadline_mod.activate(e.deadline):
                self._finish(e, result=e.thunk())
        except BaseException as err:
            self._finish(e, error=err)
        finally:
            _entry_wait.value = 0.0

    def _finish(self, e: _Entry, result=None, error=None) -> None:
        e.result = result
        e.error = error
        if e.signature is not None:
            with self._mu:
                if self._inflight.get(e.signature) is e:
                    del self._inflight[e.signature]
        e.event.set()

    # -- lifecycle -----------------------------------------------------------

    def close(self, drain: Optional[float] = None) -> bool:
        """Graceful drain: stop admission, let the workers complete
        queued + in-flight work, fail the rest after ``drain`` seconds.
        Returns True when everything drained in time."""
        drain = self.drain_timeout if drain is None else drain
        t0 = time.monotonic()
        with self._mu:
            if self._closing:
                return True
            self._closing = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=max(0.0, drain - (time.monotonic() - t0)))
        clean = True
        # pop under the lock, finish outside it: _finish re-acquires
        # _mu to drop the coalescing-inflight entry, so calling it here
        # with _mu held self-deadlocks on any queued signatured request
        leftovers: list[_Entry] = []
        with self._mu:
            for cq in self._classes.values():
                while cq.q:
                    clean = False
                    leftovers.append(cq.q.popleft())
        for e in leftovers:
            self._finish(e, error=Overloaded("server shut down", status=503))
        metrics.observe(metrics.PIPELINE_DRAIN_SECONDS, time.monotonic() - t0)
        return clean and all(not t.is_alive() for t in self._threads)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """The /debug/pipeline snapshot."""
        with self._mu:
            return {
                "enabled": True,
                "closing": self._closing,
                "batch_max": self.batch_max,
                "batch_window_s": self.batch_window,
                "dispatch_handoff": self.dispatch_handoff,
                "coalesce_hits": self.coalesce_hits,
                "coalesce_inflight": len(self._inflight),
                "batches": self.batches,
                "batched_entries": self.batched_entries,
                "deadline_expired": self.expired,
                "weighted_fair": any(
                    cq.q.weight_fn is not None for cq in self._classes.values()
                ),
                "tenants": {
                    idx: dict(row)
                    for idx, row in self.tenant_counters.items()
                },
                "classes": {
                    c: {
                        "queue_depth": len(cq.q),
                        "queue_limit": cq.limit,
                        "workers": cq.workers,
                        "busy": cq.busy,
                        "admitted": cq.admitted,
                        "sheds": cq.sheds,
                        "completed": cq.completed,
                    }
                    for c, cq in self._classes.items()
                },
            }
