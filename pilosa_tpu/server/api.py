"""Programmatic API (L6) — validated surface over holder/executor/cluster
(reference api.go).

Each method is gated on cluster state like the reference's
validAPIMethods (api.go:70-93): while the cluster is RESIZING only a
restricted set is callable.
"""

from __future__ import annotations

import io
import json
import time
from typing import Optional

import numpy as np

from pilosa_tpu import SHARD_WIDTH, __version__
from pilosa_tpu.core import FieldOptions, Row
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.executor import ExecOptions
from pilosa_tpu.pql import parse
from pilosa_tpu.server import deadline, pipeline
from pilosa_tpu.utils import events, heat, metrics, profiler, trace

# cluster states (reference cluster.go:42-45)
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"

# Methods permitted while RESIZING/STARTING (reference api.go:70-93;
# fragment streaming must stay available mid-resize — it IS the resize)
_RESIZING_METHODS = {
    "cluster_message",
    "state",
    "status",
    "resize_abort",
    "fragment_data",
    "fragment_blocks",
    "fragment_block_data",
    "schema",
}


class APIError(Exception):
    def __init__(self, message: str, status: int = 400) -> None:
        super().__init__(message)
        self.status = status


from pilosa_tpu.utils.errors import NotFoundError as _SharedNotFound  # noqa: E402


class NotFoundError(APIError, _SharedNotFound):
    """API-level 404. Subclasses BOTH APIError (carries the status for
    the HTTP layer) and the shared utils.errors.NotFoundError, so
    ``except`` on either type catches it — no same-named-type trap."""

    def __init__(self, message: str) -> None:
        APIError.__init__(self, message, status=404)


class API:
    def __init__(self, holder, executor, cluster=None, server=None) -> None:
        self.holder = holder
        self.executor = executor
        self.cluster = cluster
        self.server = server

    # -- state gate --

    def _state(self) -> str:
        if self.cluster is None:
            return STATE_NORMAL
        return self.cluster.state

    def _validate(self, method: str) -> None:
        state = self._state()
        if state == STATE_NORMAL:
            return
        if state == STATE_RESIZING and method in _RESIZING_METHODS:
            return
        if state == STATE_STARTING and method in _RESIZING_METHODS | {"schema"}:
            return
        raise APIError(
            f"api method {method} unavailable in cluster state {state}", status=503
        )

    # -- query (reference api.Query:96-150) --

    def query(
        self,
        index: str,
        query: str,
        shards: Optional[list[int]] = None,
        remote: bool = False,
        exclude_row_attrs: bool = False,
        exclude_columns: bool = False,
        column_attrs: bool = False,
        profile: bool = False,
        cache: bool = True,
        trace_ctx: Optional[tuple] = None,
        waterfall: bool = False,
    ) -> dict:
        self._validate("query")
        # deadline boundary: cancel BEFORE the parse — an expired
        # request must cost the server nothing past this line
        dl = deadline.current()
        if dl is not None:
            dl.check(metrics.STAGE_QUERY)
        opt = ExecOptions(
            remote=remote,
            exclude_row_attrs=exclude_row_attrs,
            exclude_columns=exclude_columns,
            # cache=false bypasses the plan result cache; profile=true
            # does too — a profiled query must show real execution, not
            # a cache hit's absence of spans. profile=waterfall likewise:
            # a cache hit has no device leg to attribute
            cache=cache and not profile and not waterfall,
        )
        # root span: forced by profile=true or a sampled upstream
        # traceparent (the ingress point ADOPTS the caller's trace id),
        # else admitted by the tracer's sample rate / slow-query
        # threshold (NOP when off — the untraced query allocates no
        # span anywhere below)
        root = trace.TRACER.trace(
            metrics.STAGE_QUERY, force=profile, ctx=trace_ctx, index=index
        )
        # always-on attribution (ISSUE 12): every served query carries a
        # waterfall accumulator — a plain dict in a contextvar, one get
        # + float add per instrumented leg, no spans, no sampling gate.
        # Created HERE (not the HTTP thread) because pipeline thunks run
        # on worker threads where the handler's contextvars don't reach.
        wf: dict = {}
        t_q0 = time.monotonic()
        # an UNSAMPLED upstream context still propagates its ids to
        # dispatch items and outbound RPC headers, span-free
        with root, trace.push_ctx(
            trace_ctx if root is trace.NOP_SPAN else None
        ), trace.attrib_activate(wf):
            # when this query came through the serving pipeline, its
            # admission-queue wait predates the root span — backfill it
            # so profile=true shows where serving latency went
            wait = pipeline.current_queue_wait()
            if wait > 0:
                wf[trace.WF_PIPELINE_QUEUE] = wait
                if root is not trace.NOP_SPAN:
                    root.record(
                        metrics.STAGE_PIPELINE_WAIT, root.t0 - wait, wait
                    )
            try:
                t0p = time.monotonic()
                q = parse(query)
                wf[trace.WF_PLAN_CANON] = time.monotonic() - t0p
            except Exception as e:
                raise APIError(f"parsing: {e}") from e
            idx = self.holder.index(index)
            if idx is None:
                raise NotFoundError(f"index not found: {index}")
            results = self.executor.execute(index, q, shards, opt)
        resp: dict = {"results": results}
        # total covers parse → results plus the pre-span pipeline wait;
        # the handler pops _waterfall into the aggregator + SLO monitor
        total_s = (time.monotonic() - t_q0) + wf.get(trace.WF_PIPELINE_QUEUE, 0.0)
        resp["_waterfall"] = profiler.WATERFALL.summarize(wf, total_s)
        if waterfall:
            resp["profile"] = {"waterfall": resp["_waterfall"]}
        if profile:
            resp["profile"] = trace.TRACER.stitched(root.to_dict())
        if remote and root is not trace.NOP_SPAN:
            # federation remote leg: return this process's serialized
            # span tree in the response envelope so the root process
            # grafts it into ONE stitched trace (Dapper-style)
            # stitched: a rank-0 replay span grafts into this leader's
            # buffer synchronously, so it rides back in the envelope too
            resp["spans"] = [trace.TRACER.stitched(root.to_dict())]
        if column_attrs and idx.column_attrs is not None:
            cols = set()
            for r in results:
                if isinstance(r, Row):
                    cols.update(int(c) for c in r.columns())
            attr_sets = []
            for col in sorted(cols):
                attrs = idx.column_attrs.attrs(col)
                if attrs:
                    attr_sets.append({"id": col, "attrs": attrs})
            resp["columnAttrs"] = attr_sets
        return resp

    # -- schema CRUD --

    def create_index(self, name: str, keys: bool = False) -> None:
        self._validate("create_index")
        try:
            self.holder.create_index(name, keys=keys)
        except ValueError as e:
            raise APIError(str(e), status=409 if "exists" in str(e) else 400)
        if self.server is not None:
            self.server.send_sync({"type": "create-index", "index": name, "keys": keys})

    def delete_index(self, name: str) -> None:
        self._validate("delete_index")
        try:
            self.holder.delete_index(name)
        except ValueError as e:
            raise NotFoundError(str(e))
        if self.server is not None:
            self.server.send_sync({"type": "delete-index", "index": name})

    def create_field(self, index: str, field: str, options: dict) -> None:
        self._validate("create_field")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        try:
            idx.create_field(field, FieldOptions.from_dict(options or {}))
        except ValueError as e:
            raise APIError(str(e), status=409 if "exists" in str(e) else 400)
        if self.server is not None:
            self.server.send_sync(
                {"type": "create-field", "index": index, "field": field,
                 "options": options or {}}
            )

    def delete_field(self, index: str, field: str) -> None:
        self._validate("delete_field")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        try:
            idx.delete_field(field)
        except ValueError as e:
            raise NotFoundError(str(e))
        if self.server is not None:
            self.server.send_sync(
                {"type": "delete-field", "index": index, "field": field}
            )

    def delete_view(self, index: str, field: str, view: str) -> None:
        self._validate("delete_view")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        v = f.views.pop(view, None)
        if v is not None:
            v.close()
            if v.path:
                import shutil

                shutil.rmtree(v.path, ignore_errors=True)

    def schema(self) -> list[dict]:
        self._validate("schema")
        return self.holder.schema()

    def fragment_inventory(self) -> list[dict]:
        """Every (index, field, view, shard) this node holds — the
        resize coordinator unions these across old owners so fragment
        moves enumerate what EXISTS, not the whole shard space (the
        reference's availableShards bitmaps serve the same purpose,
        cluster.go:689-773)."""
        out = []
        for iname, idx in self.holder.indexes.items():
            for fname, fld in idx.fields.items():
                for vname, view in fld.views.items():
                    for shard in sorted(view.fragments):
                        out.append(
                            {
                                "index": iname,
                                "field": fname,
                                "view": vname,
                                "shard": shard,
                            }
                        )
        return out

    def views(self, index: str, field: str) -> list[str]:
        self._validate("views")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        return sorted(f.views)

    # -- imports (reference api.Import:652-696) --

    def _gang_import(self, op: str, payload: dict, local: bool = False) -> bool:
        """Multihost leader: broadcast an import descriptor so every
        rank's holder replays the identical mutation; True when the
        gang handled it (the leader thread and every follower re-enter
        this method with the gang flag set and fall through to the
        local body). In a FEDERATED deployment the cluster plane routes
        shard groups first, so only the ``import_*_local`` legs
        (local=True) replay through the gang. timestamps may be
        datetimes on internal callers — gang payloads are JSON, so
        those callers (cluster legs) never run in multihost mode."""
        mh = getattr(self.server, "multihost", None) if self.server else None
        if mh is None or not mh.should_dispatch_import(local):
            return False
        from pilosa_tpu.parallel.multihost import (
            Descriptor,
            KIND_IMPORT,
            KIND_IMPORT_VALUES,
        )

        kind = KIND_IMPORT if op == "import" else KIND_IMPORT_VALUES
        mh.dispatch(Descriptor(kind, payload), deadline=deadline.current())
        return True

    def import_bits(
        self,
        index: str,
        field: str,
        row_ids: list[int],
        column_ids: list[int],
        timestamps: Optional[list] = None,
        row_keys: Optional[list[str]] = None,
        column_keys: Optional[list[str]] = None,
    ) -> None:
        self._validate("import")
        if self._gang_import(
            "import",
            {
                "index": index,
                "field": field,
                "row_ids": list(row_ids),
                "column_ids": list(column_ids),
                "timestamps": list(timestamps) if timestamps else None,
                "row_keys": list(row_keys) if row_keys else None,
                "column_keys": list(column_keys) if column_keys else None,
            },
        ):
            return
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        f = idx.field(field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        ts = self.executor.translate_store
        if column_keys:
            if ts is None:
                raise APIError("translate store not configured")
            column_ids = ts.translate_columns_to_ids(index, column_keys)
        if row_keys:
            if ts is None:
                raise APIError("translate store not configured")
            row_ids = ts.translate_rows_to_ids(index, field, row_keys)
        # Route bit groups to their shard owners (the reference's client
        # groups by owner before POSTing, http/client.go:276,922; routing
        # server-side keeps single-endpoint imports correct in a cluster).
        if self.cluster is not None and len(self.cluster.nodes) > 1:
            self._route_import(
                index, field, row_ids, column_ids, timestamps, local_only=False
            )
            return
        parsed_ts = _parse_timestamps(timestamps)
        f.import_bits(row_ids, column_ids, parsed_ts)

    def import_bits_local(self, index, field, row_ids, column_ids, timestamps=None):
        """Internal: import bits into this node only (owner-side leg).
        On a federated gang leader this leg replays through the gang so
        follower holders receive the identical shard group."""
        if self._gang_import(
            "import",
            {
                "index": index,
                "field": field,
                "row_ids": list(row_ids),
                "column_ids": list(column_ids),
                "timestamps": list(timestamps) if timestamps else None,
                "local": True,
            },
            local=True,
        ):
            return
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        f.import_bits(row_ids, column_ids, _parse_timestamps(timestamps))

    def _route_import(self, index, field, row_ids, column_ids, timestamps, local_only):
        from pilosa_tpu import SHARD_WIDTH as SW

        groups: dict[int, list[int]] = {}
        for i, col in enumerate(column_ids):
            groups.setdefault(col // SW, []).append(i)
        ts = timestamps or [0] * len(column_ids)
        for shard, idxs in sorted(groups.items()):
            rows = [row_ids[i] for i in idxs]
            cols = [column_ids[i] for i in idxs]
            tss = [ts[i] for i in idxs] if timestamps else None
            for node in self.cluster.shard_nodes(index, shard):
                if node.id == self.cluster.node_id:
                    self.import_bits_local(index, field, rows, cols, tss)
                else:
                    self.cluster.client.import_bits_local(
                        node.uri, index, field, rows, cols, tss
                    )

    # -- ingest write waves (server/ingest.py group commit) --

    def apply_write_wave(
        self, index: str, field: str, row_ids, column_ids, sets=None
    ) -> int:
        """Apply one coalesced ingest write wave: sets AND clears in a
        single batch, one op-log group commit + fsync and one
        generation bump per touched fragment, one KIND_WRITE_WAVE gang
        frame. Returns the number of bits that changed (or the wave
        size when the gang replays it — follower counts aren't
        collected). In a multi-node cluster, shard groups route to
        their owners first; a remote owner acks only after its own
        ingest queue group-commits, so durability is owner-side."""
        self._validate("import")
        if self.cluster is not None and len(self.cluster.nodes) > 1:
            groups: dict[int, list[int]] = {}
            for i, col in enumerate(column_ids):
                groups.setdefault(int(col) // SHARD_WIDTH, []).append(i)
            flags = sets if sets is not None else [True] * len(column_ids)
            total = 0
            for shard, idxs in sorted(groups.items()):
                rows = [int(row_ids[i]) for i in idxs]
                cols = [int(column_ids[i]) for i in idxs]
                ss = [bool(flags[i]) for i in idxs]
                # every replica applies the group, but it counts ONCE
                # toward the wave total (replication factor > 1 must
                # not inflate the acked/changed count); prefer the
                # local replica's exact changed count when we hold one
                local_changed = None
                remote_changed = None
                for node in self.cluster.shard_nodes(index, shard):
                    if node.id == self.cluster.node_id:
                        local_changed = self.apply_write_wave_local(
                            index, field, rows, cols, ss
                        )
                    else:
                        c = self.cluster.client.ingest(
                            node.uri, index, field, rows, cols, ss
                        )
                        remote_changed = max(remote_changed or 0, c)
                if local_changed is not None:
                    total += local_changed
                elif remote_changed is not None:
                    total += remote_changed
            return total
        return self.apply_write_wave_local(index, field, row_ids, column_ids, sets)

    def apply_write_wave_local(
        self, index: str, field: str, row_ids, column_ids, sets=None
    ) -> int:
        """Owner-side wave leg: on a gang leader the wave crosses the
        collective plane as ONE replayed frame (vs one broadcast per
        bit on the interactive path); every rank then applies the
        identical batch below."""
        mh = getattr(self.server, "multihost", None) if self.server else None
        # dispatch flag mirrors _gang_import: a federated gang replays
        # only local legs (pass local=True), a single-plane gang owns
        # the top-level wave (local=False)
        if mh is not None and mh.should_dispatch_import(mh.federated):
            from pilosa_tpu.parallel.multihost import Descriptor, KIND_WRITE_WAVE

            mh.dispatch(
                Descriptor(
                    KIND_WRITE_WAVE,
                    {
                        "index": index,
                        "field": field,
                        "row_ids": [int(r) for r in row_ids],
                        "column_ids": [int(c) for c in column_ids],
                        "sets": [bool(s) for s in sets] if sets is not None else None,
                    },
                ),
                deadline=deadline.current(),
            )
            return len(row_ids)
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        flags = sets if sets is not None else [True] * len(row_ids)
        groups: dict[int, list[int]] = {}
        for i, col in enumerate(column_ids):
            groups.setdefault(int(col) // SHARD_WIDTH, []).append(i)
        v = f.create_view_if_not_exists(VIEW_STANDARD)
        changed = 0
        for shard, idxs in sorted(groups.items()):
            frag = v.create_fragment_if_not_exists(shard)
            changed += frag.apply_bit_batch(
                [int(row_ids[i]) for i in idxs],
                [int(column_ids[i]) for i in idxs],
                [bool(flags[i]) for i in idxs],
            )
            # heat write hook lives in the local-apply leg, so gang
            # replay (every rank re-enters here with dispatch false)
            # records the wave exactly once per rank
            heat.record_write(index, field, shard, len(idxs))
        return changed

    def import_values(
        self,
        index: str,
        field: str,
        column_ids: list[int],
        values: list[int],
        column_keys: Optional[list[str]] = None,
    ) -> None:
        self._validate("import_value")
        if self._gang_import(
            "import_values",
            {
                "index": index,
                "field": field,
                "column_ids": list(column_ids),
                "values": list(values),
                "column_keys": list(column_keys) if column_keys else None,
            },
        ):
            return
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        ts = self.executor.translate_store
        if column_keys:
            if ts is None:
                raise APIError("translate store not configured")
            column_ids = ts.translate_columns_to_ids(index, column_keys)
        if self.cluster is not None and len(self.cluster.nodes) > 1:
            from pilosa_tpu import SHARD_WIDTH as SW

            groups: dict[int, list[int]] = {}
            for i, col in enumerate(column_ids):
                groups.setdefault(col // SW, []).append(i)
            for shard, idxs in sorted(groups.items()):
                cols = [column_ids[i] for i in idxs]
                vals = [values[i] for i in idxs]
                for node in self.cluster.shard_nodes(index, shard):
                    if node.id == self.cluster.node_id:
                        # through the local entry point, not f.import_values:
                        # on a federated gang leader the owner-side leg must
                        # replay through the gang so follower holders stay
                        # bit-identical (same as _route_import for bits)
                        self.import_values_local(index, field, cols, vals)
                    else:
                        self.cluster.client.import_values_local(
                            node.uri, index, field, cols, vals
                        )
            return
        f.import_values(column_ids, values)

    def import_values_local(self, index, field, column_ids, values):
        if self._gang_import(
            "import_values",
            {
                "index": index,
                "field": field,
                "column_ids": list(column_ids),
                "values": list(values),
                "local": True,
            },
            local=True,
        ):
            return
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        f.import_values(column_ids, values)

    # -- export (reference api.ExportCSV:328) --

    def export_csv(self, index: str, field: str, shard: int) -> bytes:
        """CSV bytes for one shard, "row,col\\n" lines (the reference's
        Go csv writer likewise emits bare \\n, http/handler.go
        handleGetExport) — both paths byte-identical so cross-node
        export diffs can't depend on whether the native library built."""
        self._validate("export_csv")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        frag = self.holder.fragment(index, field, VIEW_STANDARD, shard)
        if frag is None:
            return b""
        positions = np.asarray(frag.storage.slice_all(), dtype=np.uint64)
        if positions.size == 0:
            return b""
        rows = positions // np.uint64(SHARD_WIDTH)
        cols = np.uint64(frag.shard * SHARD_WIDTH) + (
            positions % np.uint64(SHARD_WIDTH)
        )
        # native formatter (inverse of the import parser); Python
        # fallback when the library isn't built
        from pilosa_tpu import native_bridge

        out = native_bridge.format_csv_pairs(rows, cols)
        if out is not None:
            return out
        return (
            "".join(f"{r},{c}\n" for r, c in zip(rows.tolist(), cols.tolist()))
        ).encode()

    # -- fragment sync endpoints (reference api.go:376-472) --

    def fragment_blocks(
        self, index: str, field: str, shard: int, view: str = VIEW_STANDARD
    ) -> list[dict]:
        self._validate("fragment_blocks")
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            raise NotFoundError("fragment not found")
        return [
            {"id": bid, "checksum": digest.hex()} for bid, digest in frag.blocks()
        ]

    def apply_block_fixes(
        self,
        index: str,
        field: str,
        view: str,
        shard: int,
        rows,
        columns,
        clear_rows,
        clear_columns,
    ) -> None:
        """Anti-entropy push target: apply a peer's consensus block merge
        to ANY view (time quantums, bsig_*) — the view-aware replacement
        for the reference's standard-only Set/Clear PQL push
        (reference fragment.go:1874 'Only sync the standard block')."""
        import numpy as np

        self._validate("import")
        fld = self.holder.field(index, field)
        if fld is None:
            raise NotFoundError(f"field not found: {field}")
        v = fld.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        frag.import_block_pairs(
            np.asarray(rows, dtype=np.uint64),
            np.asarray(columns, dtype=np.uint64),
            np.asarray(clear_rows, dtype=np.uint64),
            np.asarray(clear_columns, dtype=np.uint64),
        )

    def fragment_block_data(
        self, index: str, field: str, view: str, shard: int, block: int
    ) -> dict:
        self._validate("fragment_block_data")
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            raise NotFoundError("fragment not found")
        rows, cols = frag.block_data(block)
        return {"rows": rows.tolist(), "columns": cols.tolist()}

    def marshal_fragment(self, index: str, field: str, view: str, shard: int) -> bytes:
        """Fragment backup archive: a tar with "data" (roaring bytes),
        "cache" (protobuf id list), and "digest" (blake2b-128 hex of
        the data entry) entries, the reference's WriteTo format
        (fragment.go:1511-1568) extended with the checksum the restore
        side verifies before applying. A quarantined fragment refuses
        (503): its bits are poisoned and must not propagate to peers."""
        import hashlib
        import io
        import tarfile

        self._validate("fragment_data")
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            raise NotFoundError("fragment not found")
        frag.check_serving()
        from pilosa_tpu.core.cache import encode_cache

        with frag.mu:  # consistent (data, cache) snapshot under writers
            data = frag.storage.to_bytes()
            cbuf = encode_cache(frag.cache.ids())
        digest = hashlib.blake2b(data, digest_size=16).hexdigest().encode()
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w") as tw:
            for name, blob in (("data", data), ("cache", cbuf), ("digest", digest)):
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                info.mode = 0o600
                tw.addfile(info, io.BytesIO(blob))
        return out.getvalue()

    def unmarshal_fragment(
        self, index: str, field: str, view: str, shard: int, data: bytes
    ) -> None:
        """Restore a fragment from a tar archive (reference ReadFrom,
        fragment.go:1570-1681) or from raw roaring bytes (this
        framework's pre-tar wire format). The archive's checksum (the
        "digest" entry, when present) is verified and the bytes fully
        PARSED before the live fragment is touched — a corrupt backup
        can never clobber a healthy fragment mid-apply."""
        import hashlib
        import io
        import tarfile

        self._validate("fragment_data")
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        from pilosa_tpu.core.cache import decode_cache
        from pilosa_tpu.roaring import Bitmap

        cache_ids = None
        want_digest = None
        try:
            with tarfile.open(fileobj=io.BytesIO(data)) as tr:
                members = {m.name: m for m in tr.getmembers()}
                entry = members.get("data")
                blob = tr.extractfile(entry) if entry is not None else None
                if blob is None:
                    raise APIError("fragment archive has no 'data' entry")
                data = blob.read()
                centry = members.get("cache")
                cfile = tr.extractfile(centry) if centry is not None else None
                if cfile is not None:
                    cache_ids = decode_cache(cfile.read())
                dentry = members.get("digest")
                dfile = tr.extractfile(dentry) if dentry is not None else None
                if dfile is not None:
                    want_digest = dfile.read().decode("ascii", "replace").strip()
        except tarfile.ReadError:
            pass  # raw roaring bytes

        if want_digest is not None:
            got = hashlib.blake2b(data, digest_size=16).hexdigest()
            if got != want_digest:
                metrics.count(metrics.RESTORE_REFUSED)
                events.record(
                    events.RESTORE_REFUSED,
                    index=index,
                    field=field,
                    view=view,
                    shard=shard,
                    reason="fragment archive digest mismatch",
                )
                raise APIError(
                    "fragment archive checksum mismatch; restore refused",
                    status=400,
                )
        try:
            storage = Bitmap.unmarshal_binary(data)
        except Exception as e:
            metrics.count(metrics.RESTORE_REFUSED)
            events.record(
                events.RESTORE_REFUSED,
                index=index,
                field=field,
                view=view,
                shard=shard,
                reason="fragment archive unparseable",
            )
            raise APIError(
                f"fragment archive unparseable; restore refused: {e}",
                status=400,
            )
        self._replace_fragment_storage(frag, storage, cache_ids)

    def _replace_fragment_storage(self, frag, storage, cache_ids=None) -> None:
        """Swap a fragment's bitmap for an already-verified one and
        rebuild every derived structure. Clears any quarantine: the
        incoming storage passed verification, so this IS the repair."""
        with frag.mu:
            op_writer = frag.storage.op_writer
            frag.storage = storage
            frag.storage.op_writer = op_writer
            frag.generation += 1
            frag.quarantined = False
            frag.quarantine_reason = ""
            frag._delta_reset()  # wholesale replace: no replayable deltas
            frag._row_cache.clear()
            frag.checksums.clear()
            frag._occ = None
            frag._recompute_max_row_id()
            frag.cache.clear()
            if cache_ids is None:
                # raw-bytes restore carries no cache entry: rebuild from
                # the restored rows so TopN answers immediately
                cache_ids = frag.row_ids()
            for row_id in cache_ids:
                # already under frag.mu — use the unlocked row read
                frag.cache.bulk_add(
                    row_id, frag._unprotected_row(row_id).count()
                )
            frag.cache.invalidate()
            frag.snapshot()

    # -- holder backup / restore (ISSUE 15) --

    BACKUP_MANIFEST_VERSION = 1

    def backup(self) -> bytes:
        """Full-holder backup: a tar of the schema plus every fragment's
        roaring bytes, led by a MANIFEST.json naming every member with
        its blake2b-128 digest and size. The manifest is written FIRST
        so a restore can verify the whole archive before applying a
        byte. A quarantined fragment refuses the backup (503) — backing
        up known-poisoned bits would launder the corruption into the
        recovery path."""
        import hashlib
        import io
        import tarfile

        self._validate("fragment_data")
        entries: list[tuple[str, bytes]] = []
        schema_blob = json.dumps(self.holder.schema()).encode()
        entries.append(("schema.json", schema_blob))
        for iname, idx in self.holder.indexes.items():
            for fname, fld in idx.fields.items():
                for vname, view in fld.views.items():
                    for shard, frag in sorted(view.fragments.items()):
                        frag.check_serving()
                        with frag.mu:
                            data = frag.storage.to_bytes()
                        entries.append(
                            (f"fragments/{iname}/{fname}/{vname}/{shard}", data)
                        )
        # key translation logs ride along: a restored holder must
        # resolve exactly the archive's keys (translate/<store>.log
        # members; older restore targets verify-then-ignore unknown
        # prefixes, so the manifest version stays 1)
        ts = self.executor.translate_store
        if ts is not None and hasattr(ts, "store_files"):
            for name, blob in ts.store_files():
                entries.append((f"translate/{name}.log", blob))
        manifest = {
            "version": self.BACKUP_MANIFEST_VERSION,
            "entries": {
                name: {
                    "blake2b": hashlib.blake2b(blob, digest_size=16).hexdigest(),
                    "size": len(blob),
                }
                for name, blob in entries
            },
        }
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w") as tw:
            for name, blob in [
                ("MANIFEST.json", json.dumps(manifest, indent=1).encode())
            ] + entries:
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                info.mode = 0o600
                tw.addfile(info, io.BytesIO(blob))
        metrics.count(metrics.BACKUP_ARCHIVES)
        return out.getvalue()

    def restore(self, archive: bytes) -> dict:
        """Restore a holder backup. EVERYTHING is verified before
        ANYTHING is applied: the manifest must name exactly the members
        present, every blob must match its recorded digest and size,
        the schema must parse, and every fragment blob must parse as a
        roaring bitmap. Any failure refuses the whole restore (400)
        with the holder untouched."""
        import hashlib
        import io
        import tarfile

        self._validate("fragment_data")
        from pilosa_tpu.roaring import Bitmap
        from pilosa_tpu.translate.store import SpaceStore

        def refuse(reason: str) -> APIError:
            metrics.count(metrics.RESTORE_REFUSED)
            events.record(events.RESTORE_REFUSED, reason=reason)
            return APIError(f"{reason}; restore refused", status=400)

        try:
            with tarfile.open(fileobj=io.BytesIO(archive)) as tr:
                blobs = {}
                for m in tr.getmembers():
                    f = tr.extractfile(m)
                    if f is not None:
                        blobs[m.name] = f.read()
        except tarfile.ReadError:
            raise refuse("backup archive is not a tar")
        mblob = blobs.pop("MANIFEST.json", None)
        if mblob is None:
            raise refuse("backup archive has no MANIFEST.json")
        try:
            manifest = json.loads(mblob)
            version = manifest["version"]
            want = manifest["entries"]
        except Exception:
            raise refuse("backup manifest unparseable")
        if version != self.BACKUP_MANIFEST_VERSION:
            raise refuse(f"backup manifest version {version} unsupported")
        if set(want) != set(blobs):
            missing = sorted(set(want) - set(blobs))[:3]
            extra = sorted(set(blobs) - set(want))[:3]
            raise refuse(
                f"backup members diverge from manifest"
                f" (missing={missing} extra={extra})"
            )
        for name, meta in want.items():
            blob = blobs[name]
            if len(blob) != meta.get("size"):
                raise refuse(f"backup entry {name} size mismatch")
            got = hashlib.blake2b(blob, digest_size=16).hexdigest()
            if got != meta.get("blake2b"):
                raise refuse(f"backup entry {name} checksum mismatch")
        try:
            schema = json.loads(blobs["schema.json"])
        except Exception:
            raise refuse("backup schema.json unparseable")
        fragments = []
        for name, blob in blobs.items():
            if not name.startswith("fragments/"):
                continue
            parts = name.split("/")
            if len(parts) != 5 or not parts[4].isdigit():
                raise refuse(f"backup entry {name} has a malformed path")
            try:
                storage = Bitmap.unmarshal_binary(blob)
            except Exception:
                raise refuse(f"backup entry {name} unparseable")
            fragments.append((parts[1], parts[2], parts[3], int(parts[4]), storage))
        translate_blobs = {}
        ts = self.executor.translate_store
        for name, blob in blobs.items():
            if not name.startswith("translate/") or not name.endswith(".log"):
                continue
            store = name[len("translate/") : -len(".log")]
            if (
                "/" not in store
                or ".." in store
                or store.startswith(("/", "\\"))
            ):
                raise refuse(f"backup entry {name} has a malformed path")
            # a tampered translate log would silently rebind every key
            # written through it — every frame must verify (intact CRC
            # prefix covering the whole member), same
            # verify-everything-before-apply bar as fragments
            probe = SpaceStore(None, "probe")
            if probe._replay(blob) != len(blob):
                raise refuse(f"backup entry {name} unparseable")
            translate_blobs[store] = blob
        if translate_blobs and (ts is None or not hasattr(ts, "restore_stores")):
            raise refuse("backup has translate entries but no translate store")
        # -- verification complete: apply --
        self.holder.apply_schema(schema)
        for iname, fname, vname, shard, storage in fragments:
            fld = self.holder.field(iname, fname)
            view = fld.create_view_if_not_exists(vname)
            frag = view.create_fragment_if_not_exists(shard)
            self._replace_fragment_storage(frag, storage)
        if translate_blobs:
            # replace-all semantics WITHIN the translate plane: the
            # restored holder resolves exactly the archive's keys
            # (archives without translate members leave local stores
            # untouched, like fragments the archive doesn't name)
            ts.restore_stores(translate_blobs)
        metrics.count(metrics.RESTORE_APPLIED)
        if self.server is not None:
            self.server.send_sync({"type": "schema", "schema": schema})
        return {"fragments": len(fragments), "version": version}

    # -- caches --

    def recalculate_caches(self) -> None:
        self._validate("recalculate_caches")
        for idx in self.holder.indexes.values():
            for f in idx.fields.values():
                for v in f.views.values():
                    for frag in v.fragments.values():
                        frag.cache.recalculate()
        # rank reorders can change TopN candidate walks without any
        # fragment generation bump — cached TopN results are stale
        pc = getattr(self.executor, "plan_cache", None)
        if pc is not None:
            pc.epoch_reset()
        if self.server is not None:
            self.server.send_sync({"type": "recalculate-caches"})

    # -- info / status --

    def version(self) -> str:
        return __version__

    def info(self) -> dict:
        import os

        return {
            "shardWidth": SHARD_WIDTH,
            "cpuPhysicalCores": os.cpu_count(),
            "cpuLogicalCores": os.cpu_count(),
        }

    def state(self) -> str:
        return self._state()

    def status(self) -> dict:
        nodes = []
        if self.cluster is not None:
            nodes = [n.to_dict() for n in self.cluster.nodes]
        out = {
            "state": self._state(),
            "nodes": nodes,
            "localID": getattr(self.cluster, "node_id", "") if self.cluster else "",
        }
        # gang health (ISSUE 7 bugfix): a degraded gang was previously
        # indistinguishable from a healthy one on the public route
        mh = getattr(self.server, "multihost", None) if self.server else None
        if mh is not None:
            out["gang"] = mh.health()
        job = (
            self.cluster.resize_job_status()
            if self.cluster is not None and hasattr(self.cluster, "resize_job_status")
            else None
        )
        if job is not None:
            out["resizeJob"] = job
        integ = self._integrity_status()
        if integ:
            out["integrity"] = integ
        return out

    def _integrity_status(self) -> dict:
        """Quarantined fragments + scrub-unrecoverable records for
        /status — empty dict when the holder is healthy so the common
        path stays unchanged."""
        quarantined = []
        for iname, idx in self.holder.indexes.items():
            for fname, fld in idx.fields.items():
                for vname, view in fld.views.items():
                    for shard, frag in view.fragments.items():
                        if frag.quarantined:
                            quarantined.append(
                                {
                                    "index": iname,
                                    "field": fname,
                                    "view": vname,
                                    "shard": shard,
                                    "reason": frag.quarantine_reason,
                                }
                            )
        out: dict = {}
        if quarantined:
            out["quarantined"] = quarantined
        scrubber = getattr(self.server, "scrubber", None) if self.server else None
        if scrubber is not None:
            unrec = scrubber.unrecoverable_list()
            if unrec:
                out["unrecoverable"] = unrec
        return out

    def hosts(self) -> list[dict]:
        if self.cluster is None:
            return []
        return [n.to_dict() for n in self.cluster.nodes]

    def shard_nodes(self, index: str, shard: int) -> list[dict]:
        self._validate("shard_nodes")
        if self.cluster is None:
            return []
        return [n.to_dict() for n in self.cluster.shard_nodes(index, shard)]

    def max_shards(self) -> dict[str, int]:
        return {
            name: idx.max_shard() for name, idx in self.holder.indexes.items()
        }

    # -- cluster ops (wired by the cluster layer) --

    def cluster_message(self, msg: dict) -> None:
        if self.server is None:
            raise APIError("cluster not configured")
        self.server.receive_message(msg)

    def gang_apply(self, kind: int, payload: dict, epoch: int) -> None:
        """Replicated-mode gang follower: apply one epoch-stamped
        descriptor pushed by the gang leader (parallel/federation.py)."""
        if self.server is None:
            raise APIError("gang not configured")
        self.server.gang_apply(kind, payload, epoch)

    def gang_rejoin(self, follower_uri: str) -> dict:
        """Gang leader: re-form the gang around a re-staged follower;
        returns the post-re-form health block (new epoch included)."""
        if self.server is None:
            raise APIError("gang not configured")
        return self.server.gang_rejoin(follower_uri)

    def set_coordinator(self, node_id: str) -> None:
        self._validate("set_coordinator")
        if self.cluster is None:
            raise APIError("cluster not configured")
        self.cluster.set_coordinator(node_id)

    def remove_node(self, node_id: str) -> None:
        self._validate("remove_node")
        if self.cluster is None:
            raise APIError("cluster not configured")
        self.cluster.remove_node(node_id)

    def resize_abort(self) -> None:
        if self.cluster is None:
            raise APIError("cluster not configured")
        self.cluster.resize_abort()

    def column_attr_diff(self, index: str, blocks: list) -> dict:
        """Return column attrs for blocks that differ from the caller's
        checksums (reference api.go attr-diff path / holder.go:654-740)."""
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        store = idx.column_attrs
        if store is None:
            return {}
        theirs = [(b[0], bytes.fromhex(b[1])) for b in blocks]
        mine = store.blocks()
        their_map = dict(theirs)
        out = {}
        for bid, digest in mine:
            if their_map.get(bid) != digest:
                out.update(store.block_data(bid))
        return {str(k): v for k, v in out.items()}

    def row_attr_diff(self, index: str, field: str, blocks: list) -> dict:
        f = self.holder.field(index, field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        store = f.row_attr_store
        if store is None:
            return {}
        theirs = dict((b[0], bytes.fromhex(b[1])) for b in blocks)
        out = {}
        for bid, digest in store.blocks():
            if theirs.get(bid) != digest:
                out.update(store.block_data(bid))
        return {str(k): v for k, v in out.items()}

    def probe_node(self, uri: str) -> bool:
        """Probe ``uri``'s /status with the cluster's short probe
        timeout; the relay half of SWIM indirect probing. Only URIs
        belonging to known cluster members are probed — the reference's
        memberlist ping-req likewise only targets members — so the
        endpoint cannot be used as an open relay into arbitrary
        internal addresses (SSRF)."""
        if self.cluster is None:
            return False
        from pilosa_tpu.utils.uri import same_endpoint

        with self.cluster.mu:
            known = any(
                same_endpoint(n.uri, uri) for n in self.cluster.nodes
            )
        if not known:
            return False
        try:
            self.cluster._probe_client.status(uri)
            return True
        except Exception:
            return False

    def get_translate_data(self, offset: int, store: str = "") -> bytes:
        ts = self.executor.translate_store
        if ts is None:
            raise APIError("translate store not configured")
        if store:
            try:
                return ts.read_store(store, offset)
            except ValueError as e:
                raise APIError(str(e), status=400)
        data, _ = ts.read_from(offset)
        return data

    def translate_stores(self) -> list:
        """Durable translate stores with byte offsets — what a peer
        polls to pull-replicate key assignments."""
        ts = self.executor.translate_store
        if ts is None:
            raise APIError("translate store not configured")
        return ts.stores()

    def translate_debug(self) -> dict:
        ts = self.executor.translate_store
        if ts is None:
            return {"enabled": False}
        out = ts.stats()
        out["enabled"] = True
        return out

    def translate_ingest_keys(
        self, index: str, field: str, row_keys, column_keys
    ) -> tuple:
        """Keyed-ingest resolution: translate the batch's key lists to
        id lists BEFORE the ingest queue sees it, so write waves (and
        their routed local legs) carry integer ids only. One translate
        batch per ingest wave — assignments group-commit with one
        fsync per store touched."""
        ts = self.executor.translate_store
        if ts is None:
            raise APIError("translate store not configured")
        rows = cols = None
        if column_keys:
            cols = ts.translate_columns_to_ids(
                index, [str(k) for k in column_keys]
            )
        if row_keys:
            rows = ts.translate_rows_to_ids(
                index, field, [str(k) for k in row_keys]
            )
        return rows, cols

    def translate_keys(self, index: str, field: str, keys: list) -> list:
        """Mint (or look up) ids for keys — the federated-forward
        target; this node must OWN every key space the batch touches.
        Mints LOCALLY unconditionally (never re-forwards — see
        Translator.mint).

        When this node's OWN ownership resolution names a different
        owner for any key, the request is rejected with 409: minting
        here would permanently fork the cluster's id space (each mint
        is durable in the local log). The bind-vs-advertise case — an
        owner's advertised name differing from its bind address — is
        handled inside ``Server._translate_owner`` via URI equivalence
        + DNS resolution (``Server._is_self``), NOT via anything
        request-controlled: a client-supplied header must never be
        able to open the mint gate on a non-owner."""
        ts = self.executor.translate_store
        if ts is None:
            raise APIError("translate store not configured")
        keys = [str(k) for k in keys]
        check = getattr(ts, "misowned", None)
        if check is not None:
            owner = check(index, field, keys)
        elif self.server is not None:
            owner = self.server.translate_primary()
        else:
            owner = ""
        if owner:
            raise APIError(
                f"not the owner of these keys (owner={owner}); minting "
                "here would fork the cluster id space — post to the "
                "owner or fix translate-primary-url",
                status=409,
            )
        return ts.mint(index, field, keys)


def _parse_timestamps(timestamps):
    if not timestamps or not any(t for t in timestamps):
        return None
    from datetime import datetime

    return [
        datetime.fromtimestamp(t) if isinstance(t, (int, float)) and t else None
        for t in timestamps
    ]
