"""Background integrity scrubber (ISSUE 15) — a low-priority server
loop that sweeps every locally-owned fragment verifying its on-disk
bytes: the snapshot's blake2b digest trailer, a CRC walk of the op-log
tail, and (deep mode) a full re-parse of the file compared block-by-
block against the in-memory bitmap.

Bit rot is the failure the durability work (ISSUE 11) can't see: fsync
told the truth at write time, then the medium lied later. Waiting for
a query to trip over a rotted page means serving wrong answers in the
meantime; the scrubber finds the rot first, quarantines the fragment
(reads fail with a clean 503 instead of garbage), and repairs it by
pulling a verified copy from a healthy replica over the fragment-backup
plane. Fragments with no healthy source are journaled and surfaced in
``/status`` under ``integrity.unrecoverable`` — loud, not silent.

The sweep is throttled (``scrub-throttle`` seconds between fragments)
so a big holder scrubs in the background without starving queries.
``GET /debug/scrub`` reports the stats below; ``POST /debug/scrub``
runs a synchronous sweep (operator "scrub now").
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.utils import events, metrics


class Scrubber:
    """One per server; owns sweep state and the unrecoverable record."""

    def __init__(self, server) -> None:
        self.server = server
        cfg = server.config
        self.interval = float(getattr(cfg, "scrub_interval", 300.0))
        self.throttle = float(getattr(cfg, "scrub_throttle", 0.05))
        self.deep = bool(getattr(cfg, "scrub_deep", True))
        self.repair = bool(getattr(cfg, "scrub_repair", True))
        self._mu = threading.Lock()
        # (index, field, view, shard) -> record dict; cleared on repair
        self._unrecoverable: dict = {}
        self.sweeps = 0
        self.fragments_scanned = 0
        self.corruptions = 0
        self.repairs = 0
        self.last_sweep_seconds = 0.0
        self.last_sweep_at = 0.0

    # -- sweep ----------------------------------------------------------

    def sweep(self, index: str = "", repair=None) -> dict:
        """One full pass over the local holder (or one index when
        ``index`` is given — the operator's scoped "scrub now").
        ``repair`` overrides the configured scrub-repair for this sweep
        (False = detect-and-quarantine only, e.g. to survey damage
        before pulling replica copies). Returns a summary dict (also
        what POST /debug/scrub responds with)."""
        do_repair = self.repair if repair is None else bool(repair)
        start = time.monotonic()
        scanned = corrupt = repaired = unrecoverable = 0
        only = index
        for index, field, view, shard, frag in self._local_fragments(only):
            scanned += 1
            if frag.quarantined:
                # found corrupt earlier (open-time check or a previous
                # sweep) and still unrepaired — retry the repair only
                reason = frag.quarantine_reason
            else:
                reason = frag.verify_integrity(deep=self.deep)
                if reason is not None:
                    corrupt += 1
                    metrics.count(
                        metrics.SCRUB_CORRUPTIONS,
                        reason=reason.split(" at ")[0],
                    )
                    events.record(
                        events.SCRUB_CORRUPTION,
                        index=index,
                        field=field,
                        view=view,
                        shard=shard,
                        reason=reason,
                    )
            if reason is not None and do_repair:
                if self._repair(index, field, view, shard, frag, reason):
                    repaired += 1
                else:
                    unrecoverable += 1
            if self.throttle > 0:
                closed = getattr(self.server, "_closed", None)
                if closed is not None and closed.wait(self.throttle):
                    break
                if closed is None:
                    time.sleep(self.throttle)
        elapsed = time.monotonic() - start
        with self._mu:
            self.sweeps += 1
            self.fragments_scanned += scanned
            self.corruptions += corrupt
            self.repairs += repaired
            self.last_sweep_seconds = elapsed
            self.last_sweep_at = time.time()
        metrics.count(metrics.SCRUB_SWEEPS)
        metrics.count(metrics.SCRUB_FRAGMENTS_SCANNED, scanned)
        metrics.observe(metrics.SCRUB_SWEEP_SECONDS, elapsed)
        return {
            "scanned": scanned,
            "corrupt": corrupt,
            "repaired": repaired,
            "unrecoverable": unrecoverable,
            "seconds": elapsed,
        }

    def _local_fragments(self, index: str = ""):
        holder = self.server.holder
        cluster = getattr(self.server, "cluster", None)
        for iname, idx in list(holder.indexes.items()):
            if index and iname != index:
                continue
            for fname, fld in list(idx.fields.items()):
                for vname, view in list(fld.views.items()):
                    for shard, frag in sorted(view.fragments.items()):
                        if not frag.path:
                            continue  # in-memory fragment: nothing on disk
                        if cluster is not None and not cluster.owns_shard(
                            iname, shard
                        ):
                            continue
                        yield iname, fname, vname, shard, frag

    def _repair(self, index, field, view, shard, frag, reason) -> bool:
        key = (index, field, view, shard)
        cluster = getattr(self.server, "cluster", None)
        ok = False
        if cluster is not None:
            try:
                ok = cluster.repair_fragment(index, field, view, shard)
            except Exception as e:
                self.server.logger.printf(
                    "scrub repair %s/%s/%s/%s failed: %s",
                    index, field, view, shard, e,
                )
        if ok:
            metrics.count(metrics.SCRUB_REPAIRS)
            events.record(
                events.SCRUB_REPAIR,
                index=index,
                field=field,
                view=view,
                shard=shard,
                reason=reason,
            )
            with self._mu:
                self._unrecoverable.pop(key, None)
            return True
        metrics.count(metrics.SCRUB_UNRECOVERABLE)
        events.record(
            events.SCRUB_UNRECOVERABLE,
            index=index,
            field=field,
            view=view,
            shard=shard,
            reason=reason,
        )
        with self._mu:
            self._unrecoverable[key] = {
                "index": index,
                "field": field,
                "view": view,
                "shard": shard,
                "reason": reason,
                "since": self._unrecoverable.get(key, {}).get(
                    "since", time.time()
                ),
            }
        return False

    # -- introspection --------------------------------------------------

    def unrecoverable_list(self) -> list[dict]:
        with self._mu:
            return [dict(v) for _, v in sorted(self._unrecoverable.items())]

    def stats(self) -> dict:
        with self._mu:
            return {
                "interval": self.interval,
                "throttle": self.throttle,
                "deep": self.deep,
                "repair": self.repair,
                "sweeps": self.sweeps,
                "fragmentsScanned": self.fragments_scanned,
                "corruptions": self.corruptions,
                "repairs": self.repairs,
                "lastSweepSeconds": self.last_sweep_seconds,
                "lastSweepAt": self.last_sweep_at,
                "unrecoverable": [
                    dict(v) for _, v in sorted(self._unrecoverable.items())
                ],
            }
