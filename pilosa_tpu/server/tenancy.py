"""Multi-tenant QoS (ISSUE 19) — the index as the unit of isolation.

ROADMAP item 3 ("millions of users for real") needs more than key
translation: every index shares the pipeline class queues, the
HbmGovernor budgets by *subsystem* (stager / plan cache / scratch), and
one abusive dashboard can starve everyone's interactive p50. This module
is the policy layer that closes that gap; the mechanisms live where the
resources live and take their tenant policy from here:

* **Admission** (`TenancyManager.admit`): a token bucket per tenant —
  sustained rate from ``tenant-qps`` (explicit, else the default rate
  scaled by the tenant's weight) — plus an in-flight byte cap from
  ``tenant-inflight-bytes``. An exhausted tenant gets a clean
  ``TenantThrottled`` (HTTP 429 + an accurate ``Retry-After`` computed
  from its own refill rate) instead of a global ``Overloaded``: the
  abuser backs off, everyone else never notices. Internal legs of
  distributed queries are exempt — the origin node already charged the
  owning tenant, and throttling the cluster data plane mid-query would
  turn one tenant's burst into fleet-wide 500s.

* **Scheduling** (``weight``): each pipeline class queue dequeues
  weighted-fair across tenants (virtual-time WFQ, server/pipeline.py
  ``_TenantFairQueue``) using the weights configured here
  (``tenant-weights``, Ghodsi-style dominant-resource shares collapsed
  to one dimension — queue slots). A tenant's burst queues behind its
  own weight, not the fleet.

* **Memory** (``hbm_quota`` / ``over_hbm_quota``): per-tenant byte
  quotas enforced as HbmGovernor *sub-tenant* accounts — stager, T1,
  and device-plan-cache charges carry the index, relief sweeps prefer
  over-quota tenants first, and a tenant at quota degrades only its own
  queries (its blocks are the first evicted, including by its own
  inserts).

* **Attribution** (``slo_objectives`` + ``observe``): per-tenant SLO
  objectives (``tenant-objectives``) registered into the process
  ``slo.MONITOR`` under ``tenant:<index>`` keys so burn alerts and the
  existing gauge tick export per-tenant burn state through ``/metrics``
  and the fleet scrape; latency waterfalls grow a tenant dimension in
  utils/profiler.py.

Default config (no tenant keys set) must cost nothing and change
nothing: ``TenancyManager.enabled`` is False, ``admit`` returns without
taking a lock, and the pipeline keeps plain FIFO order — the gauntlet
stays bit-identical single-tenant.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from pilosa_tpu.server.pipeline import CLASS_INTERNAL, Overloaded
from pilosa_tpu.utils import metrics
from pilosa_tpu.utils import slo as slo_mod

# objectives registered into the shared SLOMonitor use this prefix so
# per-tenant burn state coexists with the per-class objectives in one
# monitor (one tick, one scrape) without key collisions
TENANT_SLO_PREFIX = "tenant:"

# weights below this are clamped: a zero/negative weight would starve a
# tenant forever (and divide by zero in the WFQ virtual-time arithmetic)
MIN_WEIGHT = 1e-3


class TenantThrottled(Overloaded):
    """Per-tenant admission refused: the tenant's own token bucket (or
    in-flight byte cap) is exhausted. Always HTTP 429 with a
    ``Retry-After`` derived from the tenant's refill rate — distinct
    from a genuinely overloaded server (``Overloaded`` status 503), so
    well-behaved clients back off per-tenant while the rest of the
    fleet sees no error at all."""

    def __init__(self, message: str, retry_after: float = 1.0) -> None:
        super().__init__(message, retry_after=retry_after, status=429)


def parse_tenant_map(spec: str) -> tuple[dict[str, float], Optional[float]]:
    """``index=value[,...]`` → ({index: value}, default). The ``*`` key
    sets the default applied to unlisted tenants. Malformed entries are
    skipped — a telemetry/QoS knob must not fail the boot."""
    out: dict[str, float] = {}
    default: Optional[float] = None
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, rhs = part.partition("=")
        try:
            val = float(rhs.strip())
        except ValueError:
            continue
        if val < 0:
            continue
        name = name.strip()
        if name == "*":
            default = val
        elif name:
            out[name] = val
    return out, default


def parse_tenant_objectives(spec: str) -> tuple[dict, Optional[tuple]]:
    """``index=latency_ms@target[,...]`` → ({index: (latency_s,
    target)}, default-or-None). Same grammar as slo.parse_objectives
    plus the ``*`` default key."""
    parsed = slo_mod.parse_objectives(spec) if (spec or "").strip() else {}
    default = parsed.pop("*", None)
    return parsed, default


class _Bucket:
    """One tenant's admission state: a token bucket (qps) plus an
    in-flight byte ledger. Mutated under the manager lock only."""

    __slots__ = ("tokens", "t_refill", "inflight_bytes", "throttled", "admitted")

    def __init__(self, burst: float) -> None:
        self.tokens = burst
        self.t_refill = time.monotonic()
        self.inflight_bytes = 0
        self.throttled = 0
        self.admitted = 0


class TenancyManager:
    """Per-index QoS policy: weights, admission buckets, HBM quotas,
    SLO objectives. One instance per server, threaded into the pipeline
    (scheduling + admission), the HBM governor (quotas), and the
    handler (attribution)."""

    def __init__(
        self,
        weights: str = "",
        qps: str = "",
        hbm_quota: str = "",
        inflight_bytes: str = "",
        objectives: str = "",
        default_qps: float = 0.0,
        burst_s: float = 2.0,
    ) -> None:
        self._weights, wdef = parse_tenant_map(weights)
        self.default_weight = max(MIN_WEIGHT, wdef if wdef is not None else 1.0)
        self._weights = {
            k: max(MIN_WEIGHT, v) for k, v in self._weights.items()
        }
        self._qps, qdef = parse_tenant_map(qps)
        # unlisted tenants: explicit * default, else the global default
        # rate scaled by the tenant's weight (0 = no rate limit)
        self.default_qps = qdef if qdef is not None else float(default_qps)
        self._quotas_f, quota_def = parse_tenant_map(hbm_quota)
        self.default_hbm_quota = int(quota_def) if quota_def else 0
        self._inflight, idef = parse_tenant_map(inflight_bytes)
        self.default_inflight_bytes = int(idef) if idef else 0
        self.tenant_objectives, self.default_objective = (
            parse_tenant_objectives(objectives)
        )
        # a burst of ``burst_s`` seconds at the sustained rate: absorbs
        # a dashboard redraw without tripping, still bounds the abuser
        self.burst_s = float(burst_s)
        # enabled only when some per-tenant policy is configured — the
        # single-tenant default must stay a zero-cost passthrough
        self.enabled = bool(
            self._weights
            or wdef is not None
            or self._qps
            or self.default_qps > 0
            or self._quotas_f
            or self.default_hbm_quota
            or self._inflight
            or self.default_inflight_bytes
            or self.tenant_objectives
            or self.default_objective is not None
        )
        self._mu = threading.Lock()
        self._buckets: dict[str, _Bucket] = {}

    # -- scheduling weight ----------------------------------------------------

    def weight(self, index: str) -> float:
        return self._weights.get(index, self.default_weight)

    # -- HBM quota ------------------------------------------------------------

    def hbm_quota(self, index: str) -> int:
        """Byte quota for one tenant's total HBM-domain footprint
        (stager blocks + device plan cache). 0 = unlimited."""
        q = self._quotas_f.get(index)
        return int(q) if q is not None else self.default_hbm_quota

    def hbm_quotas(self) -> dict[str, int]:
        return {k: int(v) for k, v in self._quotas_f.items()}

    # -- admission ------------------------------------------------------------

    def _rate(self, index: str) -> float:
        r = self._qps.get(index)
        if r is not None:
            return r
        if self.default_qps <= 0:
            return 0.0
        return self.default_qps * (self.weight(index) / self.default_weight)

    def _inflight_limit(self, index: str) -> int:
        lim = self._inflight.get(index)
        return int(lim) if lim is not None else self.default_inflight_bytes

    def admit(self, index: str, cls: str, nbytes: int = 0) -> None:
        """Charge one request against ``index``'s bucket; raises
        ``TenantThrottled`` (HTTP 429) when the tenant is over its own
        rate or byte cap. Internal legs are exempt (see module doc).
        Every admit must be paired with ``release`` — the pipeline does
        this in ``submit``'s finally."""
        if not self.enabled or cls == CLASS_INTERNAL:
            return
        rate = self._rate(index)
        limit = self._inflight_limit(index)
        if rate <= 0 and limit <= 0:
            return
        now = time.monotonic()
        with self._mu:
            b = self._buckets.get(index)
            if b is None:
                b = self._buckets[index] = _Bucket(
                    burst=max(1.0, rate * self.burst_s)
                )
            if rate > 0:
                burst = max(1.0, rate * self.burst_s)
                b.tokens = min(burst, b.tokens + (now - b.t_refill) * rate)
                b.t_refill = now
                if b.tokens < 1.0:
                    b.throttled += 1
                    retry = (1.0 - b.tokens) / rate
                    metrics.count(
                        metrics.TENANT_THROTTLED, tenant=index, reason="qps"
                    )
                    raise TenantThrottled(
                        f"tenant {index!r} over its query rate "
                        f"({rate:g}/s); retry later",
                        retry_after=max(0.001, retry),
                    )
            if limit > 0 and nbytes > 0 and (
                b.inflight_bytes + nbytes > limit and b.inflight_bytes > 0
            ):
                b.throttled += 1
                metrics.count(
                    metrics.TENANT_THROTTLED, tenant=index, reason="bytes"
                )
                raise TenantThrottled(
                    f"tenant {index!r} over its in-flight byte cap "
                    f"({b.inflight_bytes}/{limit}); retry later",
                    retry_after=0.05,
                )
            if rate > 0:
                b.tokens -= 1.0
            b.inflight_bytes += int(nbytes)
            b.admitted += 1
            inflight = b.inflight_bytes
        metrics.gauge(metrics.TENANT_INFLIGHT_BYTES, inflight, tenant=index)

    def release(self, index: str, cls: str, nbytes: int = 0) -> None:
        if not self.enabled or cls == CLASS_INTERNAL or nbytes <= 0:
            return
        with self._mu:
            b = self._buckets.get(index)
            if b is None:
                return
            b.inflight_bytes = max(0, b.inflight_bytes - int(nbytes))
            inflight = b.inflight_bytes
        metrics.gauge(metrics.TENANT_INFLIGHT_BYTES, inflight, tenant=index)

    # -- SLO attribution ------------------------------------------------------

    def slo_objectives(self) -> dict:
        """Objectives to merge into the process SLOMonitor, keyed
        ``tenant:<index>``. Explicitly listed tenants only — tenants
        covered by the ``*`` default are registered lazily on first
        ``observe`` (their names are not known at boot)."""
        return {
            TENANT_SLO_PREFIX + idx: obj
            for idx, obj in self.tenant_objectives.items()
        }

    def observe(self, index: str, duration_s: float, ok: bool) -> None:
        """Record one served query against the tenant's SLO objective
        (lazily registering ``*``-default tenants) and its latency
        summary metric."""
        if not self.enabled or not index:
            return
        key = TENANT_SLO_PREFIX + index
        mon = slo_mod.MONITOR
        if not mon.has_class(key):
            obj = self.tenant_objectives.get(index) or self.default_objective
            if obj is None:
                return
            mon.ensure_class(key, obj)
        mon.record(key, duration_s, ok=ok)

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            buckets = {
                idx: {
                    "admitted": b.admitted,
                    "throttled": b.throttled,
                    "inflight_bytes": b.inflight_bytes,
                    "tokens": round(b.tokens, 3),
                }
                for idx, b in self._buckets.items()
            }
        known = set(self._weights) | set(self._qps) | set(buckets)
        return {
            "enabled": self.enabled,
            "default_weight": self.default_weight,
            "default_qps": self.default_qps,
            "default_hbm_quota": self.default_hbm_quota,
            "tenants": {
                idx: {
                    "weight": self.weight(idx),
                    "qps": self._rate(idx),
                    "hbm_quota": self.hbm_quota(idx),
                    **buckets.get(idx, {}),
                }
                for idx in sorted(known)
            },
        }
