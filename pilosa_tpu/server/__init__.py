"""API + HTTP + server runtime (L6/L7)."""

from pilosa_tpu.server.api import API, APIError, NotFoundError
from pilosa_tpu.server.config import ClusterConfig, Config, TLSConfig
from pilosa_tpu.server.deadline import Deadline, DeadlineExceeded
from pilosa_tpu.server.http_handler import Handler, encode_result, make_http_server
from pilosa_tpu.server.pipeline import Overloaded, QueryPipeline
from pilosa_tpu.server.server import Server

__all__ = [
    "API",
    "APIError",
    "ClusterConfig",
    "TLSConfig",
    "Config",
    "Deadline",
    "DeadlineExceeded",
    "Handler",
    "NotFoundError",
    "Overloaded",
    "QueryPipeline",
    "Server",
    "encode_result",
    "make_http_server",
]
