"""Fleet telemetry collector (ISSUE 10) — the pull plane behind
``/metrics?fleet=true`` and ``/debug/fleet``.

Every server owns a collector; only the one on a gang/federation leader
ever accumulates members. Gang followers announce their scrape endpoint
at boot (POST ``/internal/fleet/register``, triggered by the leader-URI
handshake in server.py), and each registered member answers
``GET /internal/fleet/snapshots`` with its gang-local snapshot list —
its own registry plus its OWN registered members'. A federation leader
therefore aggregates the whole fleet in two hops: its own gang list,
plus one pull per peer gang leader on the cluster plane (each of which
returns that gang's list). Every snapshot carries an ``instance`` label
(the member's URI) in the rendered exposition, so per-rank series stay
distinct in the aggregate.

Scrape failures are per-member: an unreachable rank costs its series
and a ``fleet.scrapes{outcome=error}`` count, never the whole scrape.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from pilosa_tpu.utils import metrics

# per-member pull budget: a wedged rank must not stall the scrape for
# longer than a Prometheus scrape interval tolerates
_PULL_TIMEOUT = 5.0


class FleetCollector:
    def __init__(self, server) -> None:
        self.server = server
        self._mu = threading.Lock()
        # uri -> {"uri","rank","gang","registered_at"}
        self._members: dict[str, dict] = {}
        # uri -> last pull outcome {"ok","error","t"} for /debug/fleet
        self._pulls: dict[str, dict] = {}
        self._client = None

    # -- membership ----------------------------------------------------------

    def register(self, uri: str, rank: int = -1, gang: str = "") -> None:
        """Idempotent: a re-registering member (restart, rejoin) just
        refreshes its row."""
        with self._mu:
            self._members[uri] = {
                "uri": uri,
                "rank": rank,
                "gang": gang,
                "registered_at": time.time(),
            }

    def members(self) -> list[dict]:
        with self._mu:
            return [dict(m) for m in self._members.values()]

    # -- snapshots -----------------------------------------------------------

    def local_label(self) -> str:
        import os

        return getattr(self.server, "uri", "") or f"pid:{os.getpid()}"

    def local_snapshot(self) -> dict:
        """This process's registry merged with its expvar stats — the
        same two sources the plain ``/metrics`` exposition renders."""
        snap = dict(metrics.snapshot())
        ev = getattr(self.server, "_expvar", None)
        if ev is not None:
            for k, v in ev.snapshot().items():
                snap.setdefault(k, v)
        return snap

    def _get_client(self):
        if self._client is None:
            from pilosa_tpu.parallel.client import InternalClient

            self._client = InternalClient(
                timeout=_PULL_TIMEOUT,
                ssl_context=self.server.client_ssl_context(),
            )
        return self._client

    def _pull(self, uri: str) -> list:
        """One member's gang-local snapshot list; failures are recorded
        and return empty (the scrape degrades, never dies)."""
        try:
            out = self._get_client().fleet_snapshots(uri)
            metrics.count(metrics.FLEET_SCRAPES, outcome="ok")
            with self._mu:
                self._pulls[uri] = {"ok": True, "error": "", "t": time.time()}
            return out
        except Exception as e:
            metrics.count(metrics.FLEET_SCRAPES, outcome="error")
            with self._mu:
                self._pulls[uri] = {"ok": False, "error": str(e), "t": time.time()}
            return []

    def gang_snapshots(self) -> list:
        """``[[label, snapshot], ...]`` for this process and every
        member registered here (its gang, when this is a gang leader)."""
        out = [[self.local_label(), self.local_snapshot()]]
        for m in self.members():
            out.extend(self._pull(m["uri"]))
        return out

    def collect(self) -> list:
        """The full fleet: this gang plus one pull per peer gang leader
        on the cluster plane, deduped by instance label (a peer list
        can overlap its own registration)."""
        pairs = list(self.gang_snapshots())
        cluster = getattr(self.server, "cluster", None)
        if cluster is not None:
            for node in cluster._other_nodes():
                pairs.extend(self._pull(node.uri))
        seen: set = set()
        out = []
        for pair in pairs:
            try:
                label, snap = pair[0], pair[1]
            except (IndexError, TypeError):
                continue
            if label in seen or not isinstance(snap, dict):
                continue
            seen.add(label)
            out.append((label, snap))
        return out

    # -- workload heat (ISSUE 16) -------------------------------------------

    def _pull_heat(self, uri: str) -> list:
        """One member's gang-local heat list; same per-member failure
        isolation (and fleet.scrapes accounting) as the metric pull."""
        try:
            out = self._get_client().fleet_heat(uri)
            metrics.count(metrics.FLEET_SCRAPES, outcome="ok")
            with self._mu:
                self._pulls[uri] = {"ok": True, "error": "", "t": time.time()}
            return out
        except Exception as e:
            metrics.count(metrics.FLEET_SCRAPES, outcome="error")
            with self._mu:
                self._pulls[uri] = {"ok": False, "error": str(e), "t": time.time()}
            return []

    def gang_heat(self) -> list:
        """``[[label, heat-snapshot], ...]`` for this process and every
        member registered here. Raw counters only (dim-agnostic): the
        aggregating caller picks the ranking dimension."""
        from pilosa_tpu.utils import heat

        out = [[self.local_label(), heat.snapshot()]]
        for m in self.members():
            out.extend(self._pull_heat(m["uri"]))
        return out

    def collect_heat(self) -> list:
        """Fleet-wide ``[(label, heat-snapshot), ...]`` — this gang plus
        one pull per peer gang leader, deduped by instance label."""
        pairs = list(self.gang_heat())
        cluster = getattr(self.server, "cluster", None)
        if cluster is not None:
            for node in cluster._other_nodes():
                pairs.extend(self._pull_heat(node.uri))
        seen: set = set()
        out = []
        for pair in pairs:
            try:
                label, snap = pair[0], pair[1]
            except (IndexError, TypeError):
                continue
            if label in seen or not isinstance(snap, dict):
                continue
            seen.add(label)
            out.append((label, snap))
        return out

    def debug(self) -> dict:
        with self._mu:
            pulls = {u: dict(p) for u, p in self._pulls.items()}
        return {
            "self": self.local_label(),
            "members": self.members(),
            "pulls": pulls,
        }
