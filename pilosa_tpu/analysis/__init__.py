"""Invariant checker: static AST lints + dynamic lock-order detection.

``pilosa_tpu check [--strict] [paths…]`` runs the static half; the
dynamic half rides along wherever ``OrderedLock`` replaced a raw
``threading.Lock`` (dispatch engine, pipeline, stager, plan cache,
multihost gang lifecycle). See docs/development.md for the rule
catalog and suppression syntax.
"""

from pilosa_tpu.analysis.lint import (  # noqa: F401
    Finding,
    RULES,
    check_paths,
    check_source,
)
from pilosa_tpu.analysis.locks import (  # noqa: F401
    GRAPH,
    LockGraph,
    LockOrderError,
    OrderedLock,
    held_locks,
    strict_mode,
)
