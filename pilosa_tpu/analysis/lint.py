"""Static invariant checker — project-specific AST lints.

The correctness of this codebase rests on conventions that no
general-purpose tool knows about: the PR 5/6 gang determinism contract,
owner-side write legs replaying through ``*_local`` entry points (PR 6
shipped with ``import_values`` silently bypassing gang replay — a bug
class only a dryrun caught), jit purity, donated-buffer non-reuse, and
lock discipline across ten-plus mutex-holding modules. Engler et al.'s
"deviant behavior" observation applies directly: each convention is a
mechanically checkable pattern, so this module checks them on every CI
run instead of relying on review memory.

Rules (ids are what ``# check: disable=<rule>`` names):

* ``lock-discipline`` — no blocking calls (future ``.result()``,
  ``block_until_ready``, socket/HTTP I/O, ``time.sleep``, event waits,
  thread joins, device transfers) inside a ``with <lock>:`` body; and
  no call to a same-class method that re-acquires the lock already
  held (static self-deadlock — the dynamic detector's
  ``LockOrderError`` shape, caught at lint time).
* ``lock-wrapper`` — module-level locks, and every lock in the
  instrumented modules (dispatch engine, pipeline, stager, plan cache,
  multihost lifecycle), must be ``analysis.locks.OrderedLock`` so the
  lock graph sees them.
* ``gang-routing`` — inside a cluster owner-routing loop
  (``for node in …shard_nodes(…)``), fragment/field mutations must go
  through a ``self.*_local`` gang-replicating entry point or the
  internal client — never directly (the PR 6 ``import_values`` bug).
* ``dispatch-bypass`` — executor entry points must consult the
  engine-eligibility predicate; code outside the engine must not call
  ``._execute`` directly.
* ``jit-purity`` — ``@jax.jit`` bodies must not touch wall-clock, host
  RNG, metrics, locks, or print.
* ``donation-safety`` — an operand passed to a donated-argnums kernel
  (``zeros_like_donated``) is dead after the call; any later read of
  that name is flagged.
* ``metrics-sync`` — every metric name passed to
  ``metrics.count/gauge/observe`` (literal or ``metrics.CONSTANT``)
  exists in the ``utils/metrics.py`` registry — the docs-sync test
  extended to code sites.
* ``fault-spec`` — string-literal fault schedules handed to the three
  injector families (``install_storage_faults`` /
  ``install_device_faults`` / multihost ``FaultSpec.parse`` /
  ``maybe_faulty``) parse under that family's knob grammar. A typo'd
  knob in a chaos schedule otherwise surfaces as a ValueError at the
  worst time: inside the fault window it was supposed to open.

Suppressions: ``# check: disable=<rule>[,<rule>…] (<reason>)`` on the
flagged line or alone on the line above. ``--strict`` additionally
requires every suppression to carry a reason and to name known rules.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Callable, Optional

RULES = (
    "lock-discipline",
    "lock-wrapper",
    "gang-routing",
    "dispatch-bypass",
    "jit-purity",
    "donation-safety",
    "metrics-sync",
    "fault-spec",
)

# modules migrated to OrderedLock — the five lock-heaviest (ISSUE 9);
# lock-wrapper keeps them migrated
INSTRUMENTED_MODULES = (
    "executor/dispatch.py",
    "server/pipeline.py",
    "executor/stager.py",
    "plan/cache.py",
    "parallel/multihost.py",
)

# fragment/field state mutators that must ride a *_local entry point on
# an owner-side cluster leg (gang replication, parallel/federation.py)
_MUTATORS = frozenset(
    {
        "import_bits",
        "import_values",
        "import_value",
        "bulk_import",
        "import_block_pairs",
        "set_bit",
        "clear_bit",
    }
)

# call names that block (or are unbounded I/O) — forbidden under a lock
_BLOCKING_ATTR_CALLS = frozenset(
    {
        "result",  # concurrent.futures / dispatch item futures
        "block_until_ready",
        "urlopen",
        "getresponse",
        "create_connection",
        "recv",
        "recv_frame",
        "recv_message",
        "sendall",
        "device_put",  # host->device transfer: real I/O
    }
)

_SUPPRESS_RE = re.compile(
    r"#\s*check:\s*disable=([A-Za-z0-9_,-]+)\s*(?:\(([^)]*)\))?"
)


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


# -- helpers ----------------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


_LOCKISH_RE = re.compile(r"(?:^|_)(?:mu|mutex|lock|cond|cv)(?:$|_)|lock$|^mu$")
_CONDISH_RE = re.compile(r"(?:^|_)(?:cond|cv)(?:$|_)")
_EVENTISH_RE = re.compile(r"(?:^|_)(?:event|ev|done|ready)(?:$|_)")
_THREADISH_RE = re.compile(r"(?:^|_)(?:thread|threads|loop|proc|worker)s?(?:$|_)")


def _last_seg(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _is_lockish(expr: ast.AST) -> bool:
    return bool(_LOCKISH_RE.search(_last_seg(_dotted(expr))))


def _walk_no_nested_funcs(node: ast.AST):
    """Yield descendants without descending into nested function /
    class definitions (their bodies run at some other time, under some
    other lock state)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            stack.extend(ast.iter_child_nodes(n))


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = _dotted(dec)
        if d in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            f = _dotted(dec.func)
            if f in ("jax.jit", "jit"):
                return True
            if f in ("functools.partial", "partial") and dec.args:
                if _dotted(dec.args[0]) in ("jax.jit", "jit"):
                    return True
    return False


# -- rule: lock-discipline ---------------------------------------------------


def _blocking_reason(call: ast.Call) -> Optional[str]:
    f = call.func
    d = _dotted(f)
    if d in ("time.sleep",):
        return "time.sleep"
    if isinstance(f, ast.Attribute):
        recv = _last_seg(_dotted(f.value))
        if f.attr in _BLOCKING_ATTR_CALLS:
            return f".{f.attr}()"
        if f.attr == "wait" and _EVENTISH_RE.search(recv) and not _CONDISH_RE.search(recv):
            # Event.wait does NOT release the enclosing lock (unlike
            # Condition.wait) — a waiter under a lock starves whoever
            # must set the event
            return f"{recv}.wait()"
        if f.attr == "join" and _THREADISH_RE.search(recv):
            return f"{recv}.join()"
    return None


def _methods_acquiring(cls: ast.ClassDef) -> dict[str, set[str]]:
    """method name -> set of self.<attr> lock names it acquires (via
    ``with self.<attr>`` or ``self.<attr>.acquire()``)."""
    out: dict[str, set[str]] = {}
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        acquired: set[str] = set()
        for n in ast.walk(item):
            if isinstance(n, ast.With):
                for w in n.items:
                    d = _dotted(w.context_expr)
                    if d.startswith("self.") and _is_lockish(w.context_expr):
                        acquired.add(d)
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr == "acquire":
                    d = _dotted(n.func.value)
                    if d.startswith("self."):
                        acquired.add(d)
        if acquired:
            out[item.name] = acquired
    return out


def _reentrant_lock_attrs(cls: ast.ClassDef) -> set[str]:
    """self.<attr> names assigned an RLock (or reentrant OrderedLock)
    anywhere in the class — self-call nesting on those is legal."""
    out: set[str] = set()
    for n in ast.walk(cls):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            f = _dotted(n.value.func)
            reent = f.endswith("RLock") or (
                f.endswith("OrderedLock")
                and any(
                    kw.arg == "reentrant"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value
                    for kw in n.value.keywords
                )
            )
            if reent:
                for t in n.targets:
                    d = _dotted(t)
                    if d.startswith("self."):
                        out.add(d)
    return out


def rule_lock_discipline(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []

    def scan_with(w: ast.With, lock_name: str, acquirers, reentrant) -> None:
        for n in _walk_no_nested_funcs(w):
            if not isinstance(n, ast.Call):
                continue
            why = _blocking_reason(n)
            if why is not None:
                findings.append(
                    ctx.finding(
                        n.lineno,
                        "lock-discipline",
                        f"blocking call {why} inside `with {lock_name}:` — "
                        "move the wait/IO outside the critical section",
                    )
                )
            # static self-deadlock: self.m() where m re-acquires this lock
            d = _dotted(n.func)
            if (
                d.startswith("self.")
                and "." not in d[5:]
                and lock_name.startswith("self.")
                and lock_name not in reentrant
            ):
                m = d[5:]
                if lock_name in acquirers.get(m, ()):
                    findings.append(
                        ctx.finding(
                            n.lineno,
                            "lock-discipline",
                            f"self.{m}() re-acquires {lock_name} already "
                            "held here (self-deadlock on a non-reentrant "
                            "lock)",
                        )
                    )

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        acquirers = _methods_acquiring(cls)
        reentrant = _reentrant_lock_attrs(cls)
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for n in ast.walk(fn):
                if isinstance(n, ast.With):
                    for w in n.items:
                        if _is_lockish(w.context_expr):
                            scan_with(n, _dotted(w.context_expr), acquirers, reentrant)
    # module/function-level (non-class) with-lock bodies: blocking-call
    # scan only (no self-deadlock analysis without a class)
    class_lines: set[int] = set()
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        end = getattr(cls, "end_lineno", cls.lineno)
        class_lines.update(range(cls.lineno, end + 1))
    for n in ast.walk(tree):
        if isinstance(n, ast.With) and n.lineno not in class_lines:
            for w in n.items:
                if _is_lockish(w.context_expr):
                    scan_with(n, _dotted(w.context_expr), {}, set())
    # dedup (a with nested in a with over the same lines)
    seen: set[tuple] = set()
    out = []
    for f in findings:
        k = (f.line, f.rule, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


# -- rule: lock-wrapper ------------------------------------------------------


def rule_lock_wrapper(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []
    instrumented = ctx.relpath.replace(os.sep, "/").endswith(INSTRUMENTED_MODULES)

    def bare_lock(call: ast.Call) -> Optional[str]:
        d = _dotted(call.func)
        if d in ("threading.Lock", "threading.RLock"):
            return d
        if d == "threading.Condition" and not call.args:
            # Condition() conjures a hidden bare lock
            return "threading.Condition()"
        return None

    # module-level statements (assignments at module scope)
    for stmt in tree.body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call) and not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                kind = bare_lock(n)
                if kind is not None:
                    findings.append(
                        ctx.finding(
                            n.lineno,
                            "lock-wrapper",
                            f"module-level {kind} — create it via "
                            "analysis.locks.OrderedLock so the lock graph "
                            "sees it",
                        )
                    )
    if instrumented:
        for n in ast.walk(tree):
            if isinstance(n, ast.Call):
                kind = bare_lock(n)
                if kind is not None and not any(
                    f.line == n.lineno for f in findings
                ):
                    findings.append(
                        ctx.finding(
                            n.lineno,
                            "lock-wrapper",
                            f"{kind} in an instrumented module — use "
                            "analysis.locks.OrderedLock (lock-order "
                            "detection is migrated here)",
                        )
                    )
    return findings


# -- rule: gang-routing ------------------------------------------------------


def rule_gang_routing(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []

    def contains_shard_nodes(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr == "shard_nodes":
                    return True
        return False

    for loop in [n for n in ast.walk(tree) if isinstance(n, ast.For)]:
        if not contains_shard_nodes(loop.iter):
            continue
        for n in _walk_no_nested_funcs(loop):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr not in _MUTATORS:
                continue
            recv = _dotted(n.func.value)
            if recv == "self":
                continue  # self.import_*_local-style entry points
            if "client" in recv.split("."):
                continue  # remote leg via the internal HTTP client
            findings.append(
                ctx.finding(
                    n.lineno,
                    "gang-routing",
                    f"owner-side write leg calls {recv}.{n.func.attr}() "
                    "directly inside a shard_nodes() routing loop — on a "
                    "federated gang leader this bypasses gang replay "
                    "(followers diverge; the PR 6 import_values bug). "
                    f"Route through self.{n.func.attr}_local(...)",
                )
            )
    return findings


# -- rule: dispatch-bypass ---------------------------------------------------

# modules allowed to call Executor._execute directly: the executor
# itself and the engine that IS the dispatch loop
_EXECUTE_WHITELIST = ("executor/executor.py", "executor/dispatch.py")


def rule_dispatch_bypass(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []
    rel = ctx.relpath.replace(os.sep, "/")
    if not rel.endswith(_EXECUTE_WHITELIST):
        for n in ast.walk(tree):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "_execute"
                and _dotted(n.func.value) != "self"
            ):
                findings.append(
                    ctx.finding(
                        n.lineno,
                        "dispatch-bypass",
                        "direct ._execute() call bypasses Executor.execute "
                        "— new entry points must go through execute() so "
                        "the engine-eligibility predicate "
                        "(gang/cluster/remote/serial/write/re-entrant) is "
                        "consulted",
                    )
                )
    if rel.endswith("executor/executor.py") or ctx.fixture_role == "executor":
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            if cls.name != "Executor":
                continue
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if not fn.name.startswith("execute"):
                    continue
                body_names = {
                    x.attr
                    for x in ast.walk(fn)
                    if isinstance(x, ast.Attribute)
                }
                if not ({"_engine_eligible", "dispatch_engine"} & body_names):
                    findings.append(
                        ctx.finding(
                            fn.lineno,
                            "dispatch-bypass",
                            f"executor entry point {fn.name}() never "
                            "consults the engine-eligibility predicate "
                            "(_engine_eligible / dispatch_engine) — "
                            "eligible local reads must route through the "
                            "continuous-batching engine",
                        )
                    )
    return findings


# -- rule: jit-purity --------------------------------------------------------

_IMPURE_CALLS = {
    "time.time": "wall-clock",
    "time.monotonic": "wall-clock",
    "time.perf_counter": "wall-clock",
    "time.sleep": "blocking sleep",
    "datetime.now": "wall-clock",
    "print": "host I/O (use jax.debug.print)",
}


def rule_jit_purity(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []
    for fn in [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef) and _is_jit_decorated(n)
    ]:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                why = _IMPURE_CALLS.get(d)
                if why is None and d.endswith(".now") and "datetime" in d:
                    why = "wall-clock"
                if why is not None:
                    findings.append(
                        ctx.finding(
                            n.lineno,
                            "jit-purity",
                            f"@jax.jit body calls {d}() — {why}; traced "
                            "once at compile time, then baked into the "
                            "kernel forever",
                        )
                    )
            d = _dotted(n) if isinstance(n, (ast.Attribute, ast.Name)) else ""
            if d.startswith(("random.", "np.random.", "numpy.random.")):
                findings.append(
                    ctx.finding(
                        n.lineno,
                        "jit-purity",
                        f"@jax.jit body touches host RNG {d} — use "
                        "jax.random with an explicit key",
                    )
                )
            elif d.startswith(("metrics.", "REGISTRY.")) or d.startswith(
                "threading."
            ):
                findings.append(
                    ctx.finding(
                        n.lineno,
                        "jit-purity",
                        f"@jax.jit body references {d} — metrics/locks are "
                        "host side effects; they run at trace time only",
                    )
                )
            if isinstance(n, ast.With):
                for w in n.items:
                    if _is_lockish(w.context_expr):
                        findings.append(
                            ctx.finding(
                                n.lineno,
                                "jit-purity",
                                f"@jax.jit body takes lock "
                                f"{_dotted(w.context_expr)} — host side "
                                "effect, runs at trace time only",
                            )
                        )
    # dedup Attribute-chain double reports (np.random.default_rng hits
    # both the Attribute and its parent)
    seen: set[tuple] = set()
    out = []
    for f in findings:
        k = (f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


# -- rule: donation-safety ---------------------------------------------------


def rule_donation_safety(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []
    for fn in [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        donations: list[tuple[int, str]] = []  # (line, operand name)
        rebinds: dict[str, list[int]] = {}
        loads: dict[str, list[int]] = {}
        for n in _walk_no_nested_funcs(fn):
            if isinstance(n, ast.Call):
                f = n.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else ""
                )
                if name in ("zeros_like_donated", "_zeros_like_donated"):
                    for a in n.args:
                        if isinstance(a, ast.Name):
                            donations.append((n.lineno, a.id))
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    rebinds.setdefault(n.id, []).append(n.lineno)
                elif isinstance(n.ctx, ast.Load):
                    loads.setdefault(n.id, []).append(n.lineno)
        for dline, var in donations:
            for lline in loads.get(var, ()):
                if lline <= dline:
                    continue
                # a rebind between donation and load makes the name a
                # fresh value — the donated buffer is no longer reachable
                if any(dline <= r <= lline for r in rebinds.get(var, ())):
                    continue
                findings.append(
                    ctx.finding(
                        lline,
                        "donation-safety",
                        f"{var!r} read after being donated to a "
                        f"donate_argnums kernel at line {dline} — the "
                        "buffer is deleted on TPU/GPU; this raises (or "
                        "silently reads freed memory) off-CPU",
                    )
                )
    return findings


# -- rule: metrics-sync ------------------------------------------------------


def _metric_registry():
    from pilosa_tpu.utils import metrics as m

    return m


def rule_metrics_sync(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    m = _metric_registry()
    if ctx.relpath.replace(os.sep, "/").endswith("utils/metrics.py"):
        return []  # the registry itself
    findings: list[Finding] = []
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
            continue
        if n.func.attr not in ("count", "gauge", "observe"):
            continue
        recv = _last_seg(_dotted(n.func.value))
        if recv not in ("metrics", "REGISTRY"):
            continue
        if not n.args:
            continue
        arg = n.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in m.METRICS:
                findings.append(
                    ctx.finding(
                        arg.lineno,
                        "metrics-sync",
                        f"metric name {arg.value!r} is not declared in the "
                        "utils/metrics.py registry — add it there (and to "
                        "the docs table) or fix the name",
                    )
                )
        elif isinstance(arg, ast.Attribute) and _dotted(arg.value) == "metrics":
            const = arg.attr
            val = getattr(m, const, None)
            if not isinstance(val, str) or val not in m.METRICS:
                findings.append(
                    ctx.finding(
                        arg.lineno,
                        "metrics-sync",
                        f"metrics.{const} does not resolve to a registered "
                        "metric name in utils/metrics.py",
                    )
                )
    return findings


# -- rule: fault-spec --------------------------------------------------------

# knob grammar per injector family: knob -> value kind. Kept LOCAL (no
# core/fragment, utils/chaos, or multihost import — lint also runs in
# the no-jax check job); tests parse these same grammars with the real
# spec classes to keep both directions honest.
_FAULT_KNOBS: dict[str, dict[str, str]] = {
    "storage": {
        "fsync_fail_every": "int",
        "torn_at": "int",
        "enospc_after": "int",
        "corrupt_at": "int",
        "bitrot": "int",
        "snapshot_kill": "enum:pre|post",
    },
    "device": {
        "oom_every": "int",
        "stall_every": "int",
        "stall_s": "float",
        "poison_every": "int",
        "after": "int",
    },
    "distributed": {
        "drop_every": "int",
        "dup_every": "int",
        "delay": "float",
        "after": "int",
    },
}

# call-site shape -> (family, positional index of the spec argument)
_FAULT_CALLS: dict[str, tuple[str, int]] = {
    "install_storage_faults": ("storage", 0),
    "install_device_faults": ("device", 0),
    "StorageFaultSpec.parse": ("storage", 0),
    "DeviceFaultSpec.parse": ("device", 0),
    "FaultSpec.parse": ("distributed", 0),
    "maybe_faulty": ("distributed", 1),
}


def _fault_spec_errors(family: str, text: str) -> list[str]:
    knobs = _FAULT_KNOBS[family]
    errors: list[str] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if key not in knobs:
            errors.append(
                f"unknown {family} fault knob {key!r} "
                f"(known: {', '.join(sorted(knobs))})"
            )
            continue
        if not sep:
            errors.append(f"{family} fault knob {key!r} missing '=value'")
            continue
        kind = knobs[key]
        if kind.startswith("enum:"):
            allowed = kind[len("enum:"):].split("|")
            if value.strip() not in allowed:
                errors.append(
                    f"{family} fault knob {key!r} must be one of "
                    f"{' | '.join(allowed)}, got {value.strip()!r}"
                )
            continue
        try:
            (int if kind == "int" else float)(value.strip())
        except ValueError:
            errors.append(
                f"{family} fault knob {key!r} needs "
                f"{'an integer' if kind == 'int' else 'a number'}, "
                f"got {value.strip()!r}"
            )
    return errors


def rule_fault_spec(tree: ast.Module, ctx: "FileContext") -> list[Finding]:
    findings: list[Finding] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        d = _dotted(n.func)
        name = _last_seg(d)
        hit = _FAULT_CALLS.get(name)
        if hit is None and "." in d:
            # Klass.parse form — match on the last two segments
            hit = _FAULT_CALLS.get(".".join(d.split(".")[-2:]))
        if hit is None:
            continue
        family, argidx = hit
        if len(n.args) <= argidx:
            continue
        arg = n.args[argidx]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            continue  # dynamic specs are the parser's problem at runtime
        for msg in _fault_spec_errors(family, arg.value):
            findings.append(ctx.finding(arg.lineno, "fault-spec", msg))
    return findings


_RULE_FNS: dict[str, Callable] = {
    "lock-discipline": rule_lock_discipline,
    "lock-wrapper": rule_lock_wrapper,
    "gang-routing": rule_gang_routing,
    "dispatch-bypass": rule_dispatch_bypass,
    "jit-purity": rule_jit_purity,
    "donation-safety": rule_donation_safety,
    "metrics-sync": rule_metrics_sync,
    "fault-spec": rule_fault_spec,
}


# -- engine -----------------------------------------------------------------


class FileContext:
    def __init__(self, relpath: str, fixture_role: str = "") -> None:
        self.relpath = relpath
        # tests feed fixture snippets with a role hint ("executor") so
        # path-scoped rules can be exercised on synthetic files
        self.fixture_role = fixture_role

    def finding(self, line: int, rule: str, message: str) -> Finding:
        return Finding(self.relpath, line, rule, message)


class Suppressions:
    """``# check: disable=<rule>[,<rule>] (<reason>)`` markers, applying
    to their own line and (for standalone comment lines) the next
    line."""

    def __init__(self, source: str) -> None:
        self.by_line: dict[int, set[str]] = {}
        self.entries: list[tuple[int, tuple[str, ...], str]] = []
        for i, text in enumerate(source.splitlines(), 1):
            mobj = _SUPPRESS_RE.search(text)
            if mobj is None:
                continue
            rules = tuple(
                r.strip() for r in mobj.group(1).split(",") if r.strip()
            )
            reason = (mobj.group(2) or "").strip()
            self.entries.append((i, rules, reason))
            target = i
            if text.lstrip().startswith("#"):
                target = i + 1  # standalone comment guards the next line
            for line in (i, target):
                self.by_line.setdefault(line, set()).update(rules)

    def covers(self, line: int, rule: str) -> bool:
        return rule in self.by_line.get(line, ())


def check_source(
    source: str,
    relpath: str,
    rules: Optional[tuple] = None,
    strict: bool = False,
    fixture_role: str = "",
) -> list[Finding]:
    """Run the rule set over one file's source. Returns surviving
    findings (suppressed ones removed; strict adds suppression-hygiene
    findings)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, "parse", f"syntax error: {e.msg}")]
    ctx = FileContext(relpath, fixture_role=fixture_role)
    sup = Suppressions(source)
    findings: list[Finding] = []
    for rule in rules or RULES:
        findings.extend(_RULE_FNS[rule](tree, ctx))
    findings = [f for f in findings if not sup.covers(f.line, f.rule)]
    if strict:
        for line, names, reason in sup.entries:
            for r in names:
                if r not in RULES:
                    findings.append(
                        Finding(
                            relpath,
                            line,
                            "suppression",
                            f"unknown rule {r!r} in disable marker",
                        )
                    )
            if not reason:
                findings.append(
                    Finding(
                        relpath,
                        line,
                        "suppression",
                        "suppression without a reason — write "
                        "`# check: disable=<rule> (<why this is safe>)`",
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


_SKIP_DIRS = {
    "__pycache__",
    ".git",
    "native",
    "experiments",
    ".claude",
    "node_modules",
}


def iter_py_files(roots: list[str]) -> list[str]:
    out: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in sorted(dirnames) if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def repo_root() -> str:
    """The tree `pilosa_tpu check` (no args) checks: the repo when the
    package sits inside one (tests/ alongside), else the package dir."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parent = os.path.dirname(pkg)
    if os.path.isdir(os.path.join(parent, "tests")) and os.path.isdir(
        os.path.join(parent, "pilosa_tpu")
    ):
        return parent
    return pkg


def check_paths(
    paths: Optional[list[str]] = None, strict: bool = False
) -> list[Finding]:
    """Run every rule over the given files/dirs (default: the repo)."""
    if not paths:
        paths = [repo_root()]
    base = repo_root()
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, base)
        if rel.startswith(".."):
            rel = path
        with open(path, encoding="utf-8") as f:
            src = f.read()
        findings.extend(check_source(src, rel, strict=strict))
    return findings
