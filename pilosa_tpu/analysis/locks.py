"""Dynamic lock-order verification — the runtime half of the invariant
checker (see lint.py for the static half).

The serving stack is deeply concurrent: the dispatch engine, serving
pipeline, HBM stager, plan cache, and multihost gang lifecycle each
guard their state with a mutex, and several of those sections call into
each other (a pipeline worker executes through the executor, which
touches the stager and the plan cache; the gang leader loop touches the
pipeline's drain path). Nothing enforced an acquisition ORDER between
those locks — an AB/BA inversion would ship silently and deadlock only
under production interleavings.

``OrderedLock`` is a drop-in ``threading.Lock``/``RLock`` wrapper that
records, per thread, the stack of wrapped locks currently held. When a
thread acquires lock B while holding lock A it records the edge A→B in
a process-global lock graph; an edge that closes a cycle (B→…→A already
recorded) is a lock-order violation:

* under tests (``PYTEST_CURRENT_TEST`` in the environment) or with
  ``PILOSA_LOCK_STRICT=1`` the acquire raises ``LockOrderError``
  BEFORE blocking — the suite fails fast on the inversion instead of
  hanging until a timeout;
* in production the cycle is counted on the ``analysis.lock_cycles``
  gauge (and the edge set size on ``analysis.lock_graph_edges``) and
  execution proceeds — detection must never be the thing that takes
  the server down.

A same-thread re-acquire of a non-reentrant OrderedLock (a guaranteed
self-deadlock when blocking without a timeout) always raises — turning
an infinite hang into a stack trace is strictly better in every mode.

Edges are keyed by lock NAME, not object: names are lock *classes* in
the lockdep sense ("stager.mu", "pipeline.mu"), so the discipline holds
across instances. Same-name pairs are never recorded as edges (two
executors' stager locks nesting across instances is an ownership
question, not an ordering one).

Overhead: the hot path is one tuple-membership probe against an
immutable frozenset (GIL-safe to read without locking) plus a
thread-local list append/pop — the graph mutex is only taken when a
never-before-seen edge appears. Measured on the executor micro-bench
the instrumented build is within noise of bare ``threading.Lock``
(<5%, pinned by tests/test_analysis.py).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from pilosa_tpu.utils import metrics


class LockOrderError(RuntimeError):
    """A lock acquisition that closes a cycle in the global lock graph
    (or re-enters a non-reentrant lock on the same thread)."""


_tls = threading.local()


def _held_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def strict_mode() -> bool:
    """Fail-fast on violations? Explicit ``PILOSA_LOCK_STRICT`` wins
    (``0`` disables even under pytest); otherwise strict exactly when a
    test is running."""
    v = os.environ.get("PILOSA_LOCK_STRICT")
    if v is not None:
        return v != "0"
    return "PYTEST_CURRENT_TEST" in os.environ


class LockGraph:
    """Process-global acquisition-order graph. ``edge a→b`` means some
    thread acquired b while holding a. Cycle detection runs only when a
    new edge appears; known-edge acquisitions stay on the lock-free
    fast path (``known`` is an immutable frozenset, atomically
    replaced)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._edges: dict[str, set[str]] = {}
        self.known: frozenset = frozenset()
        self._cycles: dict[tuple, int] = {}
        self._logged: set[tuple] = set()

    def observe(self, held: tuple, name: str) -> Optional[tuple]:
        """Record edges held[i]→name; return the canonical cycle tuple
        if any new edge closed one, else None."""
        new_cycle: Optional[tuple] = None
        with self._mu:
            for h in held:
                if h == name:
                    continue
                targets = self._edges.setdefault(h, set())
                if name in targets:
                    continue
                path = self._path(name, h)
                targets.add(name)
                if path is not None:
                    # the new h→name edge closes the name→…→h path
                    # (path already ends at h) into a cycle
                    cyc = _canon_cycle(tuple(path))
                    self._cycles[cyc] = self._cycles.get(cyc, 0) + 1
                    new_cycle = cyc
            self.known = frozenset(
                (a, b) for a, bs in self._edges.items() for b in bs
            )
            n_cycles = len(self._cycles)
            n_edges = len(self.known)
        metrics.gauge(metrics.ANALYSIS_LOCK_GRAPH_EDGES, n_edges)
        if new_cycle is not None:
            metrics.gauge(metrics.ANALYSIS_LOCK_CYCLES, n_cycles)
        return new_cycle

    def _path(self, src: str, dst: str) -> Optional[list]:
        """DFS path src→…→dst through recorded edges, or None. Caller
        holds ``_mu``."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def cycles(self) -> dict[tuple, int]:
        with self._mu:
            return dict(self._cycles)

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        """Test hook: forget everything (the global graph outlives any
        one test's lock topology)."""
        with self._mu:
            self._edges.clear()
            self.known = frozenset()
            self._cycles.clear()
            self._logged.clear()


GRAPH = LockGraph()


def _canon_cycle(nodes: tuple) -> tuple:
    """Rotation-invariant cycle key: rotate so the smallest name leads,
    so A→B→A and B→A→B count as ONE cycle."""
    i = nodes.index(min(nodes))
    return nodes[i:] + nodes[:i]


class OrderedLock:
    """``threading.Lock``/``RLock`` wrapper that feeds the global lock
    graph. Supports the full lock protocol plus the private trio
    (``_is_owned``/``_release_save``/``_acquire_restore``) so it slots
    into ``threading.Condition`` unchanged."""

    __slots__ = ("name", "reentrant", "_inner", "_graph")

    def __init__(
        self,
        name: str,
        reentrant: bool = False,
        graph: Optional[LockGraph] = None,
    ) -> None:
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._graph = graph if graph is not None else GRAPH

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held_stack()
        if held:
            self._check_order(held, blocking, timeout)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held = _held_stack()
            held.append(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self.reentrant:
            # RLock has no locked() before 3.12; probe non-blocking
            if self._inner.acquire(blocking=False):
                self._inner.release()
                return False
            return True
        return self._inner.locked()

    # -- ordering ------------------------------------------------------------

    def _check_order(self, held: list, blocking: bool, timeout: float) -> None:
        graph = self._graph
        if not self.reentrant and any(x is self for x in held):
            if blocking and (timeout is None or timeout < 0):
                # guaranteed deadlock — raising beats hanging, always
                raise LockOrderError(
                    f"self-deadlock: {self.name!r} re-acquired on the "
                    "thread that already holds it"
                )
            return  # bounded acquire: let it time out naturally
        known = graph.known
        names = []
        for x in held:
            if x is self or x.name == self.name:
                continue
            if (x.name, self.name) not in known:
                names.append(x.name)
        if not names:
            return  # fast path: every edge already vetted
        cycle = graph.observe(tuple(dict.fromkeys(names)), self.name)
        if cycle is not None and strict_mode():
            raise LockOrderError(
                "lock-order cycle: "
                + " -> ".join(cycle + (cycle[0],))
                + f" (acquiring {self.name!r} while holding "
                + ", ".join(repr(n) for n in names)
                + ")"
            )

    # -- threading.Condition integration ------------------------------------

    def _is_owned(self) -> bool:
        if self.reentrant:
            return self._inner._is_owned()
        return any(x is self for x in _held_stack())

    def _release_save(self):
        held = _held_stack()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                n += 1
        if self.reentrant:
            return (self._inner._release_save(), n)
        self._inner.release()
        return (None, n)

    def _acquire_restore(self, state) -> None:
        inner_state, n = state
        if self.reentrant:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        held = _held_stack()
        for _ in range(max(1, n)):
            held.append(self)

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name!r} reentrant={self.reentrant}>"


def held_locks() -> tuple:
    """Names of OrderedLocks held by the calling thread, outermost
    first (diagnostics / tests)."""
    return tuple(x.name for x in _held_stack())
