"""Distribution layer (L5): SPMD device-mesh execution + cluster."""

from pilosa_tpu.parallel.spmd import (
    SHARD_AXIS,
    ShardBatchPlan,
    bsi_sum_spmd,
    count_fold_spmd,
    make_mesh,
    put_sharded,
    row_algebra_spmd,
    shard_spec,
    topn_batch_spmd,
    topn_spmd,
)

__all__ = [
    "SHARD_AXIS",
    "ShardBatchPlan",
    "bsi_sum_spmd",
    "count_fold_spmd",
    "make_mesh",
    "put_sharded",
    "row_algebra_spmd",
    "shard_spec",
    "topn_batch_spmd",
    "topn_spmd",
]
