"""InternalClient — node-to-node data plane over HTTP (reference
http/client.go). JSON instead of protobuf; same endpoint map."""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Optional

from pilosa_tpu.utils import events, metrics, privateproto, trace

# retry backoff cap: one fence window, not a liveness probe interval —
# a leg that can't land in ~2s should fail over, not keep waiting
_BACKOFF_CAP = 2.0


class ClientError(Exception):
    """A failed node-to-node request.

    ``transport`` is True when the node never answered (refused
    connection, DNS, socket timeout) — liveness evidence — and False
    for HTTP-level errors, where the node is provably alive.
    ``status`` carries the HTTP status code when one was received."""

    def __init__(self, msg: str, transport: bool = False, status=None) -> None:
        super().__init__(msg)
        self.transport = transport
        self.status = status


def _retryable(e: ClientError) -> bool:
    """Transient failures worth a retry: the node never answered
    (transport) or answered 503 — a fencing gang leader says exactly
    that during re-formation. Any other HTTP error is deterministic
    (bad query, missing field) and retrying just repeats it."""
    return e.transport or e.status == 503


class InternalClient:
    def __init__(
        self,
        timeout: float = 30.0,
        ssl_context=None,
        retries: int = 0,
        retry_backoff: float = 0.05,
    ) -> None:
        self.timeout = timeout
        # for https:// peers (reference http/client.go builds its
        # transport from the TLS config, server/server.go:166-240);
        # None = system defaults
        self.ssl_context = ssl_context
        # cross-gang RPC retry policy (capped exponential + full
        # jitter); retries=0 preserves one-shot semantics — the probe
        # client and control-plane broadcasts stay one-shot so liveness
        # verdicts and status gossip remain prompt
        self.retries = retries
        self.retry_backoff = retry_backoff

    def _with_retry(self, op: str, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` with up to ``self.retries`` retries on transient
        failures, honoring the ambient request deadline: a retry whose
        backoff cannot fit in the remaining budget is not attempted —
        the caller's failover path (replica re-map) is faster than a
        doomed wait."""
        if self.retries <= 0:
            return fn()
        from pilosa_tpu.server import deadline as _deadline

        attempt = 0
        while True:
            try:
                return fn()
            except ClientError as e:
                if not _retryable(e) or attempt >= self.retries:
                    if attempt:
                        metrics.count(metrics.CLIENT_RETRY_EXHAUSTED, op=op)
                        events.record(
                            events.CLIENT_RETRY_EXHAUSTED,
                            op=op,
                            attempts=attempt + 1,
                            error=str(e),
                        )
                    raise
                delay = min(_BACKOFF_CAP, self.retry_backoff * (2 ** attempt))
                delay *= 0.5 + random.random() * 0.5  # jitter
                dl = _deadline.current()
                if dl is not None and dl.remaining() <= delay:
                    metrics.count(metrics.CLIENT_RETRY_EXHAUSTED, op=op)
                    events.record(
                        events.CLIENT_RETRY_EXHAUSTED,
                        op=op,
                        attempts=attempt + 1,
                        error=f"deadline too close for retry: {e}",
                    )
                    raise
                attempt += 1
                metrics.count(metrics.CLIENT_RETRIES, op=op)
                time.sleep(delay)

    def _request(
        self,
        method: str,
        uri: str,
        path: str,
        body: Optional[bytes] = None,
        query: Optional[dict] = None,
        raw: bool = False,
        headers: Optional[dict] = None,
    ):
        url = uri + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url, data=body, method=method, headers=headers or {})
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self.ssl_context
            ) as resp:
                data = resp.read()
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:
                msg = str(e)
            raise ClientError(f"{method} {url}: {msg}", status=e.code) from e
        except (urllib.error.URLError, OSError) as e:
            raise ClientError(f"{method} {url}: {e}", transport=True) from e
        if raw:
            return data
        return json.loads(data or b"{}")

    # -- query (reference QueryNode, http/client.go:225) --

    def query_node(
        self,
        uri: str,
        index: str,
        query: str,
        shards: Optional[list[int]] = None,
        remote: bool = True,
        trace_ctx: Optional[tuple] = None,
    ) -> list[dict]:
        q = {"remote": "true" if remote else "false"}
        if shards is not None:
            q["shards"] = ",".join(str(s) for s in shards)
        # distributed trace propagation: the remote leg runs under the
        # caller's trace id (traceparent header); a sampled leg answers
        # with its serialized spans and we graft them into the live
        # tree right here — the one place every outbound query passes
        headers = None
        if trace_ctx is not None:
            headers = {"traceparent": trace.format_traceparent(trace_ctx)}
        # safe to retry even for writes: Set/Clear are idempotent and a
        # transport failure means the request may or may not have
        # landed either way — at-least-once is the existing contract
        resp = self._with_retry(
            "query",
            lambda: self._request(
                "POST",
                uri,
                f"/index/{index}/query",
                body=query.encode(),
                query=q,
                headers=headers,
            ),
        )
        spans = resp.get("spans")
        if spans:
            sp = trace.current()
            if sp is not None:
                for d in spans:
                    sp.graft(d)
                metrics.count(
                    metrics.TRACE_REMOTE_SPANS, len(spans), source="envelope"
                )
        return resp.get("results", [])

    # -- imports (reference Import/ImportValue, http/client.go:276,428) --

    def import_bits(self, uri: str, index: str, field: str, row_ids, column_ids, timestamps=None) -> None:
        body = {"rowIDs": list(row_ids), "columnIDs": list(column_ids)}
        if timestamps is not None:
            body["timestamps"] = list(timestamps)
        self._with_retry(
            "import",
            lambda: self._request(
                "POST",
                uri,
                f"/index/{index}/field/{field}/import",
                body=json.dumps(body).encode(),
            ),
        )

    def import_values(self, uri: str, index: str, field: str, column_ids, values) -> None:
        body = {"columnIDs": list(column_ids), "values": list(values)}
        self._with_retry(
            "import",
            lambda: self._request(
                "POST",
                uri,
                f"/index/{index}/field/{field}/import-value",
                body=json.dumps(body).encode(),
            ),
        )

    def import_bits_local(self, uri, index, field, row_ids, column_ids, timestamps=None):
        body = {"rowIDs": list(row_ids), "columnIDs": list(column_ids), "local": True}
        if timestamps is not None:
            body["timestamps"] = list(timestamps)
        self._with_retry(
            "import",
            lambda: self._request(
                "POST",
                uri,
                f"/index/{index}/field/{field}/import",
                body=json.dumps(body).encode(),
            ),
        )

    def ingest(self, uri, index, field, row_ids, column_ids, sets=None):
        """Owner-side ingest leg: the remote node group-commits the
        batch (one fsynced op-log append per touched fragment) and
        acks only after its fsync, so a 2xx here carries the same
        durability contract as a local ack. The ``local`` marker keeps
        the remote from re-routing the wave back through the cluster
        (with replicas > 1 that ping-pong would deadlock the two
        single-threaded committers against each other). A retry after
        a failed commit is safe: a nacked wave leaves the remote
        fragment unmodified, so the retry re-logs the identical ops.
        Returns the remote's changed-bit count."""
        body = {
            "rowIDs": list(row_ids),
            "columnIDs": list(column_ids),
            "local": True,
        }
        if sets is not None:
            body["sets"] = [bool(s) for s in sets]
        resp = self._with_retry(
            "ingest",
            lambda: self._request(
                "POST",
                uri,
                f"/index/{index}/field/{field}/ingest",
                body=json.dumps(body).encode(),
            ),
        )
        return int(resp.get("changed", len(body["rowIDs"])))

    def import_values_local(self, uri, index, field, column_ids, values):
        body = {"columnIDs": list(column_ids), "values": list(values), "local": True}
        self._with_retry(
            "import",
            lambda: self._request(
                "POST",
                uri,
                f"/index/{index}/field/{field}/import-value",
                body=json.dumps(body).encode(),
            ),
        )

    # -- fragment sync (reference FragmentBlocks/BlockData:637,682) --

    def fragment_blocks(
        self, uri: str, index: str, field: str, shard: int, view: str = "standard"
    ) -> list[dict]:
        resp = self._request(
            "GET",
            uri,
            "/internal/fragment/blocks",
            query={"index": index, "field": field, "shard": shard, "view": view},
        )
        return resp.get("blocks", [])

    def send_block_fixes(
        self,
        uri: str,
        index: str,
        field: str,
        view: str,
        shard: int,
        set_pairs,
        clear_pairs,
    ) -> None:
        """Push a consensus block merge to a replica — reaches every
        view, unlike Set/Clear PQL (see api.apply_block_fixes)."""
        self._request(
            "POST",
            uri,
            "/internal/fragment/block/data",
            body=json.dumps(
                {
                    "index": index,
                    "field": field,
                    "view": view,
                    "shard": shard,
                    "rows": [int(p[0]) for p in set_pairs],
                    "columns": [int(p[1]) for p in set_pairs],
                    "clearRows": [int(p[0]) for p in clear_pairs],
                    "clearColumns": [int(p[1]) for p in clear_pairs],
                }
            ).encode(),
        )

    def block_data(
        self, uri: str, index: str, field: str, view: str, shard: int, block: int
    ) -> dict:
        return self._request(
            "GET",
            uri,
            "/internal/fragment/block/data",
            query={
                "index": index,
                "field": field,
                "view": view,
                "shard": shard,
                "block": block,
            },
        )

    # -- shard streaming for resize (reference RetrieveShardFromURI:544) --

    def translate_keys(self, uri: str, index: str, field: str, keys: list) -> list:
        """Mint ids for keys on the translate primary."""
        resp = self._request(
            "POST",
            uri,
            "/internal/translate/keys",
            body=json.dumps({"index": index, "field": field, "keys": list(keys)}).encode(),
        )
        return resp.get("ids", [])

    def fragment_inventory(self, uri: str) -> list[dict]:
        """Every (index, field, view, shard) the node holds."""
        return self._request("GET", uri, "/internal/fragments")

    def retrieve_fragment(
        self, uri: str, index: str, field: str, view: str, shard: int
    ) -> bytes:
        return self._request(
            "GET",
            uri,
            "/internal/fragment/data",
            query={"index": index, "field": field, "view": view, "shard": shard},
            raw=True,
        )

    def send_fragment(
        self, uri: str, index: str, field: str, view: str, shard: int, data: bytes
    ) -> None:
        self._request(
            "POST",
            uri,
            "/internal/fragment/data",
            body=data,
            query={"index": index, "field": field, "view": view, "shard": shard},
        )

    # -- attr diff sync (reference ColumnAttrDiff/RowAttrDiff:732,776) --

    def column_attr_diff(self, uri: str, index: str, blocks: list) -> dict:
        resp = self._request(
            "POST",
            uri,
            f"/internal/index/{index}/attr/diff",
            body=json.dumps({"blocks": blocks}).encode(),
        )
        return resp.get("attrs", {})

    def row_attr_diff(self, uri: str, index: str, field: str, blocks: list) -> dict:
        resp = self._request(
            "POST",
            uri,
            f"/internal/index/{index}/field/{field}/attr/diff",
            body=json.dumps({"blocks": blocks}).encode(),
        )
        return resp.get("attrs", {})

    # -- control messages (reference SendMessage, http/client.go:822) --

    def send_message(self, uri: str, msg: dict) -> None:
        # control plane rides the reference's protobuf envelope
        # (broadcast.go:71-113); JSON remains the debug fallback for
        # message shapes with no wire mapping
        if privateproto.encodable(msg):
            body, headers = (
                privateproto.marshal_message(msg),
                {"Content-Type": privateproto.CONTENT_TYPE},
            )
        else:
            body, headers = json.dumps(msg).encode(), None
        self._request(
            "POST", uri, "/internal/cluster/message", body=body, headers=headers
        )

    # -- federation (parallel/federation.py) --

    def gang_apply(self, uri: str, kind: int, payload: dict, epoch: int) -> None:
        """Replicate one epoch-stamped gang descriptor to a follower in
        replicated mode. The follower 409s on an epoch mismatch (stale
        replica — it must rejoin before applying anything)."""
        self._with_retry(
            "gang_apply",
            lambda: self._request(
                "POST",
                uri,
                "/internal/gang/apply",
                body=json.dumps(
                    {"kind": kind, "payload": payload, "epoch": epoch}
                ).encode(),
            ),
        )

    # -- fleet observability (server/fleet.py, utils/trace.py) --

    def push_spans(self, uri: str, trace_id: str, spans: list[dict]) -> None:
        """Ship serialized span dicts to the trace owner's stitch
        buffer (gang follower → leader; the collective plane is one-way
        so spans ride HTTP)."""
        self._request(
            "POST",
            uri,
            "/internal/trace/push",
            body=json.dumps({"trace_id": trace_id, "spans": spans}).encode(),
        )

    def fleet_register(self, uri: str, member_uri: str, rank: int = -1, gang: str = "") -> None:
        """Announce ``member_uri``'s scrape endpoint to the fleet
        collector at ``uri``."""
        self._request(
            "POST",
            uri,
            "/internal/fleet/register",
            body=json.dumps(
                {"uri": member_uri, "rank": rank, "gang": gang}
            ).encode(),
        )

    def fleet_snapshots(self, uri: str) -> list:
        """One member's gang-local ``[[label, snapshot], ...]`` list."""
        resp = self._request("GET", uri, "/internal/fleet/snapshots")
        return resp.get("snapshots", [])

    def fleet_heat(self, uri: str) -> list:
        """One member's gang-local ``[[label, heat-snapshot], ...]``
        list — the heat-ledger leg of the fleet telemetry plane."""
        resp = self._request("GET", uri, "/internal/fleet/heat")
        return resp.get("heat", [])

    def gang_rejoin(self, uri: str, follower_uri: str) -> dict:
        """Announce a re-staged follower to its gang leader; the leader
        re-forms the gang around it and returns the new epoch."""
        return self._request(
            "POST",
            uri,
            "/internal/gang/rejoin",
            body=json.dumps({"uri": follower_uri}).encode(),
        )

    # -- misc --

    def status(self, uri: str) -> dict:
        return self._request("GET", uri, "/status")

    def probe_indirect(self, via_uri: str, target_uri: str) -> bool:
        """SWIM ping-req: ask ``via_uri`` to probe ``target_uri`` on our
        behalf (reference memberlist indirect probing, the
        gossip/gossip.go:431-494 tunables). Returns the peer's verdict;
        an unreachable RELAY answers False (no verdict ≠ alive)."""
        out = self._request(
            "POST",
            via_uri,
            "/internal/probe",
            body=json.dumps({"uri": target_uri}).encode(),
        )
        return bool(out.get("alive"))

    def schema(self, uri: str) -> list[dict]:
        return self._request("GET", uri, "/schema").get("indexes", [])

    def max_shards(self, uri: str) -> dict:
        return self._request("GET", uri, "/internal/shards/max").get("standard", {})

    def translate_data(self, uri: str, offset: int, store: str = "") -> bytes:
        """Raw translate-log frames from ``offset``; ``store`` names one
        key space (pilosa_tpu/translate/), empty = the legacy
        whole-WAL stream."""
        q: dict = {"offset": offset}
        if store:
            q["store"] = store
        return self._request(
            "GET", uri, "/internal/translate/data", query=q, raw=True
        )

    def translate_stores(self, uri: str) -> list[dict]:
        """A peer's durable translate stores with their current byte
        offsets — the pull-replication listing."""
        return self._request("GET", uri, "/internal/translate/stores")
