"""Cluster — shard placement, replication, membership, resize,
anti-entropy (reference cluster.go + holder.go syncer).

Control plane: the reference coordinates membership with SWIM gossip
(memberlist UDP probing); here the control plane is host-side HTTP to
the coordinator — node-join messages, ClusterStatus broadcasts, resize
instructions — carrying the same message set (reference
broadcast.go:52-158). The data plane (queries, imports, fragment
streaming) flows through InternalClient exactly as in the reference;
on-device cross-shard reduction additionally rides ICI collectives
(parallel/spmd.py).

Placement is hash-identical to the reference (FNV partition + jump
hash + ring replicas) so resizes move the same minimal fragment set.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Callable, Optional

from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.parallel.hashing import DEFAULT_PARTITION_N, Jmphasher, partition
from pilosa_tpu.parallel.multihost import GangUnavailable
from pilosa_tpu.parallel.node import Node
from pilosa_tpu.utils import heat, metrics, trace
from pilosa_tpu.utils.errors import NotFoundError
from pilosa_tpu.parallel.wire import pairs_to_tuples

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"


class ResizeJob:
    """Resumable background resize job (reference resizeJob,
    cluster.go:1309-1423): tracks per-node instruction completion and
    exposes a state machine (RUNNING → DONE | ABORTED | FAILED) instead
    of blocking the coordinator's message handler on an Event.wait."""

    _ids = itertools.count(1)

    RUNNING = "RUNNING"
    DONE = "DONE"
    ABORTED = "ABORTED"
    FAILED = "FAILED"

    def __init__(
        self, action: str, new_nodes: list, pending: set, target_id: str = ""
    ) -> None:
        self.id = next(self._ids)
        self.action = action
        self.target_id = target_id  # the node being added/removed
        self.new_nodes = new_nodes
        self.pending = pending
        self.state = self.RUNNING
        self.done = threading.Event()
        self.error: Optional[str] = None

    def to_dict(self) -> dict:
        # .copy() is a single C-level op under the GIL, safe against a
        # concurrent discard from the completion handler; iterating the
        # live set here could raise "changed size during iteration"
        return {
            "id": self.id,
            "action": self.action,
            "state": self.state,
            "pendingNodes": sorted(self.pending.copy()),
            "error": self.error,
        }

# per-node liveness states (the reference's memberlist SWIM
# alive/suspect/dead, gossip/gossip.go:431-494)
NODE_READY = "READY"
NODE_SUSPECT = "SUSPECT"
NODE_DOWN = "DOWN"


class ShardUnavailableError(Exception):
    """reference errShardUnavailable (executor.go:1699)."""


class Cluster:
    def __init__(
        self,
        node_id: str,
        uri: str,
        replica_n: int = 1,
        partition_n: int = DEFAULT_PARTITION_N,
        hasher=None,
        static: bool = True,
        coordinator: bool = True,
        coordinator_uri: Optional[str] = None,
        topology_path: Optional[str] = None,
        logger=None,
        probe_timeout: float = 2.0,
        down_after: int = 3,
        ssl_context=None,
    ) -> None:
        self.node_id = node_id
        self.uri = uri
        self.replica_n = replica_n
        self.partition_n = partition_n
        self.hasher = hasher or Jmphasher()
        self.static = static
        self.is_coordinator = coordinator
        self.coordinator_uri = coordinator_uri
        self.topology_path = topology_path
        self.logger = logger
        self.state = STATE_STARTING
        self.nodes: list[Node] = []
        self.client = InternalClient(ssl_context=ssl_context)
        self.server = None  # attached Server (broadcaster target)
        self.mu = threading.RLock()
        self._joined = threading.Event()
        self._resize_job: Optional[ResizeJob] = None
        # serial queue of deferred (add_node, remove_node) actions — the
        # reference processes joins one at a time through the
        # listenForJoins channel (cluster.go:1025, ±1 node per job)
        self._resize_queue: deque = deque()
        self._resize_abort = threading.Event()
        self.resize_timeout = 120.0
        self._pool = ThreadPoolExecutor(max_workers=16)
        # liveness probing (SWIM analog): consecutive probe failures per
        # node; down_after failures → DOWN, any failure → SUSPECT
        self.down_after = down_after
        self._fail_counts: dict[str, int] = {}
        # nodes with a DOWN-verification probe in flight (guarded by
        # self.mu): a chatty unreachable peer must cost at most ONE
        # blocked pool thread, not one per inbound message
        self._verifying: set[str] = set()
        self.probe_timeout = probe_timeout
        self._probe_client = InternalClient(
            timeout=probe_timeout, ssl_context=ssl_context
        )
        # federation hook (parallel/federation.py): when this node is a
        # gang leader, local map-reduce / write legs must replay through
        # the gang instead of touching the holder directly — set to a
        # callable (index, call, shards, opt) -> executor result
        self.local_executor: Optional[Callable] = None

    # -- wiring --------------------------------------------------------------

    def attach_server(self, server) -> None:
        self.server = server
        me = Node(self.node_id, self.uri, is_coordinator=self.is_coordinator)
        with self.mu:
            if not any(n.id == me.id for n in self.nodes):
                self.nodes.append(me)
            self._sort_nodes()
        if self.static:
            self.state = STATE_NORMAL
            self._save_topology()
        elif self.is_coordinator:
            # the coordinator's CONFIG is operator intent: load the
            # node list but never let persisted placement params shadow
            # a deliberate config change (it re-broadcasts its values)
            self._load_topology(adopt_params=False)
            self.state = STATE_NORMAL
            self._save_topology()
        else:
            # followers load persisted params so a restart doesn't run
            # with misconfigured local values before the first status
            # broadcast re-teaches them
            self._load_topology(adopt_params=True)
            self._join()

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _sort_nodes(self) -> None:
        self.nodes.sort(key=lambda n: n.id)

    def set_nodes(self, nodes: list[Node]) -> None:
        """Static topology injection (tests / cluster.hosts config)."""
        with self.mu:
            self.nodes = list(nodes)
            self._sort_nodes()

    def local_node(self) -> Node:
        for n in self.nodes:
            if n.id == self.node_id:
                return n
        raise KeyError(self.node_id)

    def coordinator_node(self) -> Optional[Node]:
        for n in self.nodes:
            if n.is_coordinator:
                return n
        return None

    # -- topology persistence (reference .topology, cluster.go:1519-1554) ---

    def _save_topology(self) -> None:
        if not self.topology_path:
            return
        os.makedirs(os.path.dirname(self.topology_path) or ".", exist_ok=True)
        with open(self.topology_path, "w") as f:
            # placement parameters persist with the topology: an
            # adopted replicaN must survive a restart, or the node
            # reverts to its misconfigured local value and recreates
            # the ownership divergence adoption exists to close
            json.dump(
                {
                    "nodes": [n.to_dict() for n in self.nodes],
                    "replicaN": self.replica_n,
                    "partitionN": self.partition_n,
                },
                f,
            )

    def _load_topology(self, adopt_params: bool = True) -> None:
        if not self.topology_path:
            return
        try:
            with open(self.topology_path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            return
        if isinstance(raw, list):  # legacy format: bare node list
            raw = {"nodes": raw}
        saved = [Node.from_dict(d) for d in raw.get("nodes", [])]
        with self.mu:
            by_id = {n.id: n for n in self.nodes}
            for n in saved:
                if n.id not in by_id:
                    # liveness is runtime evidence, not durable fact: a
                    # DOWN/SUSPECT persisted before a restart says
                    # nothing about the peer NOW (memberlist likewise
                    # starts every member alive and lets probing
                    # re-discover). Without this, a full-cluster
                    # restart would boot with peers stuck DOWN — and
                    # DOWN is only cleared by an active probe success.
                    n.state = NODE_READY
                    self.nodes.append(n)
            self._sort_nodes()
            if adopt_params:
                for key, attr in (
                    ("replicaN", "replica_n"),
                    ("partitionN", "partition_n"),
                ):
                    v = raw.get(key)
                    if v and int(v) != getattr(self, attr):
                        if self.logger:
                            self.logger.printf(
                                "restoring cluster %s=%s from topology "
                                "(local config had %s)",
                                attr, v, getattr(self, attr),
                            )
                        setattr(self, attr, int(v))

    # -- membership (HTTP control plane replacing gossip) --------------------

    def _join(self) -> None:
        """Announce to the coordinator and wait for a ClusterStatus that
        includes us in state NORMAL (reference nodeJoin path)."""
        assert self.coordinator_uri
        me = self.local_node()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                self.client.send_message(
                    self.coordinator_uri,
                    {"type": "node-join", "node": me.to_dict()},
                )
                break
            except ClientError:
                time.sleep(0.2)
        if not self._joined.wait(timeout=60):
            raise TimeoutError("timed out joining cluster")

    # -- liveness (reference memberlist SWIM probing + NodeStatus
    #    push/pull, gossip/gossip.go:431-494, server.go:565-630) ------------

    # peers asked to confirm a suspect before it can be marked down
    # (reference memberlist IndirectChecks default = 3; we use 2 —
    # clusters here are typically small)
    INDIRECT_PROBES = 2

    def probe_nodes(self) -> None:
        """One liveness sweep: short-timeout /status probe of every peer,
        with SWIM-style INDIRECT confirmation — a direct failure is
        re-tried through up to INDIRECT_PROBES healthy third nodes
        (/internal/probe ping-req) before it counts, so one partitioned
        link cannot mark a healthy node DOWN (reference memberlist
        probing, gossip/gossip.go:431-494). A failure moves the node to
        SUSPECT; down_after consecutive failures to DOWN (skipped by
        query planning but kept in the topology — removal stays
        operator-initiated, reference cluster.go:1629-1631). A
        successful probe restores READY. Probes fan out through the
        pool, so a sweep costs one WORST-CASE peer verdict — direct
        probe timeout plus up to INDIRECT_PROBES serial relay
        round-trips for a dead peer (each relay blocks its own probe
        timeout before answering alive=false) — not O(dead peers) of
        them; the wait below is deadlined so one wedged relay
        connection cannot stall liveness forever."""

        def probe(node):
            try:
                self._probe_client.status(node.uri)
                alive = True
            except (ClientError, OSError):
                alive = self._probe_via_peers(node)
            self._note_probe(node, alive)

        futures = [self._pool.submit(probe, n) for n in self._other_nodes()]
        # worst case per peer: direct timeout + INDIRECT_PROBES relays,
        # each costing a request timeout that already includes the
        # relay's own probe; generous margin, but never unbounded
        deadline = time.monotonic() + self.probe_timeout * (
            2 + 2 * self.INDIRECT_PROBES
        )
        for f in futures:
            try:
                f.result(timeout=max(0.1, deadline - time.monotonic()))
            except FuturesTimeoutError:
                # concurrent.futures.TimeoutError only aliases the
                # builtin on 3.11+; catching the futures class works on
                # every supported Python
                continue  # verdict lands via _note_probe when it finishes

    def _probe_via_peers(self, target: Node) -> bool:
        """Ask up to INDIRECT_PROBES healthy peers to probe ``target``;
        alive if ANY confirms. Relays are chosen RANDOMLY per probe
        (like memberlist's k-random member selection) — a fixed choice
        would let one bad relay pair permanently defeat indirect
        confirmation — excluding self, the target, and already-DOWN
        nodes. With no eligible relay (2-node cluster) the direct
        verdict stands."""
        import random

        with self.mu:
            eligible = [
                n
                for n in self.nodes
                if n.id not in (self.node_id, target.id)
                and n.state != NODE_DOWN
            ]
        relays = random.sample(
            eligible, min(self.INDIRECT_PROBES, len(eligible))
        )
        for relay in relays:
            try:
                if self._probe_client.probe_indirect(relay.uri, target.uri):
                    if self.logger:
                        self.logger.printf(
                            "indirect probe: %s reached %s (direct path failed)",
                            relay.id, target.id,
                        )
                    return True
            except (ClientError, OSError):
                continue
        return False

    def _note_probe(self, node: Node, alive: bool, *, traffic: bool = False) -> None:
        """Record liveness evidence. ``traffic`` marks passive evidence
        (a message received from the node) as opposed to an active
        direct/indirect probe verdict. Passive evidence can refresh a
        READY/SUSPECT node but can NOT resurrect a DOWN one: a message
        sent while the node was still alive may land after the prober
        declared it DOWN (send/receive are not ordered with probe
        sweeps), and flipping DOWN->READY on that stale evidence would
        route queries to a dead node until the next sweep. Only a
        successful probe — evidence the node answers NOW — clears DOWN
        (memberlist similarly requires a live ack to refute death)."""
        with self.mu:
            # a concurrent ClusterStatus application rebuilds self.nodes
            # from dicts — re-resolve by id so the result lands on the
            # object the planner actually reads, not an orphaned ref
            node = next((n for n in self.nodes if n.id == node.id), node)
            if alive:
                if traffic and node.state == NODE_DOWN:
                    # verify off-thread instead: if the peer really is
                    # back (e.g. it just restarted and pushed its
                    # status), the probe success — active evidence —
                    # clears DOWN within one round-trip. One in-flight
                    # verification per node, or sustained traffic from
                    # a dead-to-us peer would queue a pool task per
                    # message and starve the probe sweeps.
                    if node.id not in self._verifying:
                        self._verifying.add(node.id)
                        self._pool.submit(self._verify_down, node)
                    return
                changed = node.state != NODE_READY
                node.state = NODE_READY
                self._fail_counts.pop(node.id, None)
            else:
                c = self._fail_counts.get(node.id, 0) + 1
                self._fail_counts[node.id] = c
                want = NODE_DOWN if c >= self.down_after else NODE_SUSPECT
                changed = node.state != want
                node.state = want
        if changed:
            if self.logger:
                self.logger.printf("node %s -> %s", node.id, node.state)
            # announce the state flip so every node's planner agrees;
            # off-thread so a query-path caller never blocks on fan-out
            if self.is_coordinator:
                threading.Thread(target=self._broadcast_status, daemon=True).start()

    def _verify_down(self, node: Node) -> None:
        """Direct probe of a DOWN node that just sent us traffic; a
        success is the active evidence required to clear DOWN."""
        try:
            self._probe_client.status(node.uri)
        except (ClientError, OSError):
            return
        finally:
            with self.mu:
                self._verifying.discard(node.id)
        self._note_probe(node, True)

    def push_node_status(self, sync: bool = False) -> None:
        """Periodic NodeStatus exchange: schema + maxShards to peers
        (the reference's gossip push/pull payload, server.go:602-630) so
        schema and shard-count drift heals without waiting for a write.
        ``sync`` (boot-time join sync) fans the per-peer pushes out
        through the pool and joins with a deadline: open() pays ~one
        probe timeout total, not peers × timeout when several are
        black-holed."""
        if self.server is None:
            return
        holder = self.server.holder
        msg = {
            "type": "node-status",
            "node_id": self.node_id,
            "schema": holder.schema(),
            "maxShards": {
                name: idx.max_shard() for name, idx in holder.indexes.items()
            },
        }
        # federation: gang lifecycle rides the periodic exchange too, so
        # a peer that was down during a transition broadcast still heals
        # within one status interval instead of routing to a stale view
        mh = getattr(self.server, "multihost", None)
        if mh is not None and mh.federated:
            msg["gang"] = {"state": mh.state, "epoch": mh.epoch}
        if not sync:
            self.send_async(msg)
            return

        def push(n):
            try:
                self._probe_client.send_message(n.uri, msg)
            except (ClientError, OSError):
                pass  # down peer: its own boot push heals the reverse path

        futs = [self._pool.submit(push, n) for n in self._other_nodes()]
        deadline = time.monotonic() + self.probe_timeout * 2
        for f in futs:
            try:
                f.result(timeout=max(0.1, deadline - time.monotonic()))
            except FuturesTimeoutError:
                pass  # laggard keeps pushing in the background

    def pull_node_status(self) -> None:
        """Startup state PULL: fetch each live peer's schema + max
        shards directly (the other half of memberlist's join-time
        push/pull). A node restarted LAST would otherwise have pushed
        its state but received nobody's — its peers pushed while it was
        down — and serve local-shards-only answers until the periodic
        exchange."""
        if self.server is None:
            return
        holder = self.server.holder

        def pull(n):
            try:
                schema = self._probe_client.schema(n.uri)
                if schema:
                    holder.apply_schema(schema)
                for name, m in (self._probe_client.max_shards(n.uri) or {}).items():
                    idx = holder.index(name)
                    if idx is not None:
                        idx.set_remote_max_shard(int(m))
                # federation: adopt the peer gang's CURRENT lifecycle —
                # this node may have been down when it was broadcast
                gang = (self._probe_client.status(n.uri) or {}).get("gang")
                if gang:
                    with self.mu:
                        n.gang_state = gang.get("state", "")
                        n.gang_epoch = int(gang.get("epoch", 0))
            except (ClientError, OSError):
                pass  # peer down: its push will heal us when it boots

        # parallel fan-out + deadlined join, like the boot-time push:
        # several black-holed peers cost ~one probe timeout, not their sum
        futs = [self._pool.submit(pull, n) for n in self._other_nodes()]
        deadline = time.monotonic() + self.probe_timeout * 2
        for f in futs:
            try:
                f.result(timeout=max(0.1, deadline - time.monotonic()))
            except FuturesTimeoutError:
                pass

    def _apply_node_status(self, msg: dict) -> None:
        self._apply_remote_holder_state(msg)
        # traffic from a node is liveness evidence — but passive: it
        # cannot clear DOWN (see _note_probe)
        sender = next((n for n in self.nodes if n.id == msg.get("node_id")), None)
        if sender is not None:
            self._note_probe(sender, True, traffic=True)
            gang = msg.get("gang")
            if gang:
                with self.mu:
                    sender.gang_state = gang.get("state", "")
                    sender.gang_epoch = int(gang.get("epoch", 0))

    def _apply_remote_holder_state(self, msg: dict) -> None:
        """Merge a peer's schema + maxShards into the local holder (the
        shared payload of ClusterStatus and NodeStatus messages)."""
        if self.server is None:
            return
        if msg.get("schema"):
            self.server.holder.apply_schema(msg["schema"])
        for name, m in (msg.get("maxShards") or {}).items():
            idx = self.server.holder.index(name)
            if idx is not None:
                idx.set_remote_max_shard(m)

    def receive_message(self, msg: dict) -> None:
        typ = msg.get("type")
        if typ == "node-join":
            self._handle_node_join(Node.from_dict(msg["node"]))
        elif typ == "cluster-status":
            self._apply_cluster_status(msg)
        elif typ == "node-status":
            self._apply_node_status(msg)
        elif typ == "resize-instruction":
            threading.Thread(
                target=self._follow_resize_instruction, args=(msg,), daemon=True
            ).start()
        elif typ == "resize-complete":
            self._mark_resize_complete(msg)
        elif typ == "holder-clean":
            self._holder_clean()
        elif typ == "set-coordinator":
            self._apply_set_coordinator(msg["node"]["id"])
        elif typ == "gang-state":
            self._apply_gang_state(msg)
        elif typ == "node-leave":
            pass  # deliberate: no automatic removal (reference cluster.go:1629)
        else:
            raise ValueError(f"unknown cluster message: {typ}")

    def _apply_gang_state(self, msg: dict) -> None:
        """Federation: a gang leader announced a lifecycle transition —
        update its node so placement stops routing writes to a fencing
        gang and reads prefer ACTIVE owners (parallel/federation.py)."""
        with self.mu:
            node = next(
                (n for n in self.nodes if n.id == msg.get("node_id")), None
            )
            if node is None:
                return
            node.gang_state = msg.get("state", "")
            node.gang_epoch = int(msg.get("epoch", 0))
        if self.logger:
            self.logger.printf(
                "gang %s -> %s (epoch %s)",
                msg.get("node_id"), msg.get("state"), msg.get("epoch"),
            )

    def announce_gang_state(self, state: str, epoch: int) -> None:
        """Broadcast THIS node's gang lifecycle to every peer (and apply
        it locally) — called from the runtime's state-change hook."""
        msg = {
            "type": "gang-state",
            "node_id": self.node_id,
            "state": state,
            "epoch": epoch,
        }
        self._apply_gang_state(msg)
        self.send_async(msg)

    def _handle_node_join(self, node: Node) -> None:
        """Coordinator-side join handling (reference nodeJoin,
        cluster.go:1638-1697)."""
        if not self.is_coordinator:
            return
        with self.mu:
            known = any(n.id == node.id for n in self.nodes)
            if known:
                self._broadcast_status()
                return
            has_data = self.server is not None and self.server.holder.has_data()
            if not has_data:
                self.nodes.append(node)
                self._sort_nodes()
                self._save_topology()
                self._broadcast_status()
                return
        # Data present: full resize dance.
        self._start_resize(add_node=node)

    def _apply_cluster_status(self, msg: dict) -> None:
        with self.mu:
            # the whole status payload — node list, cluster state,
            # placement parameters — is authoritative only from the
            # COORDINATOR: a follower's broadcast carries its own
            # (possibly stale or misconfigured) copy, and adopting a
            # stale node list cluster-wide is an outage. A follower's
            # status still counts as liveness + schema evidence
            # (handled by the caller / _apply_remote_holder_state).
            if msg.get("fromCoordinator"):
                self.nodes = [Node.from_dict(d) for d in msg["nodes"]]
                self._sort_nodes()
                self.state = msg["state"]
                for key, attr in (
                    ("replicaN", "replica_n"),
                    ("partitionN", "partition_n"),
                ):
                    v = msg.get(key)
                    if v and v != getattr(self, attr):
                        if self.logger:
                            self.logger.printf(
                                "adopting cluster %s=%s (local config had %s)",
                                attr, v, getattr(self, attr),
                            )
                        setattr(self, attr, int(v))
            self._save_topology()
        self._apply_remote_holder_state(msg)
        if any(n.id == self.node_id for n in self.nodes) and self.state == STATE_NORMAL:
            self._joined.set()

    def _broadcast_status(self) -> None:
        msg = self._status_message()
        self._apply_cluster_status(msg)
        self.send_async(msg)

    def _status_message(self) -> dict:
        holder = self.server.holder if self.server else None
        with self.mu:
            node_dicts = [n.to_dict() for n in self.nodes]
            state = self.state
        return {
            "type": "cluster-status",
            "state": state,
            "nodes": node_dicts,
            "schema": holder.schema() if holder else [],
            # reference NodeStatus carries MaxShards in gossip push/pull
            # (server.go:602-630)
            "maxShards": (
                {name: idx.max_shard() for name, idx in holder.indexes.items()}
                if holder
                else {}
            ),
            # placement parameters are CLUSTER-wide semantics, not
            # per-node config: a joiner with a different replicas=
            # setting would compute different shard ownership than the
            # rest of the cluster — its holder-clean then deletes
            # fragments the others think it owns (observed data loss).
            # The coordinator's values ride every status broadcast and
            # peers adopt them; fromCoordinator gates adoption so a
            # follower's own broadcast (e.g. a local abort) can never
            # overwrite the cluster's parameters with its misconfig.
            "replicaN": self.replica_n,
            "partitionN": self.partition_n,
            "fromCoordinator": self.is_coordinator,
        }

    # -- broadcaster (reference broadcast.go / server.go:520-547) ------------

    def _other_nodes(self) -> list[Node]:
        return [n for n in self.nodes if n.id != self.node_id]

    def send_sync(self, msg: dict) -> None:
        errs = []
        for n in self._other_nodes():
            try:
                self.client.send_message(n.uri, msg)
            except ClientError as e:
                errs.append(e)
        if errs:
            raise errs[0]

    def send_async(self, msg: dict, client: Optional[InternalClient] = None) -> None:
        """Best-effort broadcast (errors swallowed). Sequential on
        purpose: consecutive broadcasts keep per-peer ordering, which
        keeps ClusterStatus application monotone without sequence
        numbers. ``client`` overrides the transport (the boot-time sync
        passes the short-timeout probe client)."""
        client = client or self.client
        for n in self._other_nodes():
            try:
                client.send_message(n.uri, msg)
            except (ClientError, OSError):
                pass

    def send_to(self, node: Node, msg: dict) -> None:
        if node.id == self.node_id:
            self.server.receive_message(msg)
        else:
            self.client.send_message(node.uri, msg)

    # -- placement (reference cluster.go:776-857) ----------------------------

    def partition(self, index: str, shard: int) -> int:
        return partition(index, shard, self.partition_n)

    def partition_nodes(self, partition_id: int) -> list[Node]:
        with self.mu:
            nodes = self.nodes
            n = len(nodes)
            if n == 0:
                return []
            idx = self.hasher.hash(partition_id, n)
            replica_n = min(self.replica_n, n)
            return [nodes[(idx + i) % n] for i in range(replica_n)]

    def shard_nodes(self, index: str, shard: int) -> list[Node]:
        return self.partition_nodes(self.partition(index, shard))

    def owns_shard(self, index: str, shard: int) -> bool:
        return any(n.id == self.node_id for n in self.shard_nodes(index, shard))

    def contains_shards(self, index: str, max_shard: int) -> list[int]:
        return [
            s for s in range(max_shard + 1) if self.owns_shard(index, s)
        ]

    # -- distributed map/reduce (reference mapReduce, executor.go:1444-1593) -

    def map_reduce(self, index, shards, c, opt, map_fn, reduce_fn, zero_factory=None):
        shards = list(shards or [])
        # Fresh accumulators everywhere: adopting a mapped value as the
        # accumulator would let reduce_fn mutate cached fragment rows.
        result = zero_factory() if zero_factory else None
        pending = shards
        banned_nodes: set[str] = set()
        # the pool workers don't inherit this thread's contextvars: hand
        # the active span (None when untraced) to each leg explicitly
        parent = trace.current()
        while pending:
            by_node = self._shards_by_node(index, pending, banned_nodes)
            if pending and not by_node:
                raise ShardUnavailableError(f"shards unavailable: {pending}")
            next_pending: list[int] = []
            futures = []
            for node, node_shards in by_node:
                if node.id == self.node_id:
                    if self.local_executor is not None:
                        # federated leader: the local leg replays
                        # through the gang so every rank sees it
                        futures.append(
                            (node, node_shards, self._pool.submit(
                                self._map_gang_leg, parent, index, c,
                                node_shards, opt,
                            ))
                        )
                    else:
                        futures.append(
                            (node, node_shards, self._pool.submit(
                                self._map_local_leg, parent, node_shards, map_fn,
                                reduce_fn, zero_factory,
                            ))
                        )
                else:
                    futures.append(
                        (node, node_shards, self._pool.submit(
                            self._map_remote_leg, parent, node, index, c,
                            node_shards,
                        ))
                    )
            for node, node_shards, fut in futures:
                try:
                    v = fut.result()
                except (ClientError, ConnectionError, GangUnavailable) as e:
                    # failover: ban the node, re-map its shards onto
                    # replicas (reference mapReduce:1496-1509). Only
                    # transport-level failures feed the liveness tracker
                    # — an HTTP error or slow query proves the node is
                    # alive, just unable to serve this request.
                    banned_nodes.add(node.id)
                    metrics.count(metrics.CLUSTER_REMOTE_ERRORS, node=node.uri)
                    if getattr(e, "transport", isinstance(e, ConnectionError)):
                        self._note_probe(node, False)
                    next_pending.extend(node_shards)
                    if self.logger:
                        self.logger.printf("node %s failed, re-mapping: %s", node.id, e)
                    continue
                result = v if result is None else reduce_fn(result, v)
            pending = next_pending
        return result

    def _shards_by_node(self, index, shards, banned: set[str]) -> list:
        """Assign each shard to its first live owner (reference
        shardsByNode, executor.go:1444-1458). Nodes marked DOWN by the
        liveness prober are skipped up front — failover before the
        query pays a timeout; SUSPECT nodes stay in rotation.

        Raises ShardUnavailableError when ANY shard has no assignable
        owner — a partially-assigned plan would silently return a wrong
        aggregate as success."""
        by_id: dict[str, tuple[Node, list[int]]] = {}
        for shard in shards:
            owners = self.shard_nodes(index, shard)
            live = [n for n in owners if n.id not in banned and n.state != NODE_DOWN]
            # all owners down → try them anyway rather than failing fast
            # (the prober may be stale)
            candidates = live or [n for n in owners if n.id not in banned]
            if not candidates:
                raise ShardUnavailableError(
                    f"shard {index}/{shard} has no live owner"
                )
            # federation: a fencing gang missed recent writes — prefer
            # an un-fenced owner for reads when one exists (it is also
            # the one that can answer without a failover round-trip)
            ok = [
                n for n in candidates
                if n.gang_state not in ("DEGRADED", "REFORMING")
            ]
            node = (ok or candidates)[0]
            by_id.setdefault(node.id, (node, []))[1].append(shard)
        return list(by_id.values())

    def _map_local_leg(self, parent, shards, map_fn, reduce_fn, zero_factory=None):
        if parent is None:
            return self._map_local(shards, map_fn, reduce_fn, zero_factory)
        with parent.child(metrics.STAGE_MAP_LOCAL, shards=len(shards)):
            return self._map_local(shards, map_fn, reduce_fn, zero_factory)

    def _map_local(self, shards, map_fn, reduce_fn, zero_factory=None):
        result = zero_factory() if zero_factory else None
        parent = trace.current()  # single branch per shard when untraced
        for shard in shards:
            if parent is not None:
                with parent.child(metrics.STAGE_MAP_SHARD, shard=shard):
                    v = map_fn(shard)
            else:
                v = map_fn(shard)
            result = v if result is None else reduce_fn(result, v)
        return result

    def _map_gang_leg(self, parent, index, c, shards, opt):
        """Federated local leg: re-enter the executor with remote=True
        so the gang hook replays the leg on every rank of THIS gang
        (parallel/federation.py wires local_executor). Raises
        GangUnavailable while the gang is fencing — map_reduce then
        bans this node and re-maps the shards onto a replica gang."""
        if parent is None:
            return self.local_executor(index, c, shards, opt)
        with parent.child(metrics.STAGE_MAP_LOCAL, shards=len(shards)):
            return self.local_executor(index, c, shards, opt)

    def _map_remote_leg(self, parent, node, index, c, shards):
        """Remote leg wrapper: per-node fan-out RPC latency lands in
        cluster.map_remote_seconds (label node) and, when the query is
        traced, as a cluster.map_remote span."""
        t0 = time.monotonic()
        try:
            if parent is None:
                return self._map_remote(node, index, c, shards)
            with parent.child(
                metrics.STAGE_MAP_REMOTE, node=node.uri, shards=len(shards)
            ):
                return self._map_remote(node, index, c, shards)
        finally:
            metrics.observe(
                metrics.CLUSTER_MAP_REMOTE_SECONDS,
                time.monotonic() - t0,
                node=node.uri,
            )

    def _map_remote(self, node, index, c, shards):
        """Remote leg: ship the call string; decode the single result
        (reference remoteExec, executor.go:1393-1440). The current
        trace context rides the RPC as a traceparent header — inside a
        traced query this runs under the cluster.map_remote child span,
        so the remote process's spans graft back exactly there."""
        results = self.client.query_node(
            node.uri,
            index,
            str(c),
            shards=shards,
            remote=True,
            trace_ctx=trace.current_ctx(),
        )
        if not results:
            return None
        return self._decode_remote(c, results[0])

    @staticmethod
    def _decode_remote(c, raw):
        """Map the JSON wire shape back to executor result types."""
        from pilosa_tpu.core import Row
        from pilosa_tpu.executor import ValCount

        if isinstance(raw, dict):
            if "columns" in raw or "keys" in raw or "attrs" in raw:
                return Row(*raw.get("columns", []))
            if "value" in raw and "count" in raw:
                return ValCount(raw["value"], raw["count"])
        if c.name == "GroupBy":
            # un-finalized wire group list ([{group, count[, sum]}, ...]);
            # the coordinator merges legs then ranks/limits once
            return list(raw) if isinstance(raw, list) else raw
        if c.name == "Distinct":
            return [int(v) for v in raw] if isinstance(raw, list) else raw
        if isinstance(raw, list):
            return pairs_to_tuples(raw)
        return raw

    # -- write fan-out (reference executeSetBit/executeClearBit) -------------

    def set_bit(self, index, c, field, row_id, col_id, timestamp, opt) -> bool:
        return self._write_bit(
            index, c, field, row_id, col_id, opt, lambda: field.set_bit(row_id, col_id, timestamp)
        )

    def clear_bit(self, index, c, field, row_id, col_id, opt) -> bool:
        return self._write_bit(
            index, c, field, row_id, col_id, opt, lambda: field.clear_bit(row_id, col_id)
        )

    def _write_bit(self, index, c, field, row_id, col_id, opt, local_fn) -> bool:
        from pilosa_tpu import SHARD_WIDTH

        shard = col_id // SHARD_WIDTH
        ret = False
        for node in self._write_targets(index, shard):
            if node.id == self.node_id:
                if self.local_executor is not None:
                    # federated leader: replay the write through the
                    # gang so follower holders stay identical
                    res = self.local_executor(index, c, None, opt)
                    if res is True:
                        ret = True
                else:
                    # direct local apply (no gang to replay through):
                    # the heat write hook fires here, mirroring the
                    # executor's local-apply leg
                    heat.record_write(index, getattr(field, "name", ""), shard, 1)
                    if local_fn():
                        ret = True
            elif not opt.remote:
                res = self.client.query_node(
                    node.uri,
                    index,
                    str(c),
                    shards=None,
                    remote=True,
                    trace_ctx=trace.current_ctx(),
                )
                if res and res[0] is True:
                    ret = True
        return ret

    def _write_targets(self, index, shard) -> list[Node]:
        """Write-owner set for one shard: owners whose gang is fencing
        (DEGRADED/REFORMING) are skipped while an un-fenced owner
        exists — the skipped gang re-converges through the rejoin-time
        anti-entropy pass (sync_holder) before it turns ACTIVE again.
        All owners fencing → write to them anyway (a replicated-mode
        DEGRADED gang still applies writes)."""
        owners = self.shard_nodes(index, shard)
        ok = [n for n in owners if n.gang_state not in ("DEGRADED", "REFORMING")]
        return ok or owners

    def forward_to_all(self, index, c, opt) -> None:
        """SetValue/attrs replicate to every node (reference
        executeSetValue remote fan-out)."""
        if opt.remote:
            return
        for node in self._other_nodes():
            self.client.query_node(
                node.uri,
                index,
                str(c),
                shards=None,
                remote=True,
                trace_ctx=trace.current_ctx(),
            )

    # -- resize (reference cluster.go:1080-1423) -----------------------------

    def set_coordinator(self, node_id: str) -> None:
        """Operator-initiated coordinator transfer. Propagated by a
        DEDICATED message every node applies directly (reference
        SetCoordinatorMessage, api.go:746) — NOT by a cluster-status
        broadcast, whose adoption is gated on fromCoordinator and
        would be ignored when the operator posted to a follower."""
        with self.mu:
            target = next((n for n in self.nodes if n.id == node_id), None)
        if target is None:
            # an unknown id must fail loudly BEFORE any state changes:
            # applying it would demote every coordinator flag
            # cluster-wide (and persist the coordinator-less topology)
            raise NotFoundError(f"node not found: {node_id}")
        self._apply_set_coordinator(node_id)
        # wire shape = reference SetCoordinatorMessage{New Node}
        # (internal/private.proto:160; utils/privateproto.py)
        self.send_async({"type": "set-coordinator", "node": target.to_dict()})

    def _apply_set_coordinator(self, node_id: str) -> None:
        with self.mu:
            for n in self.nodes:
                n.is_coordinator = n.id == node_id
            self.is_coordinator = self.node_id == node_id
            self._save_topology()

    def remove_node(self, node_id: str) -> None:
        """Operator-initiated removal (reference api.RemoveNode:776)."""
        if not self.is_coordinator:
            raise ValueError("removeNode can only be called on the coordinator")
        target = next((n for n in self.nodes if n.id == node_id), None)
        if target is None:
            raise NotFoundError(f"node not found: {node_id}")
        if self.server is not None and self.server.holder.has_data():
            self._start_resize(remove_node=target)
        else:
            with self.mu:
                self.nodes = [n for n in self.nodes if n.id != node_id]
                self._save_topology()
            self._broadcast_status()

    def resize_abort(self) -> None:
        # only the coordinator owns the job + cluster state; a
        # follower-side abort would broadcast a status nobody should
        # adopt (reference completeCurrentJob: ErrNodeNotCoordinator,
        # cluster.go:1164-1176)
        if not self.is_coordinator:
            raise ValueError("resize abort can only be called on the coordinator")
        self._resize_abort.set()
        with self.mu:
            # the operator is stopping the resize PROCESS: queued
            # follow-up actions must not restart it behind their back
            self._resize_queue.clear()
            job = self._resize_job
            if job is not None and job.state == ResizeJob.RUNNING:
                job.state = ResizeJob.ABORTED
                job.done.set()
            if self.state == STATE_RESIZING:
                self.state = STATE_NORMAL
        self._broadcast_status()

    def _start_resize(self, add_node: Optional[Node] = None, remove_node: Optional[Node] = None) -> None:
        """Coordinator: compute fragment movements between the old and
        new cluster shapes and launch a background ResizeJob (reference
        generateResizeJob / fragSources / resizeJob.run). Returns
        immediately — the message handler never blocks; a concurrent
        action queues and runs after the active job, like the
        reference's serial listenForJoins channel."""
        target = add_node or remove_node
        with self.mu:
            running = self._resize_job
            if running is not None and running.state == ResizeJob.RUNNING:
                # dedupe against BOTH the running job's own action and
                # the queue: a joiner resends node-join while its add is
                # still in flight — a double-add would corrupt hashing
                if target is not None and target.id == running.target_id:
                    return
                queued = any(
                    (a is not None and add_node is not None and a.id == add_node.id)
                    or (
                        r is not None
                        and remove_node is not None
                        and r.id == remove_node.id
                    )
                    for a, r in self._resize_queue
                )
                if not queued:
                    self._resize_queue.append((add_node, remove_node))
                return
            # re-validate (a queued action may be stale by the time it
            # runs); a stale action must still let queued successors run
            if add_node is not None and any(n.id == add_node.id for n in self.nodes):
                self._schedule_next_resize_locked()
                return
            if remove_node is not None and not any(
                n.id == remove_node.id for n in self.nodes
            ):
                self._schedule_next_resize_locked()
                return
            self._resize_abort.clear()
            old_nodes = list(self.nodes)
            new_nodes = list(self.nodes)
            if add_node is not None:
                new_nodes = new_nodes + [add_node]
            if remove_node is not None:
                new_nodes = [n for n in new_nodes if n.id != remove_node.id]
            new_nodes.sort(key=lambda n: n.id)
            job = ResizeJob(
                "remove" if remove_node is not None else "add",
                new_nodes,
                {n.id for n in new_nodes},
                target_id=target.id if target is not None else "",
            )
            self._resize_job = job
            self.state = STATE_RESIZING
        self.send_async(self._status_message())

        try:
            # inventory of the node being removed is best-effort (a DEAD
            # node can't answer, and removal is the documented recovery
            # for one); every other old node must answer or the plan
            # would miss fragments — abort + rollback beats data loss
            optional = {remove_node.id} if remove_node is not None else set()
            sources = self._frag_sources(old_nodes, new_nodes, optional)
        except Exception as e:  # ANY planning failure must roll back —
            # the state is already RESIZING and the watchdog isn't
            # running yet, so an escape here would wedge the cluster
            if self.logger:
                self.logger.printf("resize planning failed, rolling back: %s", e)
            with self.mu:
                job.state = ResizeJob.FAILED
                job.error = f"planning failed: {e}"
                job.done.set()
                if self.state == STATE_RESIZING:
                    self.state = STATE_NORMAL
            self._broadcast_status()
            with self.mu:
                self._schedule_next_resize_locked()
            return
        schema = self.server.holder.schema() if self.server else []
        for node in new_nodes:
            instr = {
                "type": "resize-instruction",
                "job": job.id,
                "coordinator": self.uri,
                "schema": schema,
                "sources": sources.get(node.id, []),
                "node": node.to_dict(),
                "new_nodes": [n.to_dict() for n in new_nodes],
            }
            try:
                self.send_to(node, instr)
            except Exception as e:  # unreachable node: job times out / aborts
                if self.logger:
                    self.logger.printf(
                        "resize instruction to %s failed: %s", node.id, e
                    )
        threading.Thread(
            target=self._await_resize_job, args=(job,), daemon=True
        ).start()

    def _await_resize_job(self, job: ResizeJob) -> None:
        """Background completion driver: finalize on success, roll the
        cluster back to NORMAL on abort/timeout, then start the next
        queued action."""
        completed = job.done.wait(timeout=self.resize_timeout)
        try:
            if job.state == ResizeJob.ABORTED or self._resize_abort.is_set():
                job.state = ResizeJob.ABORTED
                with self.mu:
                    if self.state == STATE_RESIZING:
                        self.state = STATE_NORMAL
                self._broadcast_status()
                return
            if not completed or job.state == ResizeJob.FAILED:
                job.state = ResizeJob.FAILED
                if job.error is None:
                    job.error = f"resize timed out after {self.resize_timeout:.0f}s"
                if self.logger:
                    self.logger.printf("resize job %d failed: %s", job.id, job.error)
                with self.mu:
                    self.state = STATE_NORMAL
                self._broadcast_status()
                return
            job.state = ResizeJob.DONE
            with self.mu:
                self.nodes = job.new_nodes
                self._sort_nodes()
                self.state = STATE_NORMAL
                self._save_topology()
            self._broadcast_status()
            # every node drops fragments it no longer owns
            self.send_async({"type": "holder-clean"})
            self._holder_clean()
        finally:
            next_action = None
            with self.mu:
                if self._resize_queue:
                    next_action = self._resize_queue.popleft()
            if next_action is not None:
                # a stale action drains through to the next one inside
                # _start_resize (_schedule_next_resize_locked)
                self._start_resize(*next_action)

    def _schedule_next_resize_locked(self) -> None:
        """Caller holds self.mu and just dropped a stale action: hand
        the next queued action to a fresh thread so the queue never
        strands behind a no-op."""
        if not self._resize_queue:
            return
        next_action = self._resize_queue.popleft()
        threading.Thread(
            target=self._start_resize, args=next_action, daemon=True
        ).start()

    def resize_job_status(self) -> Optional[dict]:
        job = self._resize_job
        return job.to_dict() if job is not None else None

    def _frag_sources(
        self,
        old_nodes: list[Node],
        new_nodes: list[Node],
        optional_ids: Optional[set] = None,
    ) -> dict:
        """node_id -> [{index, field, view, shard, from_uris}] for each
        fragment the node gains in the new shape (reference
        fragSources:689-773).

        The COORDINATOR's local fragments are not the cluster's — a
        shard living only on other nodes must still move when ownership
        changes, or holder-clean deletes the last copy. So the plan is
        computed over the UNION of every old node's fragment inventory
        (one request per node, the availableShards-bitmap analog), and
        each gained fragment carries every old holder as a candidate
        source: the receiver falls through 404s to the next holder, so
        one replica missing a write can never silently drop a transfer.
        An unreachable old node fails the resize (abort + rollback)
        rather than risk planning without its fragments."""
        out: dict[str, list[dict]] = {}

        def owners(nodes, index, shard):
            n = len(nodes)
            if n == 0:
                return []
            idx = self.hasher.hash(self.partition(index, shard), n)
            rep = min(self.replica_n, n)
            return [nodes[(idx + i) % n] for i in range(rep)]

        # cluster-wide inventory: (index, field, view, shard) -> holder
        # uris. Remote fetches fan out concurrently — planning runs
        # with the cluster gated in RESIZING, so it must be bounded by
        # the slowest node, not the sum of all of them.
        def fetch(node):
            if node.id == self.node_id:
                return node, self.server.api.fragment_inventory()
            try:
                return node, self.client.fragment_inventory(node.uri)
            except ClientError:
                if optional_ids and node.id in optional_ids:
                    # the node being removed may be dead — that is
                    # exactly why it is being removed; its replicas
                    # hold the surviving copies
                    if self.logger:
                        self.logger.printf(
                            "inventory from removed node %s unavailable; "
                            "planning from the remaining nodes", node.id
                        )
                    return node, []
                raise

        holders: dict[tuple, list[str]] = {}
        for node, inv in self._pool.map(fetch, old_nodes):
            for e in inv:
                key = (e["index"], e["field"], e["view"], e["shard"])
                holders.setdefault(key, []).append(node.uri)

        # Balance streaming load over source replicas: rotate each
        # fragment's candidate list so the first choice cycles
        # (reference fragSources spreads sources the same way).
        rr = itertools.count()
        for (iname, fname, vname, shard), holder_uris in sorted(holders.items()):
            holder_set = set(holder_uris)
            for node in owners(new_nodes, iname, shard):
                # skip only destinations that PHYSICALLY hold the
                # fragment — placement-owner math can disagree with
                # reality after prior divergence, and an owner missing
                # its copy must still receive one or holder-clean
                # deletes the last replica
                if node.uri in holder_set:
                    continue
                k = next(rr) % len(holder_uris)
                out.setdefault(node.id, []).append(
                    {
                        "index": iname,
                        "field": fname,
                        "view": vname,
                        "shard": shard,
                        "from_uris": holder_uris[k:] + holder_uris[:k],
                    }
                )
        return out

    def _follow_resize_instruction(self, msg: dict) -> None:
        """Receiver side (reference followResizeInstruction:1179-1273)."""
        try:
            if self.server is not None and msg.get("schema"):
                self.server.holder.apply_schema(msg["schema"])
            for src in msg.get("sources", []):
                if self._resize_abort.is_set():
                    return
                uris = src.get("from_uris") or [src["from_uri"]]
                data = None
                hard: Optional[ClientError] = None
                for uri in uris:
                    try:
                        data = self.client.retrieve_fragment(
                            uri, src["index"], src["field"], src["view"], src["shard"]
                        )
                        break
                    except ClientError as e:
                        # fall through to the next candidate holder on
                        # ANY failure — a holder that died mid-resize
                        # must not fail the transfer while healthy
                        # replicas remain. 404 = fragment genuinely
                        # absent there; other errors are remembered and
                        # re-raised only if NO candidate delivers.
                        if e.status != 404:
                            hard = e
                        continue
                if data is None:
                    if hard is not None:
                        raise hard
                    # every listed holder 404'd: the fragment was
                    # deleted cluster-wide since planning (e.g. a
                    # concurrent index drop) — nothing to move
                    continue
                self.server.api.unmarshal_fragment(
                    src["index"], src["field"], src["view"], src["shard"], data
                )
            complete = {
                "type": "resize-complete",
                "job": msg.get("job"),
                "node_id": self.node_id,
                "ok": True,
            }
            coord_uri = msg.get("coordinator")
            if coord_uri == self.uri:
                self._mark_resize_complete(complete)
            else:
                self.client.send_message(coord_uri, complete)
        except Exception as e:  # report failure to coordinator
            if self.logger:
                self.logger.printf("resize instruction failed: %s", e)
            fail = {
                "type": "resize-complete",
                "job": msg.get("job"),
                "node_id": self.node_id,
                "ok": False,
                "error": str(e),
            }
            coord_uri = msg.get("coordinator")
            try:
                if coord_uri == self.uri:
                    self._mark_resize_complete(fail)
                else:
                    self.client.send_message(coord_uri, fail)
            except ClientError:
                pass  # coordinator times the job out instead

    def _mark_resize_complete(self, msg: dict) -> None:
        job = self._resize_job
        if job is None or job.state != ResizeJob.RUNNING:
            return
        if msg.get("job") is not None and msg["job"] != job.id:
            return  # straggler from a previous (timed-out/aborted) job
        if not msg.get("ok", True):
            job.state = ResizeJob.FAILED
            job.error = msg.get("error") or f"node {msg.get('node_id')} failed"
            job.done.set()
            return
        job.pending.discard(msg["node_id"])
        if not job.pending:
            job.done.set()

    def _holder_clean(self) -> None:
        """Remove fragments this node no longer owns (reference
        holderCleaner.CleanHolder, holder.go:799-827)."""
        holder = self.server.holder
        for iname, idx in list(holder.indexes.items()):
            for fname, fld in list(idx.fields.items()):
                for vname, view in list(fld.views.items()):
                    for shard in list(view.fragments):
                        if not self.owns_shard(iname, shard):
                            frag = view.fragments.pop(shard)
                            frag.close()
                            if frag.path and os.path.exists(frag.path):
                                os.remove(frag.path)

    # -- anti-entropy (reference holderSyncer, holder.go:566-774) -----------

    def sync_holder(self) -> None:
        """One full anti-entropy sweep: for each locally-owned fragment
        with replicas, diff 100-row block checksums against every
        replica, pull differing blocks, and converge to the majority
        consensus of all replicas (reference fragmentSyncer.syncBlock,
        fragment.go:1737-1904)."""
        if self.replica_n < 2 or self.server is None:
            return
        holder = self.server.holder
        # attribute-store diff sync first (reference holder.go:654-740)
        for iname, idx in holder.indexes.items():
            for node in self._other_nodes():
                try:
                    if idx.column_attrs is not None:
                        blocks = [
                            [bid, digest.hex()]
                            for bid, digest in idx.column_attrs.blocks()
                        ]
                        attrs = self.client.column_attr_diff(node.uri, iname, blocks)
                        if attrs:
                            idx.column_attrs.set_bulk_attrs(
                                {int(k): v for k, v in attrs.items()}
                            )
                    for fname, fld in idx.fields.items():
                        if fld.row_attr_store is None:
                            continue
                        blocks = [
                            [bid, digest.hex()]
                            for bid, digest in fld.row_attr_store.blocks()
                        ]
                        attrs = self.client.row_attr_diff(
                            node.uri, iname, fname, blocks
                        )
                        if attrs:
                            fld.row_attr_store.set_bulk_attrs(
                                {int(k): v for k, v in attrs.items()}
                            )
                except ClientError:
                    continue
        for iname, idx in holder.indexes.items():
            for fname, fld in idx.fields.items():
                for vname, view in fld.views.items():
                    for shard, frag in list(view.fragments.items()):
                        nodes = self.shard_nodes(iname, shard)
                        if not any(n.id == self.node_id for n in nodes):
                            continue
                        remotes = [n for n in nodes if n.id != self.node_id]
                        if frag.quarantined:
                            # a quarantined fragment's bits are poisoned:
                            # syncing would vote them into the consensus.
                            # The scrubber repairs it; skip until then.
                            continue
                        if remotes:
                            self._sync_fragment(
                                iname, fname, vname, shard,
                                frag.ensure_open(), remotes,
                            )

    def _sync_fragment(self, index, field, view, shard, frag, remotes) -> None:
        import numpy as np

        my_blocks = dict(frag.blocks())
        remote_blocks = {}
        for node in remotes:
            try:
                blocks = self.client.fragment_blocks(
                    node.uri, index, field, shard, view=view
                )
                remote_blocks[node.id] = {
                    b["id"]: bytes.fromhex(b["checksum"]) for b in blocks
                }
            except ClientError:
                continue
        diff_ids = set()
        for node_id, blocks in remote_blocks.items():
            for bid, digest in blocks.items():
                if my_blocks.get(bid) != digest:
                    diff_ids.add(bid)
            for bid, digest in my_blocks.items():
                if blocks.get(bid) != digest:
                    diff_ids.add(bid)
        for bid in sorted(diff_ids):
            # Gather (row, col) sets from every replica incl. self.
            # peer_sets keeps (node, set) PAIRED — a failed block_data
            # fetch must not shift which set gets attributed to a node.
            my_rows, my_cols = frag.block_data(bid)
            mine = set(zip(my_rows.tolist(), my_cols.tolist()))
            peer_sets: list[tuple] = []
            for node in remotes:
                if node.id not in remote_blocks:
                    continue
                try:
                    d = self.client.block_data(
                        node.uri, index, field, view, shard, bid
                    )
                except ClientError:
                    continue
                peer_sets.append((node, set(zip(d["rows"], d["columns"]))))
            sets = [mine] + [s for _, s in peer_sets]
            # Majority consensus (reference mergeBlock: pair kept when
            # present on >= (replicas+1)/2 of the copies).
            total = len(sets)
            threshold = (total + 1) // 2
            from collections import Counter

            counts = Counter()
            for s in sets:
                counts.update(s)
            consensus = {pair for pair, cnt in counts.items() if cnt >= threshold}
            # Apply locally.
            to_set = consensus - mine
            to_clear = mine - consensus
            if to_set or to_clear:
                frag.import_block_pairs(
                    np.array([p[0] for p in to_set], dtype=np.uint64),
                    np.array([p[1] for p in to_set], dtype=np.uint64),
                    np.array([p[0] for p in to_clear], dtype=np.uint64),
                    np.array([p[1] for p in to_clear], dtype=np.uint64),
                )
            # Push fixes to each remote through the view-aware block
            # endpoint, so time-quantum and bsig_* views converge in
            # ONE coordinator sweep. (The reference pushes generated
            # Set/Clear PQL and can only reach the standard view that
            # way — fragment.go:1874 "Only sync the standard block";
            # its other views converge only when each replica runs its
            # own pull sweep. Conscious improvement, same consensus.)
            for node, theirs in peer_sets:
                to_set_remote = sorted(consensus - theirs)
                to_clear_remote = sorted(theirs - consensus)
                if to_set_remote or to_clear_remote:
                    try:
                        self.client.send_block_fixes(
                            node.uri, index, field, view, shard,
                            to_set_remote, to_clear_remote,
                        )
                    except ClientError:
                        pass

    def repair_fragment(self, index, field, view, shard) -> bool:
        """Repair a quarantined fragment by pulling a full verified copy
        from a healthy replica (the fragment-backup plane: the archive
        carries a digest that unmarshal_fragment checks before applying,
        so a rotted source can't re-poison us — and a quarantined source
        refuses to serve at all, 503). True when a replica delivered."""
        if self.server is None:
            return False
        nodes = self.shard_nodes(index, shard)
        for node in nodes:
            if node.id == self.node_id:
                continue
            try:
                data = self.client.retrieve_fragment(
                    node.uri, index, field, view, shard
                )
                self.server.api.unmarshal_fragment(index, field, view, shard, data)
                return True
            except Exception as e:
                if self.logger:
                    self.logger.printf(
                        "repair pull %s/%s/%s/%s from %s failed: %s",
                        index, field, view, shard, node.id, e,
                    )
                continue
        return False
