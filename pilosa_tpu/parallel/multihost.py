"""Multi-host serving runtime — gang-dispatched SPMD execution over a
``jax.distributed`` global mesh.

The reference's one end-to-end distribution story serves PQL across
machines (reference executor.go:1464-1521, cluster.go:788-857). The
rebuild's SPMD plane (parallel/spmd.py) had proven cross-process
collectives at the kernel level (MULTIPROCESS_r5.json) but the serving
path — Holder → Executor → HTTP — had only ever run on a single-process
mesh. JAX's multi-controller model makes multi-host serving a *control*
problem: every process must enter the identical compiled program in the
identical order, or the first collective deadlocks. This module is that
control layer:

* **Bootstrap** (``initialize_distributed``): ``jax.distributed``
  initialization from config/env — coordinator address, process id and
  count — with the CPU ``gloo`` collective path for tests and CPU
  deployments (the same re-assertion dance dryrun_multiprocess.py
  proved; on real multi-host TPU the ICI/DCN collectives need no
  selection).

* **One global mesh**: after bootstrap, ``jax.devices()`` is the
  GLOBAL device set (all processes); the server builds one 1-D shard
  mesh over it and hands it to the executor, whose Count/Sum/TopN
  terminals then lower to shard_map programs whose psum/all_gather hops
  span the process boundary.

* **Gang dispatch**: rank 0 owns HTTP and the Holder-facing front end.
  Every state-bearing operation — queries (reads AND writes, so
  follower holders replay to identical state), imports, schema
  messages — becomes a :class:`Descriptor` (canonical plan hash from
  plan/canon.py + exec args), is framed (:func:`encode_message`) and
  broadcast to the follower ranks over the collective plane itself
  (one fixed-size ``broadcast_one_to_all`` frame per hop, so the
  control channel rides the exact transport the data plane uses), and
  then ALL ranks enter the identical execution in lockstep. Gang
  execution is serialized through one leader thread per process, which
  is what guarantees identical collective issue order.

* **Liveness**: followers run a bounded worker loop; the leader
  broadcasts idle ticks every ``idle_interval`` so followers are never
  parked in a collective with no traffic (and measure follower lag
  from the tick timestamps); a poison pill ends the loop at shutdown;
  and every dispatch is deadline-fenced on the leader — a dead
  follower turns into a clean 503 + degrade-to-local-mesh (the
  executor falls back to a mesh over this process's own devices)
  instead of a hang.

Determinism contract for gang execution (enforced in ``_gang_opt``):
plan-result caching is disabled (per-rank cache state would diverge
and change which collectives run) and multi-call queries execute
serially (a thread pool's interleaving would reorder collective
issue). Every rank must also run the same routing config — the server
skips the autotune measurement and the device-health guard pool in
distributed mode for exactly this reason.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
from typing import Any, Callable, Optional

from pilosa_tpu.analysis.locks import OrderedLock
from pilosa_tpu.utils import events, metrics, trace

# -- gang lifecycle ----------------------------------------------------------

# Lifecycle states. FORMING only ever appears in the transition log
# (construction blocks inside jax.distributed.initialize, so a
# constructed runtime is already formed). DEGRADED is no longer a
# terminal state: a federated runtime keeps serving in replicated mode
# and returns to ACTIVE through reform().
STATE_FORMING = "FORMING"
STATE_ACTIVE = "ACTIVE"
STATE_DEGRADED = "DEGRADED"
STATE_REFORMING = "REFORMING"

_STATE_CODES = {
    STATE_FORMING: 0,
    STATE_ACTIVE: 1,
    STATE_DEGRADED: 2,
    STATE_REFORMING: 3,
}

# Gang execution modes. "collective": lockstep replay over the
# jax.distributed collective plane — every rank enters every compiled
# program. "replicated": post-re-form — each rank runs an independent
# local mesh; reads execute on the leader directly and only
# state-bearing work replicates to follower HTTP endpoints, ordered by
# the same single leader thread. The distinction exists because a dead
# peer poisons the shared gloo context (and tears the global mesh), so
# the collective plane cannot be rebuilt in-process — but the gang CAN
# re-form around HTTP replication and keep its redundancy story.
MODE_COLLECTIVE = "collective"
MODE_REPLICATED = "replicated"

# Write-call detector for the replicated-mode dispatch decision (the
# same shape http_handler uses to exempt writes from coalescing):
# replicated reads run directly on the leader's local mesh, only
# state-bearing queries need the leader thread's ordering + fan-out.
_WRITE_RE = re.compile(r"\b(?:Set\w*|Clear)\s*\(")

# -- wire framing ------------------------------------------------------------

# Message kinds. One byte on the wire.
KIND_TICK = 0  # idle heartbeat; payload = {"t": leader wall clock}
KIND_POISON = 1  # shutdown; follower loop exits
KIND_QUERY = 2  # PQL query replay (reads and writes)
KIND_IMPORT = 3  # import_bits replay
KIND_IMPORT_VALUES = 4  # import_values replay
KIND_MESSAGE = 5  # server broadcast message (schema ops, create-shard, ...)
KIND_WRITE_WAVE = 6  # coalesced ingest write wave — one frame per wave, not per bit

_MAGIC = 0xA5
# frame = [magic u8][kind u8][seq u16][total u16][len u32] + payload chunk
_HEADER = struct.Struct("<BBHHI")
DEFAULT_FRAME_BYTES = 65536


class FrameError(ValueError):
    """A frame that cannot belong to this protocol (bad magic, clipped
    header, inconsistent sequence) — never silently skipped: a desynced
    control channel must fail loudly before a collective deadlocks."""


def encode_message(kind: int, payload: bytes, frame_bytes: int = DEFAULT_FRAME_BYTES):
    """Split one message into fixed-size frames. Every frame is exactly
    ``frame_bytes`` long (zero-padded) so the broadcast program compiles
    once and is reused for every hop."""
    cap = frame_bytes - _HEADER.size
    if cap <= 0:
        raise ValueError(f"frame_bytes too small: {frame_bytes}")
    chunks = [payload[i : i + cap] for i in range(0, len(payload), cap)] or [b""]
    total = len(chunks)
    if total > 0xFFFF:
        raise ValueError(f"message too large: {len(payload)} bytes")
    frames = []
    for seq, chunk in enumerate(chunks):
        head = _HEADER.pack(_MAGIC, kind, seq, total, len(chunk))
        frames.append((head + chunk).ljust(frame_bytes, b"\x00"))
    return frames


def decode_frame(frame: bytes):
    """(kind, seq, total, chunk) for one frame."""
    if len(frame) < _HEADER.size:
        raise FrameError(f"clipped frame: {len(frame)} bytes")
    magic, kind, seq, total, length = _HEADER.unpack_from(frame)
    if magic != _MAGIC:
        raise FrameError(f"bad magic: {magic:#x}")
    if total == 0 or seq >= total:
        raise FrameError(f"bad sequence: {seq}/{total}")
    if _HEADER.size + length > len(frame):
        raise FrameError(f"length {length} exceeds frame")
    return kind, seq, total, frame[_HEADER.size : _HEADER.size + length]


def decode_message(frames) -> tuple[int, bytes]:
    """Reassemble ``encode_message`` output. Frames must be complete
    and in order (the broadcast channel is FIFO by construction)."""
    kind0 = None
    chunks = []
    for i, frame in enumerate(frames):
        kind, seq, total, chunk = decode_frame(frame)
        if kind0 is None:
            kind0 = kind
        if kind != kind0 or seq != i or total != len(frames):
            raise FrameError(
                f"inconsistent frame {i}: kind={kind} seq={seq} total={total}"
            )
        chunks.append(chunk)
    if kind0 is None:
        raise FrameError("empty message")
    return kind0, b"".join(chunks)


# -- descriptors -------------------------------------------------------------


class Descriptor:
    """One gang work item: everything a follower needs to enter the
    identical execution. ``plan`` carries the canonical plan hash
    (plan/canon.py) — the query's content identity, used for tracing
    and cross-rank result verification; execution replays from the
    serialized PQL text (``Call.__str__`` round-trips exactly — the
    same property the cluster's remote legs rely on)."""

    __slots__ = ("kind", "payload")

    def __init__(self, kind: int, payload: dict) -> None:
        self.kind = kind
        self.payload = payload

    def encode(self) -> bytes:
        return json.dumps(self.payload, separators=(",", ":")).encode()

    @classmethod
    def decode(cls, kind: int, raw: bytes) -> "Descriptor":
        return cls(kind, json.loads(raw.decode()))


def query_descriptor(
    index: str, query_text: str, shards, opt, trace_ctx: Optional[tuple] = None
) -> Descriptor:
    from pilosa_tpu.plan.canon import query_signature

    payload = {
            "index": index,
            "query": query_text,
            "shards": list(shards) if shards is not None else None,
            "plan": query_signature(query_text),
            "opt": {
                "exclude_row_attrs": bool(getattr(opt, "exclude_row_attrs", False)),
                "exclude_columns": bool(getattr(opt, "exclude_columns", False)),
                # federated legs arrive with remote=True and must replay
                # that way: the gang ranks execute their local shards
                # only, never re-route through the cluster plane
                "remote": bool(getattr(opt, "remote", False)),
            },
    }
    if trace_ctx is not None:
        # originating trace context rides the broadcast, so every rank
        # replays under the same trace id (rank-tagged replay spans)
        payload["trace"] = trace.format_traceparent(trace_ctx)
    return Descriptor(KIND_QUERY, payload)


# -- channels ----------------------------------------------------------------


class ChannelTimeout(Exception):
    """recv() saw no frame within the requested window."""


class ChannelClosed(Exception):
    """The collective plane errored under a frame (peer death, runtime
    teardown) — the channel cannot carry further traffic."""


class CollectiveChannel:
    """Fixed-frame broadcast channel over the collective plane itself:
    each hop is ONE shard_map psum over a mesh spanning every process
    — u32[global_devices, W] sharded one row per device, where only
    rank 0's first device carries the frame words, so the replicated
    psum output IS the frame on every rank. Followers *enter the same
    collective to receive*, so control and data ride the exact
    transport the serving kernels use (the machinery MULTIPROCESS_r5
    proved across the process boundary) and FIFO order is structural.

    A ``recv`` timeout cannot interrupt a blocked collective (the hop
    is inside the runtime); leader death instead surfaces as the
    backend's own collective timeout/error, which is mapped to
    :class:`ChannelClosed` — the follower loop treats both the same
    way (deadline-fenced abort)."""

    def __init__(self, frame_bytes: int = DEFAULT_FRAME_BYTES) -> None:
        import numpy as np

        if frame_bytes % 4:
            raise ValueError("frame_bytes must be a multiple of 4")
        self.frame_bytes = frame_bytes
        self._np = np
        self._state = None  # lazy: (sharding, kernel, rank, shape)

    def _init(self):
        if self._state is not None:
            return self._state
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pilosa_tpu.parallel.spmd import SHARD_AXIS, make_mesh

        mesh = make_mesh(jax.devices())
        sharding = NamedSharding(mesh, P(SHARD_AXIS))

        def kernel(block):  # u32[local_devices, W] per process
            return jax.lax.psum(jnp.sum(block, axis=0), SHARD_AXIS)

        fn = jax.jit(
            jax.shard_map(
                kernel, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P()
            )
        )
        self._state = (
            sharding,
            fn,
            jax.process_index(),
            (len(jax.devices()), self.frame_bytes // 4),
            jax.local_device_count(),
        )
        return self._state

    def _hop(self, frame: Optional[bytes]):
        """One broadcast collective; ``frame`` is the leader's payload
        (None on followers). Returns the frame bytes on every rank."""
        np = self._np
        try:
            import jax

            sharding, fn, rank, shape, local_n = self._init()
            local = np.zeros((local_n, shape[1]), dtype=np.uint32)
            if rank == 0 and frame is not None:
                local[0] = np.frombuffer(frame, dtype="<u4")
            garr = jax.make_array_from_process_local_data(
                sharding, local, global_shape=shape
            )
            out = np.asarray(fn(garr), dtype="<u4")
            return out.tobytes()
        except Exception as e:  # collective plane down (peer death, ...)
            raise ChannelClosed(str(e)) from e

    def send(self, frames) -> None:
        """Leader side: broadcast each frame in order."""
        for frame in frames:
            self._hop(frame)

    def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        """Follower side: enter the broadcast and return the frame.
        ``timeout`` is advisory here (the collective blocks in the
        runtime); the backend's own collective timeout bounds a dead
        leader and surfaces as ChannelClosed."""
        return self._hop(None)

    def recv_message(self, timeout: Optional[float] = None) -> tuple[int, bytes]:
        first = self.recv_frame(timeout)
        kind, seq, total, chunk = decode_frame(first)
        if seq != 0:
            raise FrameError(f"message starts mid-sequence: {seq}/{total}")
        chunks = [chunk]
        for _ in range(1, total):
            kind2, seq2, total2, chunk2 = decode_frame(self.recv_frame(timeout))
            if kind2 != kind or total2 != total or seq2 != len(chunks):
                raise FrameError("interleaved message frames")
            chunks.append(chunk2)
        return kind, b"".join(chunks)


class LoopbackChannel:
    """In-process stand-in for tests: a thread-safe FIFO of frames with
    a REAL recv timeout. Protocol tests (follower deadline abort,
    idle-tick liveness) run against this without a second process."""

    def __init__(self, frame_bytes: int = DEFAULT_FRAME_BYTES) -> None:
        import collections

        self.frame_bytes = frame_bytes
        self._q: "collections.deque[bytes]" = collections.deque()
        self._cond = threading.Condition(OrderedLock("multihost.loopback.mu"))
        self._closed = False

    def send(self, frames) -> None:
        with self._cond:
            if self._closed:
                raise ChannelClosed("loopback closed")
            self._q.extend(frames)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._q:
                if self._closed:
                    raise ChannelClosed("loopback closed")
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise ChannelTimeout()
                self._cond.wait(timeout=rem)
            return self._q.popleft()

    def recv_message(self, timeout: Optional[float] = None) -> tuple[int, bytes]:
        first = self.recv_frame(timeout)
        kind, seq, total, chunk = decode_frame(first)
        if seq != 0:
            raise FrameError(f"message starts mid-sequence: {seq}/{total}")
        chunks = [chunk]
        for _ in range(1, total):
            kind2, seq2, total2, chunk2 = decode_frame(self.recv_frame(timeout))
            if kind2 != kind or total2 != total or seq2 != len(chunks):
                raise FrameError("interleaved message frames")
            chunks.append(chunk2)
        return kind, b"".join(chunks)


# -- fault injection ---------------------------------------------------------

FAULTS_ENV = "PILOSA_TPU_MH_FAULTS"


class FaultSpec:
    """Deterministic fault schedule for the gang control channel,
    parsed from ``PILOSA_TPU_MH_FAULTS`` (or the ``distributed-faults``
    config knob): ``drop_every=N`` zeroes every Nth sent frame (the
    receiver sees bad magic — frame loss on the wire), ``dup_every=N``
    delivers every Nth frame twice (duplicate delivery),
    ``delay=S`` sleeps S seconds before each send (a slow or wedged
    peer), ``after=K`` starts counting only after the first K frames so
    bring-up traffic passes clean. No RNG anywhere — the follower
    desync-abort and leader fencing paths reproduce exactly, without
    SIGKILL."""

    __slots__ = ("drop_every", "dup_every", "delay", "after")

    def __init__(
        self,
        drop_every: int = 0,
        dup_every: int = 0,
        delay: float = 0.0,
        after: int = 0,
    ) -> None:
        self.drop_every = drop_every
        self.dup_every = dup_every
        self.delay = delay
        self.after = after

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        spec = cls()
        for part in (text or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, _, value = part.partition("=")
            key = key.strip()
            if key in ("drop_every", "dup_every", "after"):
                setattr(spec, key, int(value))
            elif key == "delay":
                spec.delay = float(value)
            else:
                raise ValueError(f"unknown fault knob: {key!r}")
        return spec

    def __bool__(self) -> bool:
        return bool(self.drop_every or self.dup_every or self.delay)


class FaultyChannel:
    """Wraps any channel with a :class:`FaultSpec` applied on the SEND
    side — the leader is the only sender, so one wrapper perturbs the
    whole gang. Receive paths pass through untouched: a dropped frame
    surfaces on the receiver as a FrameError (bad magic on the zeroed
    frame), exactly what a desynced collective hop looks like."""

    def __init__(self, inner, spec: FaultSpec) -> None:
        self.inner = inner
        self.spec = spec
        self.frame_bytes = inner.frame_bytes
        self._sent = 0

    def send(self, frames) -> None:
        out = []
        for frame in frames:
            self._sent += 1
            n = self._sent - self.spec.after
            if n <= 0:
                out.append(frame)
                continue
            if self.spec.drop_every and n % self.spec.drop_every == 0:
                out.append(b"\x00" * len(frame))  # lost on the wire
                continue
            out.append(frame)
            if self.spec.dup_every and n % self.spec.dup_every == 0:
                out.append(frame)
        if self.spec.delay:
            time.sleep(self.spec.delay)
        self.inner.send(out)

    def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        return self.inner.recv_frame(timeout)

    def recv_message(self, timeout: Optional[float] = None) -> tuple[int, bytes]:
        return self.inner.recv_message(timeout)

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()


def maybe_faulty(channel, spec_text: str = ""):
    """Wrap ``channel`` when a fault spec is configured (explicit
    argument wins, else the env); identity otherwise."""
    text = spec_text or os.environ.get(FAULTS_ENV, "")
    if not text:
        return channel
    return FaultyChannel(channel, FaultSpec.parse(text))


# -- bootstrap ---------------------------------------------------------------

COORD_ENV = "PILOSA_TPU_MH_COORDINATOR"
RANK_ENV = "PILOSA_TPU_MH_PROCESS_ID"
NPROCS_ENV = "PILOSA_TPU_MH_NUM_PROCESSES"


def initialize_distributed(
    coordinator_address: str = "",
    num_processes: int = 0,
    process_id: int = -1,
    use_gloo: bool = True,
) -> tuple[int, int]:
    """Initialize the ``jax.distributed`` runtime from explicit values
    or the ``PILOSA_TPU_MH_*`` environment (the launcher convention —
    one command line, per-rank env). Returns (process_id, num_processes).

    ``use_gloo`` selects the CPU gloo collective implementation — the
    only way cross-process collectives dispatch on the CPU backend
    (tests, CPU serving); flag-guarded because the knob name is
    version-dependent and irrelevant on real multi-host TPU."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(COORD_ENV, "")
    if process_id < 0:
        process_id = int(os.environ.get(RANK_ENV, "0"))
    if num_processes <= 0:
        num_processes = int(os.environ.get(NPROCS_ENV, "1"))
    if not coordinator_address:
        raise ValueError(
            "distributed serving requires a coordinator address "
            f"(--coordinator-address / {COORD_ENV})"
        )
    if use_gloo:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes, jax.process_count()
    return process_id, num_processes


def mesh_spans_processes(mesh) -> bool:
    """Does this mesh place shards on devices another process owns?"""
    import jax

    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


# -- exceptions --------------------------------------------------------------


class GangUnavailable(Exception):
    """The gang could not complete a dispatch (dead follower, channel
    down, post-degrade shutdown). Carries ``status`` 503 so the HTTP
    layer maps it like a drain shed; the runtime has already degraded
    to the local mesh, so a client retry executes locally."""

    def __init__(self, message: str) -> None:
        super().__init__(message)
        self.status = 503
        self.retry_after = 1.0


# -- follower ----------------------------------------------------------------


class GangFollower:
    """The bounded follower worker loop: receive frames, apply work
    descriptors through ``apply_fn(kind, payload)``, count ticks, exit
    on poison — or abort cleanly when the leader goes quiet past
    ``leader_timeout`` (ChannelTimeout from test channels; ChannelClosed
    from the real collective plane when the backend's own timeout
    fires). Never hangs forever on a divergent leader."""

    def __init__(
        self,
        channel,
        apply_fn: Callable[[int, dict], Any],
        leader_timeout: float = 60.0,
        on_result: Optional[Callable[[Descriptor, Any], None]] = None,
    ) -> None:
        self.channel = channel
        self.apply_fn = apply_fn
        self.leader_timeout = leader_timeout
        self.on_result = on_result
        self.ticks = 0
        self.works = 0
        self.errors = 0
        self.last_lag = 0.0
        self.stopped_reason = ""

    def run(self) -> str:
        """Loop until poison / leader loss; returns the stop reason
        ("poison" | "leader_timeout" | "channel_closed" | "desync" |
        "apply_error")."""
        while True:
            try:
                kind, raw = self.channel.recv_message(timeout=self.leader_timeout)
            except ChannelTimeout:
                self.stopped_reason = "leader_timeout"
                metrics.count(metrics.MULTIHOST_ABORTS, role="follower")
                return self.stopped_reason
            except ChannelClosed:
                self.stopped_reason = "channel_closed"
                metrics.count(metrics.MULTIHOST_ABORTS, role="follower")
                return self.stopped_reason
            except FrameError:
                # a dropped/garbled/misordered frame means this rank can
                # no longer prove it has seen the same work stream as
                # the leader — continuing could skip or replay work
                # silently. Abort cleanly; the leader's dispatch fence
                # turns the silence into the designed 503 + degrade.
                self.stopped_reason = "desync"
                metrics.count(metrics.MULTIHOST_ABORTS, role="follower")
                return self.stopped_reason
            if kind == KIND_POISON:
                self.stopped_reason = "poison"
                return self.stopped_reason
            if kind == KIND_TICK:
                self.ticks += 1
                try:
                    sent = json.loads(raw.decode()).get("t", 0.0)
                    self.last_lag = max(0.0, time.time() - float(sent))
                    metrics.observe(
                        metrics.MULTIHOST_FOLLOWER_LAG_SECONDS, self.last_lag
                    )
                except (ValueError, TypeError):
                    pass
                continue
            try:
                desc = Descriptor.decode(kind, raw)
            except ValueError:
                # frame reassembly produced bytes that don't decode: a
                # duplicated or clipped mid-message frame — same desync
                # verdict as a framing error
                self.stopped_reason = "desync"
                metrics.count(metrics.MULTIHOST_ABORTS, role="follower")
                return self.stopped_reason
            self.works += 1
            metrics.count(metrics.MULTIHOST_DISPATCHES, role="follower")
            try:
                result = self.apply_fn(kind, desc.payload)
            except _expected_apply_errors():
                # the work itself was invalid the same way on every
                # rank (bad PQL, missing index/field, value errors):
                # the leader raised the identical error to its client
                # BEFORE reaching any collective, so the gang is still
                # in lockstep — count it and continue
                self.errors += 1
                metrics.count(metrics.MULTIHOST_FOLLOWER_ERRORS)
                continue
            except Exception:
                # ANY unexpected follower-side failure may have skipped
                # collectives the leader still runs — the gang is
                # desynced and the next hop would pair mismatched
                # collectives (observed as a gloo size-mismatch abort
                # that kills BOTH processes). Abort the loop cleanly;
                # the leader's dispatch fence turns this into the
                # designed 503 + degrade-to-local-mesh.
                import traceback

                traceback.print_exc()
                self.errors += 1
                metrics.count(metrics.MULTIHOST_FOLLOWER_ERRORS)
                self.stopped_reason = "apply_error"
                metrics.count(metrics.MULTIHOST_ABORTS, role="follower")
                return self.stopped_reason
            if self.on_result is not None:
                self.on_result(desc, result)


def _expected_apply_errors() -> tuple:
    """Error types a replay can raise BEFORE any device collective —
    argument validation, parsing, missing schema. The leader raised
    the identical error at the identical point, so lockstep holds and
    the follower loop may continue. Everything else is treated as
    divergence (loop abort)."""
    from pilosa_tpu.utils.errors import NotFoundError

    return (ValueError, KeyError, NotFoundError)


# -- runtime -----------------------------------------------------------------


class MultiHostRuntime:
    """The gang-dispatch coordinator, one per process.

    Rank 0 (leader): ``dispatch()`` enqueues a descriptor; one leader
    thread pops, broadcasts the frames, then runs the work locally —
    collectives issue in queue order, matching the followers' loop
    order. ``dispatch`` blocks the calling (pipeline worker) thread on
    a future, fenced by the request deadline and
    ``dispatch_timeout`` — on expiry the gang is declared dead, the
    executor degrades to a mesh over this process's local devices, and
    the caller gets :class:`GangUnavailable` (HTTP 503).

    Followers: ``serve_follower()`` runs the :class:`GangFollower`
    loop on the calling thread until poison/abort.
    """

    def __init__(
        self,
        rank: int,
        world: int,
        channel=None,
        apply_fn: Optional[Callable[[int, dict], Any]] = None,
        frame_bytes: int = DEFAULT_FRAME_BYTES,
        idle_interval: float = 2.0,
        dispatch_timeout: float = 30.0,
        leader_timeout: float = 60.0,
        on_degrade: Optional[Callable[[], None]] = None,
        logger=None,
        faults: str = "",
    ) -> None:
        self.rank = rank
        self.world = world
        ch = channel if channel is not None else CollectiveChannel(frame_bytes)
        self.channel = maybe_faulty(ch, faults)
        self.apply_fn = apply_fn
        self.frame_bytes = frame_bytes
        self.idle_interval = idle_interval
        self.dispatch_timeout = dispatch_timeout
        self.leader_timeout = leader_timeout
        self.on_degrade = on_degrade
        self.logger = logger
        self.active = world > 1
        # lifecycle (ISSUE 7): state machine + epoch + transition log.
        # `degraded` survives as a property over `state` for callers
        # (and tests) from the PR 5 single-plane world.
        self.state = STATE_ACTIVE
        self.mode = MODE_COLLECTIVE
        self.epoch = 0
        self.federated = False  # set by the federation wiring (server)
        self.transitions: list[dict] = []
        self._replicas: list[str] = []  # replicated-mode follower URIs
        # federation hooks, wired by parallel/federation.py:
        # replicate_fn(uri, kind, payload, epoch) applies a descriptor
        # on one replicated follower (raises on terminal failure);
        # on_reform epoch-fences server state (plan cache, stager)
        # before a rejoin; on_state_change announces lifecycle moves to
        # the cluster plane.
        self.replicate_fn: Optional[Callable[[str, int, dict, int], None]] = None
        self.on_reform: Optional[Callable[[], None]] = None
        self.on_state_change: Optional[Callable[[str, int], None]] = None
        # the leader's HTTP URI, learned by followers from the leader's
        # boot-time broadcast (server.py "leader-uri" message) or the
        # rejoin config — the push target for replay spans and fleet
        # registration
        self.leader_uri: str = ""
        self._in_gang = threading.local()
        self._mu = OrderedLock("multihost.gang.mu")
        self._cond = threading.Condition(self._mu)
        self._queue: list[tuple[Descriptor, "_Future"]] = []
        self._closing = False
        self._loop_gen = 0  # bumped at degrade/reform: zombie loops exit
        # degrade swap fence: between the DEGRADED verdict and the
        # on_degrade hook finishing, local execution would still target
        # the dead collective plane — route decisions wait this out
        self._degrading = False
        self._degrading_thread: Optional[int] = None
        self._degrade_evt = threading.Event()
        self._degrade_evt.set()
        self._leader_thread: Optional[threading.Thread] = None
        self._ticker_thread: Optional[threading.Thread] = None
        self._last_send = time.monotonic()
        self.follower: Optional[GangFollower] = None
        metrics.gauge(metrics.MULTIHOST_DEGRADED, 0)
        metrics.gauge(metrics.MULTIHOST_STATE, _STATE_CODES[self.state])
        metrics.gauge(metrics.MULTIHOST_EPOCH, self.epoch)
        if self.active:
            self.transitions.append(
                {
                    "from": STATE_FORMING,
                    "to": STATE_ACTIVE,
                    "reason": "gang formed",
                    "t": time.time(),
                }
            )
        if self.active and rank == 0:
            self._start_leader_loop()
            if idle_interval > 0:
                self._ticker_thread = threading.Thread(
                    target=self._tick_loop, name="multihost-ticker", daemon=True
                )
                self._ticker_thread.start()

    @classmethod
    def replicated(
        cls,
        apply_fn: Optional[Callable[[int, dict], Any]] = None,
        dispatch_timeout: float = 30.0,
        logger=None,
    ) -> "MultiHostRuntime":
        """A replicated-mode gang of ONE: the boot path for a restarted
        gang LEADER (``federation-leader = true``). The old collective
        plane died with its peers — gloo contexts cannot be rebuilt
        in-process — so the node comes back solo: no jax.distributed,
        a loopback channel nothing ever rides, ``active`` forced so the
        leader thread orders writes, and DEGRADED until a follower
        rejoins through reform()."""
        rt = cls(
            rank=0,
            world=1,
            channel=LoopbackChannel(),
            apply_fn=apply_fn,
            idle_interval=0,  # ticks only feed collective followers
            dispatch_timeout=dispatch_timeout,
            logger=logger,
        )
        rt.active = True
        rt.mode = MODE_REPLICATED
        rt.federated = True
        rt.state = STATE_DEGRADED
        rt.transitions.append(
            {
                "from": STATE_FORMING,
                "to": STATE_DEGRADED,
                "reason": "replicated-solo boot (no replicas yet)",
                "t": time.time(),
            }
        )
        metrics.gauge(metrics.MULTIHOST_DEGRADED, 1)
        metrics.gauge(metrics.MULTIHOST_STATE, _STATE_CODES[STATE_DEGRADED])
        rt._start_leader_loop()
        return rt

    @property
    def degraded(self) -> bool:
        """PR 5 compatibility view of the lifecycle state machine."""
        return self.state == STATE_DEGRADED

    def _start_leader_loop(self) -> None:
        with self._mu:
            gen = self._loop_gen
        t = threading.Thread(
            target=self._leader_loop, args=(gen,), name="multihost-leader", daemon=True
        )
        self._leader_thread = t
        t.start()

    # -- shared ---------------------------------------------------------------

    def in_gang_thread(self) -> bool:
        return getattr(self._in_gang, "value", False)

    def _enter_gang(self):
        self._in_gang.value = True

    def _exit_gang(self):
        self._in_gang.value = False

    def _degrade_fence(self) -> None:
        """Block (bounded) while a degrade is mid-swap. The moment
        ``state`` reads DEGRADED callers run on the local executor,
        and that is only safe after ``on_degrade`` has swapped it off
        the dead collective plane — so route decisions made during the
        swap wait for it to finish. The degrading thread itself (it
        runs the hook) must never wait on its own fence."""
        if self._degrading and self._degrading_thread != threading.get_ident():
            self._degrade_evt.wait(timeout=self.dispatch_timeout)

    def should_dispatch(self) -> bool:
        """Should work on THIS thread be routed through the gang?
        Leader only, gang alive, and not already inside a gang replay
        (the leader thread and follower loop re-enter the same entry
        points with this flag set). A DEGRADED collective gang refuses
        (PR 5 fail-fast); a DEGRADED replicated gang still dispatches —
        the leader thread applies locally and redundancy returns at the
        next reform()."""
        if not (self.active and self.rank == 0 and not self.in_gang_thread()):
            return False
        self._degrade_fence()
        if self.state == STATE_REFORMING:
            # control messages apply locally-only during the (brief)
            # re-form fence — the rejoin push carries full state anyway,
            # and a 503 on a schema broadcast would fail the peer's op
            return False
        return not (self.degraded and self.mode == MODE_COLLECTIVE)

    def should_dispatch_query(self, remote: bool, query_text: str = "") -> bool:
        """Route decision for executor.execute — the decision table in
        docs/multihost.md:

        * single-plane gang (PR 5): dispatch everything that did NOT
          arrive from another node — the gang replays all state.
        * federated, collective mode: dispatch only the REMOTE legs —
          a top-level query is first split across gangs by the cluster
          plane, and each gang's local leg re-enters with remote=True.
        * federated, replicated mode: reads run directly on the
          leader's local mesh (no lockstep needed); only state-bearing
          legs dispatch, so the leader thread can order and replicate
          them.
        """
        if not (self.active and self.rank == 0 and not self.in_gang_thread()):
            return False
        self._degrade_fence()
        if not self.federated:
            return not remote and not self.degraded
        if self.mode == MODE_COLLECTIVE:
            # degraded-collective: refuse so the cluster plane fails
            # the leg over to a replica gang instead of waiting
            return remote and not self.degraded
        return remote and bool(_WRITE_RE.search(query_text or ""))

    def should_dispatch_import(self, local: bool = False) -> bool:
        """Import routing: a single-plane gang broadcasts the TOP-LEVEL
        import (the gang owns everything); a federated gang lets the
        cluster plane route shard groups first and replays only the
        LOCAL leg (the ``import_*_local`` entry points)."""
        if not (self.active and self.rank == 0 and not self.in_gang_thread()):
            return False
        self._degrade_fence()
        if self.federated:
            if self.mode == MODE_COLLECTIVE and self.degraded:
                return False
            return local
        return (not local) and not self.degraded

    def _set_state(self, to: str, reason: str) -> None:
        with self._mu:
            frm = self.state
            if frm == to:
                return
            self.state = to
            self.transitions.append(
                {"from": frm, "to": to, "reason": reason, "t": time.time()}
            )
            del self.transitions[:-16]
            epoch = self.epoch
        metrics.gauge(metrics.MULTIHOST_DEGRADED, 1 if to == STATE_DEGRADED else 0)
        metrics.gauge(metrics.MULTIHOST_STATE, _STATE_CODES.get(to, -1))
        events.record(
            events.GANG_TRANSITION, frm=frm, to=to, reason=reason, epoch=epoch
        )
        if self.logger is not None:
            self.logger.printf("multihost gang %s -> %s: %s", frm, to, reason)
        hook = self.on_state_change
        if hook is not None:
            try:
                hook(to, epoch)
            except Exception as e:
                if self.logger is not None:
                    self.logger.printf("multihost state-change hook error: %s", e)

    # -- leader ---------------------------------------------------------------

    def dispatch(self, desc: Descriptor, deadline=None) -> Any:
        """Broadcast ``desc`` to the gang and run it in lockstep;
        returns the local (leader) result. Deadline-fenced: expiry or
        ``dispatch_timeout`` — whichever is sooner — degrades the
        runtime and raises GangUnavailable."""
        fut = _Future()
        with self._mu:
            refused = (
                self._closing
                or self._degrading
                or not self.active
                or self.state == STATE_REFORMING
                or (self.state == STATE_DEGRADED and self.mode == MODE_COLLECTIVE)
            )
            if refused:
                raise GangUnavailable("multihost gang is not accepting work")
            self._queue.append((desc, fut))
            self._cond.notify_all()
        # two distinct fences: the REQUEST deadline stops the caller's
        # wait (504, the gang finishes the work and nobody reads it —
        # a slow query must never tear down a healthy gang), while
        # dispatch_timeout is the gang-death verdict (degrade + 503).
        t_dead = time.monotonic() + self.dispatch_timeout
        while not fut.event.wait(timeout=0.05):
            if deadline is not None and deadline.expired():
                deadline.check(metrics.STAGE_GANG)  # raises DeadlineExceeded
            if time.monotonic() >= t_dead:
                # a follower (or the channel) is wedged: the in-flight
                # broadcast may never complete. Fail THIS request
                # cleanly and pull the whole runtime to the local mesh
                # so the next request doesn't re-enter the dead gang.
                self.degrade(
                    "dispatch timed out after %.1fs" % self.dispatch_timeout
                )
                raise GangUnavailable(
                    f"multihost dispatch timed out after "
                    f"{self.dispatch_timeout:.1f}s; degraded to local mesh — retry"
                )
        if fut.error is not None:
            raise fut.error
        return fut.result

    def _leader_loop(self, gen: int = 0) -> None:
        self._enter_gang()
        while True:
            with self._mu:
                while (
                    not self._queue and not self._closing and gen == self._loop_gen
                ):
                    self._cond.wait(timeout=0.5)
                if gen != self._loop_gen:
                    # superseded by a degrade/reform: the queue (and the
                    # channel, if any) belong to the new loop now. A
                    # zombie stuck in a dead collective send never gets
                    # here — it just never touches new work.
                    return
                if self._closing and not self._queue:
                    return
                desc, fut = self._queue.pop(0)
                mode = self.mode
            t0 = time.monotonic()
            if mode == MODE_COLLECTIVE:
                try:
                    self._send(desc.kind, desc.encode())
                except BaseException as e:
                    fut.error = GangUnavailable(f"gang broadcast failed: {e}")
                    fut.event.set()
                    self.degrade(f"broadcast failed: {e}")
                    return
                metrics.observe(
                    metrics.MULTIHOST_BROADCAST_SECONDS, time.monotonic() - t0
                )
            metrics.count(metrics.MULTIHOST_DISPATCHES, role="leader")
            try:
                fut.result = self.apply_fn(desc.kind, desc.payload)
            except BaseException as e:
                fut.error = e
            if (
                mode == MODE_REPLICATED
                and desc.kind != KIND_TICK
                and fut.error is None
            ):
                self._replicate(desc)
            fut.event.set()

    def _replicate(self, desc: Descriptor) -> None:
        """Replicated-mode fan-out: apply the descriptor on every gang
        follower over HTTP, epoch-stamped so a stale (pre-re-form)
        follower can never apply post-re-form work. A follower that
        still fails after the client's own retries is dropped from the
        gang and the lifecycle returns to DEGRADED — the leader keeps
        serving solo, and the follower must rejoin (with a fresh state
        sync) to count again."""
        if self.replicate_fn is None:
            return
        with self._mu:
            targets = list(self._replicas)
            epoch = self.epoch
        for uri in targets:
            try:
                self.replicate_fn(uri, desc.kind, desc.payload, epoch)
            except Exception as e:
                with self._mu:
                    if uri in self._replicas:
                        self._replicas.remove(uri)
                metrics.count(metrics.MULTIHOST_ABORTS, role="replica")
                self._set_state(STATE_DEGRADED, f"replica {uri} lost: {e}")

    def _send(self, kind: int, payload: bytes) -> None:
        self.channel.send(encode_message(kind, payload, self.frame_bytes))
        self._last_send = time.monotonic()

    def _tick_loop(self) -> None:
        """Idle ticks from a side thread, but SENT by the leader thread
        via the queue — one thread owns the channel, so a tick can
        never interleave with a work message's frames."""
        while True:
            time.sleep(self.idle_interval / 2.0)
            with self._mu:
                # ticks only feed collective follower loops; a
                # replicated gang has no collective to keep alive
                if self._closing or self.degraded or self.mode != MODE_COLLECTIVE:
                    return
                busy = bool(self._queue)
            if busy or time.monotonic() - self._last_send < self.idle_interval:
                continue
            fut = _Future()
            desc = Descriptor(KIND_TICK, {"t": time.time()})
            with self._mu:
                if self._closing or self.degraded or self.mode != MODE_COLLECTIVE:
                    return
                self._queue.append((desc, fut))
                self._cond.notify_all()
            # tick RTT ≈ broadcast latency with an idle gang; a tick
            # that never completes means the gang is dead — degrade so
            # the next real query fails fast instead of paying the
            # full dispatch timeout
            if not fut.event.wait(timeout=self.dispatch_timeout):
                self.degrade("idle tick timed out")
                return
            metrics.count(metrics.MULTIHOST_TICKS)

    # -- follower -------------------------------------------------------------

    def serve_follower(self) -> str:
        """Run the follower loop on the calling thread until poison or
        leader loss; returns the stop reason."""
        self._enter_gang()
        try:
            self.follower = GangFollower(
                self.channel,
                self._apply_follower,
                leader_timeout=self.leader_timeout,
                on_result=None,
            )
            return self.follower.run()
        finally:
            self._exit_gang()

    def _apply_follower(self, kind: int, payload: dict) -> Any:
        return self.apply_fn(kind, payload)

    # -- failure / lifecycle --------------------------------------------------

    def degrade(self, reason: str) -> None:
        """Fence the gang: fail queued work, stop collective dispatch,
        and hand the executor a local mesh via ``on_degrade``.
        Idempotent. A non-federated runtime stays DEGRADED until
        process restart (PR 5 semantics); a federated runtime
        immediately re-enters service in replicated-solo mode — the
        cluster plane advertises DEGRADED so peers prefer other
        replicas, and reform() restores ACTIVE when a follower
        rejoins."""
        with self._mu:
            if self._degrading or self.state in (STATE_DEGRADED, STATE_REFORMING):
                return
            # fence BEFORE the state flip: dispatch refuses new work and
            # route decisions wait in _degrade_fence until on_degrade has
            # swapped the executor — if state read DEGRADED first, a
            # query could run locally on the dead collective plane
            # (observed: post-degrade Count on the global mesh → 'Gloo
            # all-reduce failed: Connection reset by peer')
            self._degrading = True
            self._degrading_thread = threading.get_ident()
            self._degrade_evt.clear()
            stale, self._queue = self._queue, []
            self._loop_gen += 1  # a wedged leader loop must not touch new work
        for _, fut in stale:
            fut.error = GangUnavailable(f"multihost gang degraded: {reason}")
            fut.event.set()
        metrics.count(metrics.MULTIHOST_ABORTS, role="leader")
        try:
            if self.on_degrade is not None:
                try:
                    self.on_degrade()
                except Exception as e:
                    if self.logger is not None:
                        self.logger.printf("multihost degrade hook error: %s", e)
        finally:
            self._set_state(STATE_DEGRADED, reason)
            events.record(events.GANG_DEGRADE, reason=reason, epoch=self.epoch)
            with self._mu:
                self._degrading = False
                self._degrading_thread = None
            self._degrade_evt.set()
        if self.federated and self.active and self.rank == 0:
            # keep serving: replicated-solo on the local mesh the
            # degrade hook just installed. Writes apply locally-only;
            # redundancy returns via reform() on follower rejoin.
            with self._mu:
                if self._closing:
                    return
                self.mode = MODE_REPLICATED
                self._replicas = []
            self._start_leader_loop()

    def reform(self, replicas: list[str], reason: str = "follower rejoin") -> dict:
        """Re-form the gang around HTTP replication (leader only):
        fence in-flight dispatches, bump the epoch (the fence that
        keeps plan caches, delta logs, and stale repliers from
        replaying pre-failure state), run the ``on_reform`` state
        hooks, register the follower set, and return to ACTIVE in
        replicated mode. Valid from DEGRADED (the normal path after a
        follower death), from ACTIVE-replicated (another follower
        joining), or from ACTIVE-collective (operator-forced: the
        collective plane is abandoned for HTTP replication)."""
        if not (self.active and self.rank == 0):
            raise GangUnavailable("gang re-formation is a leader-side operation")
        with self._mu:
            if self._closing:
                raise GangUnavailable("multihost runtime is closing")
            stale, self._queue = self._queue, []
            self._loop_gen += 1
        for _, fut in stale:
            fut.error = GangUnavailable("multihost gang re-forming — retry")
            fut.event.set()
        self._set_state(STATE_REFORMING, reason)
        with self._mu:
            self.epoch += 1
            epoch = self.epoch
        metrics.gauge(metrics.MULTIHOST_EPOCH, epoch)
        if self.on_reform is not None:
            try:
                self.on_reform()
            except Exception as e:
                if self.logger is not None:
                    self.logger.printf("multihost reform hook error: %s", e)
        with self._mu:
            self.mode = MODE_REPLICATED
            self._replicas = list(replicas)
        self._set_state(
            STATE_ACTIVE, f"re-formed at epoch {epoch} ({len(replicas)} replicas)"
        )
        events.record(
            events.GANG_REFORM,
            reason=reason,
            epoch=epoch,
            replicas=len(replicas),
        )
        metrics.count(metrics.MULTIHOST_REFORMS)
        self._start_leader_loop()
        return {"epoch": epoch, "state": self.state, "mode": self.mode}

    def health(self) -> dict:
        """The gang block for /status: lifecycle at a glance."""
        with self._mu:
            last = self.transitions[-1] if self.transitions else None
            return {
                "state": self.state,
                "mode": self.mode,
                "epoch": self.epoch,
                "replicas": list(self._replicas),
                "lastTransition": dict(last) if last else None,
            }

    def close(self) -> None:
        """Leader: drain the queue, broadcast the poison pill so
        followers exit their loop, stop the threads. Follower: no-op
        (the loop exits on the pill)."""
        with self._mu:
            if self._closing:
                return
            self._closing = True
            self._cond.notify_all()
        degraded_collective = self.degraded and self.mode == MODE_COLLECTIVE
        if self.rank == 0 and self.active and not degraded_collective:
            if self._leader_thread is not None:
                self._leader_thread.join(timeout=self.dispatch_timeout)
                if self._leader_thread.is_alive():
                    # the leader thread still owns the channel (a work
                    # message may be mid-frame) — interleaving the pill
                    # would desync framing; followers fall back to
                    # their own leader timeout instead
                    return
            if self.mode == MODE_COLLECTIVE:
                try:
                    self._send(KIND_POISON, b"")
                except Exception:
                    pass  # followers fall back to their own leader timeout

    def stats(self) -> dict:
        f = self.follower
        return {
            "rank": self.rank,
            "world": self.world,
            "active": self.active,
            "degraded": self.degraded,
            "state": self.state,
            "mode": self.mode,
            "epoch": self.epoch,
            "federated": self.federated,
            "replicas": list(self._replicas),
            "transitions": [dict(t) for t in self.transitions[-5:]],
            "queue_depth": len(self._queue),
            "follower": None
            if f is None
            else {
                "ticks": f.ticks,
                "works": f.works,
                "errors": f.errors,
                "last_lag_s": f.last_lag,
                "stopped_reason": f.stopped_reason,
            },
        }


class _Future:
    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None


# -- server glue -------------------------------------------------------------


def make_apply_fn(server) -> Callable[[int, dict], Any]:
    """The one place descriptor kinds map to server-side execution.
    Used identically by the leader thread and the follower loop — both
    re-enter the normal entry points with the gang thread-local set, so
    the dispatch hooks pass through and every rank runs the same code
    path on the same data."""

    def apply(kind: int, payload: dict) -> Any:
        if kind == KIND_QUERY:
            opt_kw = payload.get("opt") or {}

            def run():
                return server.executor.execute(
                    payload["index"],
                    payload["query"],
                    payload.get("shards"),
                    _gang_opt(
                        exclude_row_attrs=opt_kw.get("exclude_row_attrs", False),
                        exclude_columns=opt_kw.get("exclude_columns", False),
                        remote=opt_kw.get("remote", False),
                    ),
                )

            ctx = trace.parse_traceparent(payload.get("trace"))
            if ctx is None or not ctx[2]:
                # untraced (or unsampled) dispatch: propagate the bare
                # context span-free — the zero-allocation contract holds
                with trace.push_ctx(ctx):
                    return run()
            # sampled: this rank's replay becomes a span under the
            # ORIGINATING trace id, rank/epoch/pid-tagged, recorded in
            # this process's ring AND shipped to the trace owner so the
            # root process stitches one complete tree
            mh = server.multihost
            sp = trace.TRACER.trace(
                metrics.STAGE_MH_REPLAY,
                ctx=ctx,
                rank=mh.rank if mh is not None else getattr(server, "_mh_rank", 0),
                epoch=mh.epoch if mh is not None else getattr(server, "gang_epoch", 0),
                pid=os.getpid(),
                plan=payload.get("plan"),
            )
            try:
                with sp:
                    return run()
            finally:
                _ship_replay_span(server, sp)
        if kind == KIND_IMPORT:
            # federated legs carry local=True: the cluster plane already
            # routed the shard group here (and translated any keys), so
            # the replay must apply as-is, never re-route
            if payload.get("local"):
                server.api.import_bits_local(
                    payload["index"],
                    payload["field"],
                    payload["row_ids"],
                    payload["column_ids"],
                    payload.get("timestamps"),
                )
            else:
                server.api.import_bits(
                    payload["index"],
                    payload["field"],
                    payload["row_ids"],
                    payload["column_ids"],
                    payload.get("timestamps"),
                    payload.get("row_keys"),
                    payload.get("column_keys"),
                )
            return None
        if kind == KIND_IMPORT_VALUES:
            if payload.get("local"):
                server.api.import_values_local(
                    payload["index"],
                    payload["field"],
                    payload["column_ids"],
                    payload["values"],
                )
            else:
                server.api.import_values(
                    payload["index"],
                    payload["field"],
                    payload["column_ids"],
                    payload["values"],
                    payload.get("column_keys"),
                )
            return None
        if kind == KIND_WRITE_WAVE:
            # coalesced ingest write wave: shard groups were routed by
            # the cluster plane (if any) before the gang saw the wave,
            # so every rank applies the local leg as-is — one group
            # commit + one generation bump per touched fragment
            server.api.apply_write_wave_local(
                payload["index"],
                payload["field"],
                payload["row_ids"],
                payload["column_ids"],
                payload.get("sets"),
            )
            return None
        if kind == KIND_MESSAGE:
            server.receive_message(payload)
            return None
        raise ValueError(f"unknown descriptor kind: {kind}")

    return apply


def _replay_push_target(server) -> str:
    """Where this process ships replay spans: '' on the trace-owning
    gang leader (local graft), else the leader's HTTP URI — learned
    from the boot-time leader-uri broadcast (collective followers) or
    the rejoin config (replicated followers)."""
    mh = server.multihost
    if mh is not None and mh.rank == 0:
        return ""
    if mh is not None and mh.leader_uri:
        return mh.leader_uri
    return getattr(server.config, "federation_rejoin", "") or ""


def _ship_replay_span(server, sp) -> None:
    """Deliver one completed replay span to the trace owner's stitch
    buffer. Best-effort: span shipping must never fail (or slow) the
    replay itself."""
    if sp is trace.NOP_SPAN or not getattr(sp, "trace_id", ""):
        return
    try:
        d = sp.to_dict()
        target = _replay_push_target(server)
        if not target:
            # leader rank: the HTTP root span lives in this process —
            # graft straight into the local stitch buffer
            trace.TRACER.graft_remote(sp.trace_id, [d])
            return
        from pilosa_tpu.parallel.client import InternalClient

        InternalClient(
            timeout=5.0, ssl_context=server.client_ssl_context()
        ).push_spans(target, sp.trace_id, [d])
    except Exception:
        pass


def _gang_opt(**kw):
    """ExecOptions for gang execution: serial (identical collective
    issue order on every rank — a read pool's interleaving would
    deadlock the mesh) and cache-bypassing (per-rank plan-cache state
    would diverge and change which kernels run)."""
    from pilosa_tpu.executor import ExecOptions

    return ExecOptions(cache=False, serial=True, **kw)
