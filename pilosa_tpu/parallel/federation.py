"""Sharded gang federation (ISSUE 7): compose the gang plane with the
cluster plane so N independent ``jax.distributed`` gangs together serve
one index, and a rank death ends in re-formation instead of PR 5's
degrade-forever.

Topology
--------
``cluster.hosts`` lists the gang LEADER URIs — each leader is one
cluster node, and jump-hash places shards on leaders exactly as it
places them on plain nodes. A top-level query splits across gangs in
``cluster.map_reduce``: the LOCAL leg re-enters the executor through
``cluster.local_executor`` (wired here) with ``remote=True`` so this
gang's runtime replays it on every rank of THIS gang only; REMOTE legs
fan out over :class:`InternalClient` to the owner leader's query
endpoint and merge through the existing Row/TopN/BSI reducers.

Lifecycle
---------
Follower death fences in-flight dispatches (bounded 503), the leader
marks itself DEGRADED in the cluster plane (peers stop routing writes
to it, reads prefer other owners), then keeps serving replicated-solo.
A restarted follower boots with ``federation-rejoin = <leader>`` and
announces itself; the leader re-forms around it — anti-entropy catch-up,
schema + fragment push, epoch bump (the fence that keeps plan caches
and stale repliers from replaying pre-failure state) — and rejoins
ACTIVE in replicated mode. No path stays degraded forever.
"""

from __future__ import annotations

import time


def _client(server, timeout: float = 30.0):
    from pilosa_tpu.parallel.client import InternalClient

    cfg = server.config
    return InternalClient(
        timeout=timeout,
        ssl_context=server.client_ssl_context(),
        retries=cfg.client_retries,
        retry_backoff=cfg.client_retry_backoff,
    )


def wire(server) -> None:
    """Connect the two planes on a gang leader: the cluster plane gets
    a gang-replaying local executor, the gang runtime gets its
    federation hooks (replication, epoch fencing, state gossip)."""
    mh, cluster, ex = server.multihost, server.cluster, server.executor
    if mh is None or cluster is None:
        return
    mh.federated = True
    cfg = server.config
    # cross-gang legs retry transient failures / fencing 503s
    cluster.client.retries = cfg.client_retries
    cluster.client.retry_backoff = cfg.client_retry_backoff

    from pilosa_tpu.executor.executor import ExecOptions

    def local_executor(index, c, shards, opt):
        # remote=True: the cluster plane already routed this leg here,
        # so the gang replays it without re-splitting across gangs.
        # Plain (non-gang) options — the dispatch hook swaps in the
        # serial/cache-bypassing _gang_opt at replay time.
        o = ExecOptions(
            remote=True,
            exclude_row_attrs=getattr(opt, "exclude_row_attrs", False),
            exclude_columns=getattr(opt, "exclude_columns", False),
        )
        res = ex.execute(index, str(c), shards, o)
        if not res:
            return None
        # remote-mode results come back in wire shape (TopN returns
        # id/count dicts, executor._execute_topn) — decode exactly like
        # a remote leg so map_reduce merges one representation
        return cluster._decode_remote(c, res[0])

    cluster.local_executor = local_executor

    def replicate(uri: str, kind: int, payload: dict, epoch: int) -> None:
        # kind-agnostic: queries, imports, and coalesced ingest write
        # waves (KIND_WRITE_WAVE) all cross as one epoch-fenced frame;
        # waves committed while a follower is fenced reach it later
        # through the rejoin anti-entropy catch-up below
        cluster.client.gang_apply(uri, kind, payload, epoch)

    mh.replicate_fn = replicate
    # epoch fence on re-form: results, plans, and scorer state computed
    # against the pre-failure mesh must not survive into the new epoch
    mh.on_reform = ex._on_device_restore
    mh.on_state_change = cluster.announce_gang_state
    # seed peers immediately — a replicated-solo restart must advertise
    # DEGRADED before the first query routes to it
    cluster.announce_gang_state(mh.state, mh.epoch)


def _pull_missing_fragments(server) -> int:
    """Rejoin-time catch-up, part 1: materialize locally-owned
    fragments that were CREATED on peer replicas while this gang was
    fenced — ``sync_holder`` only block-diffs fragments that already
    exist locally, so a brand-new fragment would otherwise never
    arrive and post-re-form reads of it would be silently empty."""
    cluster, holder = server.cluster, server.holder
    if cluster is None or cluster.replica_n < 2:
        return 0
    pulled = 0
    for node in cluster._other_nodes():
        try:
            inventory = cluster.client.fragment_inventory(node.uri)
        except Exception:
            continue
        for ent in inventory:
            iname, fname = ent["index"], ent["field"]
            vname, shard = ent["view"], ent["shard"]
            owners = cluster.shard_nodes(iname, shard)
            if not any(n.id == cluster.node_id for n in owners):
                continue
            if holder.fragment(iname, fname, vname, shard) is not None:
                continue
            try:
                data = cluster.client.retrieve_fragment(
                    node.uri, iname, fname, vname, shard
                )
                server.api.unmarshal_fragment(iname, fname, vname, shard, data)
                pulled += 1
            except Exception as e:
                server.logger.printf(
                    "rejoin: fragment pull %s/%s/%s/%d from %s failed: %s",
                    iname, fname, vname, shard, node.uri, e,
                )
    return pulled


def handle_rejoin(server, follower_uri: str) -> dict:
    """Leader-side re-formation (POST /internal/gang/rejoin). Order
    matters: (1) anti-entropy catch-up for writes that routed around
    this gang while it fenced, (2) schema push so the follower can host
    fragments, (3) fragment push, (4) ``reform()`` — fence, epoch bump,
    ACTIVE. Writes landing during the push window re-converge through
    the next anti-entropy sweep."""
    from pilosa_tpu.server.api import APIError

    mh, cluster, api = server.multihost, server.cluster, server.api
    if mh is None or not mh.federated:
        raise APIError("not a federated gang leader")
    t0 = time.monotonic()
    if cluster is not None:
        try:
            _pull_missing_fragments(server)
            cluster.sync_holder()
        except Exception as e:
            server.logger.printf("rejoin: pre-re-form anti-entropy failed: %s", e)
    client = cluster.client if cluster is not None else _client(server)
    client.send_message(
        follower_uri, {"type": "schema", "schema": server.holder.schema()}
    )
    pushed = 0
    for frag in api.fragment_inventory():
        data = api.marshal_fragment(
            frag["index"], frag["field"], frag["view"], frag["shard"]
        )
        client.send_fragment(
            follower_uri,
            frag["index"],
            frag["field"],
            frag["view"],
            frag["shard"],
            data,
        )
        pushed += 1
    # merge with any followers already serving: a second rejoin must
    # not evict the first
    replicas = [u for u in mh.health()["replicas"] if u != follower_uri]
    replicas.append(follower_uri)
    fleet = getattr(server, "fleet", None)
    if fleet is not None:
        # the re-staged replica is a fleet member again: its registry
        # shows up in the leader's /metrics?fleet=true on the next scrape
        fleet.register(follower_uri, gang=server.config.distributed_coordinator)
    out = mh.reform(replicas, reason=f"follower {follower_uri} rejoined")
    out["fragments"] = pushed
    out["reformSeconds"] = round(time.monotonic() - t0, 3)
    server.logger.printf(
        "gang re-formed around %s: epoch %d, %d fragments, %.2fs",
        follower_uri,
        out["epoch"],
        pushed,
        out["reformSeconds"],
    )
    return out


def rejoin_follower(server, leader_uri: str) -> bool:
    """Follower boot path (``federation-rejoin``): announce this
    re-staged process to its gang leader and adopt the new epoch.
    Retries across the re-form budget — the leader may itself still be
    coming up or fencing. Returns True once rejoined."""
    budget = server.config.federation_reform_budget
    client = _client(server, timeout=max(budget, 10.0))
    t_dead = time.monotonic() + budget
    while True:
        try:
            resp = client.gang_rejoin(leader_uri, server.uri)
            break
        except Exception as e:
            if time.monotonic() >= t_dead:
                server.logger.printf(
                    "federation rejoin to %s failed after %.1fs: %s",
                    leader_uri,
                    budget,
                    e,
                )
                return False
            time.sleep(0.25)
    server.gang_epoch = int(resp.get("epoch", 0))
    if server.multihost is not None:
        # replay spans from this process push to the leader's stitch
        # buffer; fleet registration makes this rank scrapeable
        server.multihost.leader_uri = leader_uri
    try:
        client.fleet_register(
            leader_uri,
            server.uri,
            rank=getattr(server, "_mh_rank", -1),
            gang=server.config.distributed_coordinator,
        )
    except Exception:
        pass
    server.logger.printf(
        "rejoined gang at %s: epoch %d", leader_uri, server.gang_epoch
    )
    return True


def start_rejoin(server):
    """Run the rejoin announcement off-thread so ``open()`` returns and
    the HTTP listener can answer the leader's schema/fragment push —
    the rejoin RPC and the push it triggers would deadlock a single
    thread."""
    import threading

    t = threading.Thread(
        target=rejoin_follower,
        args=(server, server.config.federation_rejoin),
        name="federation-rejoin",
        daemon=True,
    )
    t.start()
    return t
