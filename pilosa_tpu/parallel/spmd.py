"""SPMD query execution over a device mesh (L5 compute plane).

The reference distributes per-shard work with one goroutine per shard
and HTTP scatter-gather between nodes (reference executor.go:1444-1593,
http/client.go). On TPU the same distribution is a *sharding*: fragments
stack into ``uint32[shards, rows, words]`` laid out over a 1-D mesh
axis ``"shards"`` and the cross-shard reduce runs as XLA collectives
inside the compiled program — ``psum`` over ICI for Count/Sum (the
reference's uint64-sum reduceFn), ``all_gather`` for TopN candidate
sets (the reference's Pairs.Add merge) — instead of HTTP fan-out.

The only parallel axis of a bitmap index is the shard (column) axis:
SURVEY.md §2.5 — data parallelism = shard partitioning; rows are never
split. Tensor/pipeline parallelism have no analog here; the mesh is 1-D
by design, scaling to multi-host by making the "shards" axis span hosts
(DCN hops ride the same collectives).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

if not hasattr(jax, "shard_map"):
    # jax < 0.5 ships shard_map under experimental, where the replication
    # checker kwarg is spelled check_rep instead of check_vma; adapt so
    # the kernels below read against the stable spelling
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, **kw)

    jax.shard_map = _shard_map

SHARD_AXIS = "shards"


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over the shard axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_spec() -> P:
    return P(SHARD_AXIS)


def mesh_is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh places shards on devices owned by another
    process (a jax.distributed global mesh)."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def put_sharded(mesh: Mesh, arr: np.ndarray):
    """Place a [S, ...] host array with the leading dim split over the
    mesh — the HBM staging step for a shard batch.

    On a multi-process (jax.distributed) mesh, ``device_put`` cannot
    target non-addressable devices; every process holds the identical
    full host array (the gang replays the same staging on every rank),
    so each process contributes its addressable slices via
    ``make_array_from_callback`` and the result is one global array."""
    sharding = NamedSharding(mesh, P(SHARD_AXIS))
    if mesh_is_multiprocess(mesh):
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )
    return jax.device_put(arr, sharding)


# -- SPMD kernels ------------------------------------------------------------
# Each takes shard-major stacked operands. Written against shard_map so
# the collective structure is explicit (psum/all_gather over ICI).


def count_fold_spmd(mesh: Mesh):
    """Count(Intersect(rows...)) over all shards in one program.

    stacked: u32[S, K, W] (K child rows per shard) -> i32 global count.
    AND-fold + popcount locally, then psum over the shard axis — the
    reference's executeCount sum-reduce (executor.go:966-996) as an ICI
    collective.
    """

    def kernel(block):  # block: u32[s_local, K, W] per device
        folded = jax.lax.reduce(
            block, jnp.uint32(0xFFFFFFFF), jnp.bitwise_and, (1,)
        )  # [s_local, W]
        local = jnp.sum(jax.lax.population_count(folded).astype(jnp.int32))
        return jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS),),
            out_specs=P(),
        )
    )


def topn_spmd(mesh: Mesh, k: int):
    """TopN candidate generation over all shards in one program.

    src: u32[S, W]; mat: u32[S, R, W] -> (ids i32[S*k], counts i32[S*k])
    on every device: per-shard intersection scores + local top-k, then
    all_gather of the candidate sets — the reference's two-pass TopN
    candidate exchange (executor.go:521-561) riding ICI instead of HTTP.
    The host performs the exact re-score pass (pass 2) as the reference
    does.
    """

    def kernel(src, mat):
        # per-device: src u32[s_local, W], mat u32[s_local, R, W]
        scores = jnp.sum(
            jax.lax.population_count(
                jnp.bitwise_and(mat, src[:, None, :])
            ).astype(jnp.int32),
            axis=-1,
        )  # [s_local, R]
        counts, ids = jax.lax.top_k(scores, k)  # [s_local, k] each
        counts = jax.lax.all_gather(counts.reshape(-1), SHARD_AXIS, tiled=True)
        ids = jax.lax.all_gather(ids.reshape(-1), SHARD_AXIS, tiled=True)
        return ids, counts

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=(P(), P()),
            # all_gather's replicated output can't be statically inferred
            # by the varying-manual-axes checker; results are replicated
            # by construction.
            check_vma=False,
        )
    )


def topn_batch_spmd(mesh: Mesh, k: int):
    """Batched TopN candidate generation: Q concurrent query sources
    scored against every shard in one program (the SPMD form of
    executor/batcher.py's continuous micro-batching — the shard matrix
    streams from HBM once per batch, per device).

    srcs: u32[Q, W] (replicated); mat: u32[S, R, W] (shard-sharded)
    -> (ids i32[Q, S*k], counts i32[Q, S*k]) replicated on every device.
    """

    def kernel(srcs, mat):
        # per-device: srcs u32[Q, W], mat u32[s_local, R, W].
        # lax.map over sources keeps the popcount intermediate at one
        # [s_local, R, W] buffer instead of Q of them (same trade as
        # ops.intersection_counts_matrix_batch).
        def one(src):
            return jnp.sum(
                jax.lax.population_count(
                    jnp.bitwise_and(mat, src[None, None, :])
                ).astype(jnp.int32),
                axis=-1,
            )  # [s_local, R]

        scores = jax.lax.map(one, srcs)  # [Q, s_local, R]
        q = scores.shape[0]
        counts, ids = jax.lax.top_k(scores, k)  # [Q, s_local, k]
        counts = jax.lax.all_gather(
            counts.reshape(q, -1), SHARD_AXIS, axis=1, tiled=True
        )
        ids = jax.lax.all_gather(ids.reshape(q, -1), SHARD_AXIS, axis=1, tiled=True)
        return ids, counts

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(), P(SHARD_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def count_stack_spmd(mesh: Mesh):
    """Global popcount of a shard-sharded word stack in one program.

    words: u32[S, W] (leading dim split over the mesh) -> i32 global
    count. This is the serving executor's batched Count terminal: the
    bitmap subtree has already folded elementwise (sharding-preserving),
    so the only collective is the final psum — the reference's
    uint64-sum reduceFn (executor.go:966-996) riding ICI.
    """

    def kernel(block):  # u32[s_local, W]
        local = jnp.sum(jax.lax.population_count(block).astype(jnp.int32))
        return jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(
        jax.shard_map(kernel, mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P())
    )


def topn_scores_sparse_spmd(mesh: Mesh, k: int):
    """Block-sparse per-shard TopN candidate scoring across the mesh.

    A dense form would stage every candidate row at 128 KB regardless
    of sparsity — at a 50k-candidate ranked cache that is tens of GB
    of staging per query (SURVEY.md §7 hard part 2). Here each shard
    stages only its candidates' nonempty 2^16-bit container blocks,
    padded to a common per-shard block count:

      srcs:   u32[S, W]        per-shard source bitmap (shard-sharded)
      blocks: u32[S, B, 2048]  per-shard candidate container blocks
      brow:   i32[S, B]        local candidate index per block
      bslot:  i32[S, B]        container position within the row

    Padding blocks are zero words aimed at (row 0, slot 0) and
    contribute nothing to an intersection. Returns i32[S, k] scores
    replicated everywhere via all_gather (the reference's HTTP Pairs
    exchange, executor.go:563-585, riding ICI). k is static; callers
    use pow2 chunk sizes so the compile cache stays bounded.
    """
    from pilosa_tpu.ops.packed import CONTAINER_WORDS

    def kernel(srcs, blocks, brow, bslot):
        # per-device: srcs u32[s_local, W], blocks u32[s_local, B, 2048]
        per_shard = srcs.reshape(srcs.shape[0], -1, CONTAINER_WORDS)

        def one(src_blocks, blk, br, bs):
            src_blk = src_blocks[bs]  # [B, 2048]
            pc = jax.lax.population_count(jnp.bitwise_and(blk, src_blk))
            per_block = jnp.sum(pc.astype(jnp.int32), axis=-1)
            return jax.ops.segment_sum(per_block, br, num_segments=k)

        scores = jax.vmap(one)(per_shard, blocks, brow, bslot)  # [s_local, k]
        return jax.lax.all_gather(scores, SHARD_AXIS, axis=0, tiled=True)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=P(),
            check_vma=False,
        )
    )


def bsi_sum_spmd(mesh: Mesh, bit_depth: int, has_filter: bool = True):
    """Sum(field) over all shards: per-plane popcounts psum'd over ICI.

    planes: u32[S, D+1, W], filter: u32[S, W]. Returns i32[D+1] global
    per-plane counts; host computes Σ counts[i]<<i in exact Python ints.
    has_filter is static: an unfiltered Sum counts the planes directly
    (the reference's fragment.sum with nil filter) rather than ANDing
    with an all-ones mask.
    """

    def kernel(planes, filt):
        block = (
            jnp.bitwise_and(planes, filt[:, None, :]) if has_filter else planes
        )  # [s_local, D+1, W]
        local = jnp.sum(
            jax.lax.population_count(block).astype(jnp.int32), axis=(0, 2)
        )  # [D+1]
        return jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=P(),
        )
    )


def row_algebra_spmd(mesh: Mesh, op: str):
    """Materialising bitmap algebra across shards: fold K rows per shard
    elementwise; result stays sharded (each device keeps its shard's
    result segment — no collective, like the reference's per-node Row
    segments that only merge at the coordinator)."""

    from pilosa_tpu.ops.packed import fold_rows

    def kernel(mat):  # u32[s_local, K, W]
        if op == "and":
            init, fn = jnp.uint32(0xFFFFFFFF), jnp.bitwise_and
        elif op == "or":
            init, fn = jnp.uint32(0), jnp.bitwise_or
        else:
            init, fn = jnp.uint32(0), jnp.bitwise_xor
        return jax.lax.reduce(mat, init, fn, (1,))

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS),),
            out_specs=P(SHARD_AXIS),
        )
    )


class ShardBatchPlan:
    """Host-side packing of a set of fragments into one shard-major batch.

    Pads the shard list to the mesh size (empty shards contribute zero
    words — identical results, since AND with missing shard never occurs:
    padding shards carry no query rows and reduce as zeros).
    """

    def __init__(self, mesh: Mesh, shards: list[int]) -> None:
        self.mesh = mesh
        self.n_devices = mesh.devices.size
        self.shards = list(shards)
        pad = (-len(self.shards)) % self.n_devices
        self.padded = self.shards + [-1] * pad

    def stack_rows(self, words_by_shard: dict[int, np.ndarray], width: int) -> np.ndarray:
        """words_by_shard: shard -> u32[K, W]; missing/padding → zeros."""
        k = max((w.shape[0] for w in words_by_shard.values()), default=1)
        out = np.zeros((len(self.padded), k, width), dtype=np.uint32)
        for i, s in enumerate(self.padded):
            w = words_by_shard.get(s)
            if w is not None:
                out[i, : w.shape[0]] = w
        return out
