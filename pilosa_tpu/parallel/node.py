"""Cluster node identity + URI (reference pilosa.Node / uri.go)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field


# Validation shapes follow reference uri.go:28-30: scheme is lowercase
# letters plus '+', host is hostname chars or a bracketed IPv6 literal.
_SCHEME_RE = re.compile(r"^[+a-z]+$")
_HOST_RE = re.compile(r"^[0-9a-z.-]+$|^\[[:0-9a-fA-F]+\]$")
_ADDRESS_RE = re.compile(
    r"^(?:(?P<scheme>[+a-z]+)://)?"
    r"(?P<host>[0-9a-z.-]+|\[[:0-9a-fA-F]+\])?"
    r"(?::(?P<port>[0-9]+))?$"
)


@dataclass
class URI:
    """Scheme/host/port triple (reference uri.go:45-264).

    All parts optional when parsing: ``http://localhost:10101``,
    ``localhost``, and ``:10101`` are equivalent spellings.
    """

    scheme: str = "http"
    host: str = "localhost"
    port: int = 10101

    @classmethod
    def from_address(cls, addr: str) -> "URI":
        m = _ADDRESS_RE.fullmatch(addr.strip())
        if m is None or (not m.group("host") and m.group("port") is None and not m.group("scheme")):
            raise ValueError(f"invalid address: {addr!r}")
        port = int(m.group("port") or 10101)
        if port > 0xFFFF:
            raise ValueError(f"invalid address: {addr!r} (port out of range)")
        return cls(
            scheme=m.group("scheme") or "http",
            host=m.group("host") or "localhost",
            port=port,
        )

    def set_scheme(self, scheme: str) -> None:
        if not _SCHEME_RE.fullmatch(scheme):
            raise ValueError(f"invalid scheme: {scheme!r}")
        self.scheme = scheme

    def set_host(self, host: str) -> None:
        if not _HOST_RE.fullmatch(host):
            raise ValueError(f"invalid host: {host!r}")
        self.host = host

    def __str__(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"

    def host_port(self) -> str:
        return f"{self.host}:{self.port}"

    def normalize(self) -> str:
        """Address usable by an HTTP client: a ``+``-qualified scheme
        (e.g. ``https+pb``) drops its qualifier (reference uri.go:135-142)."""
        scheme = self.scheme.split("+", 1)[0]
        return f"{scheme}://{self.host}:{self.port}"

    def path(self, p: str) -> str:
        return f"{self.normalize()}{p}"

    def to_dict(self) -> dict:
        return {"scheme": self.scheme, "host": self.host, "port": self.port}

    @classmethod
    def from_dict(cls, d: dict) -> "URI":
        return cls(
            scheme=d.get("scheme", "http"),
            host=d.get("host", "localhost"),
            port=int(d.get("port", 10101)),
        )


@dataclass
class Node:
    id: str
    uri: str  # http://host:port
    is_coordinator: bool = False
    state: str = "READY"

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d["id"],
            uri=d["uri"],
            is_coordinator=d.get("isCoordinator", False),
            state=d.get("state", "READY"),
        )
