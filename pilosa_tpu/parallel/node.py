"""Cluster node identity (reference pilosa.Node); URI lives in
utils/uri.py and is re-exported here for back-compat."""

from __future__ import annotations

from dataclasses import dataclass

from pilosa_tpu.utils.uri import URI

__all__ = ["Node", "URI"]


@dataclass
class Node:
    id: str
    uri: str  # http://host:port
    is_coordinator: bool = False
    state: str = "READY"

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d["id"],
            uri=d["uri"],
            is_coordinator=d.get("isCoordinator", False),
            state=d.get("state", "READY"),
        )
