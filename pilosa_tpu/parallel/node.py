"""Cluster node identity + URI (reference pilosa.Node / uri.go)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass
class URI:
    scheme: str = "http"
    host: str = "localhost"
    port: int = 10101

    @classmethod
    def from_address(cls, addr: str) -> "URI":
        m = re.fullmatch(
            r"(?:(?P<scheme>[a-z][a-z0-9+.-]*)://)?(?P<host>[^:/]*)(?::(?P<port>\d+))?",
            addr.strip(),
        )
        if m is None or (m.group("host") == "" and m.group("port") is None):
            raise ValueError(f"invalid address: {addr!r}")
        return cls(
            scheme=m.group("scheme") or "http",
            host=m.group("host") or "localhost",
            port=int(m.group("port") or 10101),
        )

    def __str__(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"

    def host_port(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class Node:
    id: str
    uri: str  # http://host:port
    is_coordinator: bool = False
    state: str = "READY"

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d["id"],
            uri=d["uri"],
            is_coordinator=d.get("isCoordinator", False),
            state=d.get("state", "READY"),
        )
