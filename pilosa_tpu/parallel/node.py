"""Cluster node identity (reference pilosa.Node); URI lives in
utils/uri.py and is re-exported here for back-compat."""

from __future__ import annotations

from dataclasses import dataclass

from pilosa_tpu.utils.uri import URI

__all__ = ["Node", "URI"]


@dataclass
class Node:
    id: str
    uri: str  # http://host:port
    is_coordinator: bool = False
    state: str = "READY"
    # federation: lifecycle of the gang this node leads ("" for plain
    # nodes) — peers stop routing writes to a DEGRADED/REFORMING gang
    # and prefer gang-ACTIVE owners for reads (parallel/federation.py)
    gang_state: str = ""
    gang_epoch: int = 0

    def to_dict(self) -> dict:
        d = {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }
        # optional-keyed: plain-cluster payloads stay byte-stable
        if self.gang_state:
            d["gangState"] = self.gang_state
            d["gangEpoch"] = self.gang_epoch
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d["id"],
            uri=d["uri"],
            is_coordinator=d.get("isCoordinator", False),
            state=d.get("state", "READY"),
            gang_state=d.get("gangState", ""),
            gang_epoch=d.get("gangEpoch", 0),
        )
