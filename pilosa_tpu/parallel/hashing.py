"""Shard placement hashing (reference cluster.go:39-40, 776-857).

partition = FNV-64a(index name + shard big-endian) mod 256; partition →
first owning node via the Lamping-Veach jump consistent hash; replicas =
the next replicaN-1 nodes on the (id-sorted) ring. Keeping the exact
hash layout means a resize moves the same minimal fragment set the
reference would move.
"""

from __future__ import annotations

DEFAULT_PARTITION_N = 256  # reference cluster.go:39-40


def fnv64a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def partition(index: str, shard: int, partition_n: int = DEFAULT_PARTITION_N) -> int:
    data = index.encode() + shard.to_bytes(8, "big")
    return fnv64a(data) % partition_n


def jump_hash(key: int, num_buckets: int) -> int:
    """Lamping-Veach jump consistent hash (the reference's jmphasher)."""
    if num_buckets <= 0:
        return -1
    b, j = -1, 0
    key &= 0xFFFFFFFFFFFFFFFF
    while j < num_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & 0xFFFFFFFFFFFFFFFF
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


class Jmphasher:
    def hash(self, key: int, n: int) -> int:
        return jump_hash(key, n)


class ModHasher:
    """Deterministic key % n hasher for tests (reference test.ModHasher,
    test/cluster.go:18-20)."""

    def hash(self, key: int, n: int) -> int:
        return key % n if n else -1
