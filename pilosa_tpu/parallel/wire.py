"""Wire encoding for node-to-node query results.

The reference exchanges protobuf QueryResponse messages
(internal/public.proto); here results travel as tagged JSON. Decoding
needs the call shape (a Row vs pairs vs ValCount) — same reason the
reference switches on result type in encodeQueryResponse.
"""

from __future__ import annotations

from typing import Any

from pilosa_tpu.core import Row
from pilosa_tpu.executor import ValCount


def encode_shard_result(r: Any) -> dict:
    """Result of one node's shard-map leg → JSON."""
    if isinstance(r, Row):
        return {"t": "row", "columns": [int(c) for c in r.columns()]}
    if isinstance(r, ValCount):
        return {"t": "valcount", "value": r.val, "count": r.count}
    if isinstance(r, bool):
        return {"t": "bool", "v": r}
    if isinstance(r, int):
        return {"t": "int", "v": r}
    if isinstance(r, list):
        # TopN pair lists: [{"id": .., "count": ..}]
        return {"t": "pairs", "v": r}
    if r is None:
        return {"t": "null"}
    raise TypeError(f"cannot encode result: {r!r}")


def decode_shard_result(d: dict) -> Any:
    t = d.get("t")
    if t == "row":
        r = Row(*d["columns"])
        return r
    if t == "valcount":
        return ValCount(d["value"], d["count"])
    if t == "bool":
        return d["v"]
    if t == "int":
        return d["v"]
    if t == "pairs":
        return d["v"]
    if t == "null":
        return None
    raise TypeError(f"cannot decode result: {d!r}")


def pairs_to_tuples(pairs: list) -> list[tuple[int, int]]:
    return [(p["id"], p["count"]) for p in pairs]


def tuples_to_pairs(tuples: list[tuple[int, int]]) -> list[dict]:
    return [{"id": i, "count": c} for i, c in tuples]
