"""Bit-sliced-index (BSI) kernels — Sum/Min/Max/Range as bit-plane algebra.

A BSI field stores an integer per column as bitDepth bit-plane rows plus
a not-null row at plane index bitDepth (reference fragment.go:467-836).
The reference walks roaring containers per plane; here each plane is a
packed u32[W] row and the keep/exclude recurrences become O(bitDepth)
masked word ops — fully vectorised on the VPU and fused by XLA into a
couple of HBM passes.

Every kernel takes ``planes``: u32[D+1, W] where planes[D] is the
not-null (existence) row, and an optional ``filter`` row. ``bit_depth``
is static (a property of the field schema); predicates are *traced*
scalars so varying query constants never trigger recompilation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _filtered_exists(planes, filter_row):
    exists = planes[-1]
    if filter_row is not None:
        exists = jnp.bitwise_and(exists, filter_row)
    return exists


@functools.partial(jax.jit, static_argnames=("bit_depth", "has_filter"))
def bsi_plane_counts(planes, filter_row, *, bit_depth: int, has_filter: bool):
    """Per-plane intersection counts for Sum (reference fragment.sum:563-597).

    Returns i32[bit_depth+1]: counts[i] = popcount(plane_i & filter) for
    value planes, counts[bit_depth] = filtered existence count. The host
    computes sum = Σ counts[i]<<i in arbitrary-precision Python ints —
    exactness is never at the mercy of device integer width.
    """
    f = filter_row if has_filter else None
    mat = planes if f is None else jnp.bitwise_and(planes, f[None, :])
    pc = jax.lax.population_count(mat)
    return jnp.sum(pc.astype(jnp.int32), axis=-1)


@functools.partial(jax.jit, static_argnames=("bit_depth", "has_filter"))
def bsi_min(planes, filter_row, *, bit_depth: int, has_filter: bool):
    """Min recurrence (reference fragment.min:599-630).

    Returns (bits: bool[bit_depth], count: i32) where bits[i] is True if
    bit i of the min value is set; the host assembles the value.
    """
    consider = _filtered_exists(planes, filter_row if has_filter else None)
    bits = []
    for ii in reversed(range(bit_depth)):
        x = jnp.bitwise_and(consider, jnp.bitwise_not(planes[ii]))
        cnt = jnp.sum(jax.lax.population_count(x).astype(jnp.int32))
        pred = cnt > 0
        consider = jnp.where(pred, x, consider)
        bits.append(jnp.logical_not(pred))  # bit ii of min is set iff x empty
    count = jnp.sum(jax.lax.population_count(consider).astype(jnp.int32))
    return jnp.stack(bits[::-1]) if bits else jnp.zeros(0, bool), count


@functools.partial(jax.jit, static_argnames=("bit_depth", "has_filter"))
def bsi_max(planes, filter_row, *, bit_depth: int, has_filter: bool):
    """Max recurrence (reference fragment.max:632-661)."""
    consider = _filtered_exists(planes, filter_row if has_filter else None)
    bits = []
    for ii in reversed(range(bit_depth)):
        x = jnp.bitwise_and(planes[ii], consider)
        cnt = jnp.sum(jax.lax.population_count(x).astype(jnp.int32))
        pred = cnt > 0
        consider = jnp.where(pred, x, consider)
        bits.append(pred)  # bit ii of max is set iff intersection nonempty
    count = jnp.sum(jax.lax.population_count(consider).astype(jnp.int32))
    return jnp.stack(bits[::-1]) if bits else jnp.zeros(0, bool), count


def _pred_bit(predicate, i):
    return jnp.bitwise_and(jnp.right_shift(predicate, jnp.uint32(i)), jnp.uint32(1)) == 1


@functools.partial(jax.jit, static_argnames=("bit_depth",))
def bsi_range_eq(planes, predicate, *, bit_depth: int):
    """EQ: keep columns whose every bit matches (reference rangeEQ:678-694)."""
    b = planes[-1]
    for i in reversed(range(bit_depth)):
        bit = _pred_bit(predicate, i)
        row = planes[i]
        b = jnp.where(bit, jnp.bitwise_and(b, row), jnp.bitwise_and(b, jnp.bitwise_not(row)))
    return b


@functools.partial(jax.jit, static_argnames=("bit_depth",))
def bsi_range_neq(planes, predicate, *, bit_depth: int):
    """NEQ = not-null minus EQ (reference rangeNEQ:696-710)."""
    eq = bsi_range_eq(planes, predicate, bit_depth=bit_depth)
    return jnp.bitwise_and(planes[-1], jnp.bitwise_not(eq))


@functools.partial(jax.jit, static_argnames=("bit_depth", "allow_equality"))
def bsi_range_lt(planes, predicate, *, bit_depth: int, allow_equality: bool):
    """LT / LTE keep-exclude recurrence (reference rangeLT:712-760).

    The reference short-circuits with `continue`/early-return on
    predicate bits; here those become masked selects on a traced
    predicate so one compiled kernel serves every constant.
    """
    zero = jnp.zeros_like(planes[-1])
    b = planes[-1]
    keep = zero
    leading = jnp.bool_(True)
    ret = zero
    returned = jnp.bool_(False)
    for i in reversed(range(bit_depth)):
        row = planes[i]
        bit = _pred_bit(predicate, i)
        # Leading-zero skip: while in leading zeros and bit==0, just strip rows.
        in_lz = jnp.logical_and(leading, jnp.logical_not(bit))
        b = jnp.where(in_lz, jnp.bitwise_and(b, jnp.bitwise_not(row)), b)
        leading = in_lz
        active = jnp.logical_not(in_lz)
        if i == 0 and not allow_equality:
            # bit==0 -> keep only already-kept; bit==1 -> b \ (row \ keep)
            final = jnp.where(
                bit,
                jnp.bitwise_and(b, jnp.bitwise_not(jnp.bitwise_and(row, jnp.bitwise_not(keep)))),
                keep,
            )
            ret = jnp.where(jnp.logical_and(active, jnp.logical_not(returned)), final, ret)
            returned = jnp.logical_or(returned, active)
            continue
        # bit==0: remove set columns not already kept.
        b0 = jnp.bitwise_and(b, jnp.bitwise_not(jnp.bitwise_and(row, jnp.bitwise_not(keep))))
        b = jnp.where(jnp.logical_and(active, jnp.logical_not(bit)), b0, b)
        # bit==1 (i>0): extend keep with columns having this bit unset.
        if i > 0:
            k1 = jnp.bitwise_or(keep, jnp.bitwise_and(b, jnp.bitwise_not(row)))
            keep = jnp.where(jnp.logical_and(active, bit), k1, keep)
    if not allow_equality and bit_depth > 0:
        return jnp.where(returned, ret, b)
    return b


@functools.partial(jax.jit, static_argnames=("bit_depth", "allow_equality"))
def bsi_range_gt(planes, predicate, *, bit_depth: int, allow_equality: bool):
    """GT / GTE recurrence (reference rangeGT:762-797)."""
    zero = jnp.zeros_like(planes[-1])
    b = planes[-1]
    keep = zero
    ret = zero
    returned = jnp.bool_(False)
    for i in reversed(range(bit_depth)):
        row = planes[i]
        bit = _pred_bit(predicate, i)
        if i == 0 and not allow_equality:
            # bit==1 -> only kept; bit==0 -> b \ ((b \ row) \ keep)
            bd = jnp.bitwise_and(b, jnp.bitwise_not(row))  # b \ row
            final0 = jnp.bitwise_and(b, jnp.bitwise_not(jnp.bitwise_and(bd, jnp.bitwise_not(keep))))
            final = jnp.where(bit, keep, final0)
            ret = jnp.where(returned, ret, final)
            returned = jnp.bool_(True)
            continue
        # bit==1: remove unset columns not already kept.
        bd = jnp.bitwise_and(b, jnp.bitwise_not(row))
        b1 = jnp.bitwise_and(b, jnp.bitwise_not(jnp.bitwise_and(bd, jnp.bitwise_not(keep))))
        b = jnp.where(bit, b1, b)
        # bit==0 (i>0): extend keep with columns having this bit set.
        if i > 0:
            k0 = jnp.bitwise_or(keep, jnp.bitwise_and(b, row))
            keep = jnp.where(bit, keep, k0)
    if not allow_equality and bit_depth > 0:
        return jnp.where(returned, ret, b)
    return b


@functools.partial(jax.jit, static_argnames=("bit_depth",))
def bsi_range_between(planes, pred_min, pred_max, *, bit_depth: int):
    """BETWEEN (inclusive both ends) — fused GTE(min) ∧ LTE(max) recurrence
    (reference rangeBetween:806-840)."""
    zero = jnp.zeros_like(planes[-1])
    b = planes[-1]
    keep1 = zero  # GTE side
    keep2 = zero  # LTE side
    for i in reversed(range(bit_depth)):
        row = planes[i]
        bit1 = _pred_bit(pred_min, i)
        bit2 = _pred_bit(pred_max, i)
        # GTE pred_min
        bd = jnp.bitwise_and(b, jnp.bitwise_not(row))
        b_hi = jnp.bitwise_and(b, jnp.bitwise_not(jnp.bitwise_and(bd, jnp.bitwise_not(keep1))))
        b = jnp.where(bit1, b_hi, b)
        if i > 0:
            k1 = jnp.bitwise_or(keep1, jnp.bitwise_and(b, row))
            keep1 = jnp.where(bit1, keep1, k1)
        # LTE pred_max
        b_lo = jnp.bitwise_and(b, jnp.bitwise_not(jnp.bitwise_and(row, jnp.bitwise_not(keep2))))
        b = jnp.where(bit2, b, b_lo)
        if i > 0:
            k2 = jnp.bitwise_or(keep2, jnp.bitwise_and(b, jnp.bitwise_not(row)))
            keep2 = jnp.where(bit2, k2, keep2)
    return b


@functools.partial(jax.jit, static_argnames=("bit_depth", "has_filter"))
def bsi_plane_counts_batched(planes, filter_rows, *, bit_depth: int, has_filter: bool):
    """Shard-batched Sum: planes u32[S, D+1, W], filter u32[S, W] →
    i32[D+1] summed over shards in one dispatch."""
    if has_filter:
        block = jnp.bitwise_and(planes, filter_rows[:, None, :])
    else:
        block = planes
    pc = jax.lax.population_count(block)
    return jnp.sum(pc.astype(jnp.int32), axis=(0, 2))


# -- device-resident analytics (GroupBy / Distinct / Percentile) -------------


@functools.partial(jax.jit, static_argnames=("bit_depth", "has_filter"))
def bsi_percentile_batched(planes, filter_rows, nth_bp, *, bit_depth: int, has_filter: bool):
    """Shard-batched nearest-rank percentile as a bit-sliced binary
    search over the value planes (one launch for the whole shard set).

    planes: u32[S, D+1, W]; nth_bp: traced i32 percentile in BASIS
    POINTS (95.5% → 9550) so the target rank k = ceil(nth·n/100) is
    exact integer arithmetic — never at the mercy of f32 rounding. The
    descent walks planes high→low: if ≥k considered columns have bit i
    clear, the k-th smallest has bit i clear and the zeros subset is
    kept; otherwise bit i is set and k drops by the zeros count.

    Returns (bits: bool[bit_depth], count: i32) with bits[i] = bit i of
    the k-th smallest stored value; count is the considered-column
    total (count == 0 means no value exists — bits are garbage then and
    the host must answer empty).
    """
    consider = planes[:, -1, :]
    if has_filter:
        consider = jnp.bitwise_and(consider, filter_rows)
    count = jnp.sum(jax.lax.population_count(consider).astype(jnp.int32))
    # k = ceil(nth_bp * count / 10000) without i32 overflow: split count
    # into q·10000 + r so both partial products stay far below 2^31.
    q = count // 10000
    r = count % 10000
    k = nth_bp * q + (nth_bp * r + 9999) // 10000
    k = jnp.clip(k, 1, jnp.maximum(count, 1))
    bits = []
    for i in reversed(range(bit_depth)):
        plane = planes[:, i, :]
        zeros = jnp.bitwise_and(consider, jnp.bitwise_not(plane))
        c = jnp.sum(jax.lax.population_count(zeros).astype(jnp.int32))
        pred = k <= c
        bits.append(jnp.logical_not(pred))
        consider = jnp.where(pred, zeros, jnp.bitwise_and(consider, plane))
        k = jnp.where(pred, k, k - c)
    bits_arr = jnp.stack(bits[::-1]) if bits else jnp.zeros(0, bool)
    return bits_arr, count


@functools.partial(jax.jit, static_argnames=("bit_depth", "has_filter"))
def bsi_distinct_presence(planes, filter_rows, *, bit_depth: int, has_filter: bool):
    """Distinct(field) as an OR-reduction over BSI planes with
    on-device id extraction: planes u32[S, D+1, W] → packed u32
    presence words over the value domain [0, 2^bit_depth).

    Per shard, each existing (and filtered) column's stored value is
    reassembled from its plane bits and scattered into a presence
    bitmap; shards OR-reduce in a fori_loop so the transient stays one
    shard wide. The result is itself a packed bitmap — the host decodes
    set positions to sorted values (pos + bsig.min) and cross-gang
    merges are plain ORs. Callers gate bit_depth (the presence bitmap
    is 2^bit_depth bits) before choosing this path.
    """
    nshards = planes.shape[0]
    ncols = planes.shape[2] * 32
    domain = 1 << bit_depth
    nwords = max((domain + 31) // 32, 1)
    bitpos = jnp.arange(32, dtype=jnp.uint32)

    def unpack(words):  # u32[W] -> bool[W*32], bit p at index p
        return (
            (words[:, None] >> bitpos[None, :]) & jnp.uint32(1)
        ).astype(jnp.bool_).reshape(-1)

    def shard_presence(sp, filt):
        exists = sp[-1]
        if has_filter:
            exists = jnp.bitwise_and(exists, filt)
        vals = jnp.zeros((ncols,), jnp.int32)
        for i in range(bit_depth):
            vals = vals | (unpack(sp[i]).astype(jnp.int32) << i)
        # absent columns index out of bounds and drop from the scatter
        idx = jnp.where(unpack(exists), vals, jnp.int32(domain))
        return jnp.zeros((domain,), jnp.bool_).at[idx].set(True, mode="drop")

    pres = jax.lax.fori_loop(
        0,
        nshards,
        lambda s, acc: acc | shard_presence(planes[s], filter_rows[s]),
        jnp.zeros((domain,), jnp.bool_),
    )
    total = nwords * 32
    if total != domain:
        pres = jnp.pad(pres, (0, total - domain))
    return jnp.sum(
        pres.reshape(nwords, 32).astype(jnp.uint32) << bitpos[None, :],
        axis=1,
        dtype=jnp.uint32,
    )
