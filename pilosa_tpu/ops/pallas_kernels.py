"""Pallas TPU kernels for the hottest scan: TopN intersection scoring.

The XLA path (ops.intersection_counts_matrix) already fuses AND+popcount+
reduce; this Pallas version adds explicit tiling so the fragment matrix
streams HBM→VMEM in (TILE_R, TILE_W) blocks with the src row pinned in
VMEM, accumulating per-row partial popcounts across word tiles — the
scan is purely HBM-bandwidth-bound and this keeps the working set inside
VMEM. bench.py measures both and the executor keeps whichever wins.

Falls back to interpret mode off-TPU so semantics are testable on the
CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_R = 512  # rank-1 i32 outputs tile at T(512) in XLA layout on TPU
TILE_W = 1024  # uint32 words per tile (keeps a 2 MB mat block in VMEM)


def _scores_kernel(src_ref, mat_ref, out_ref):
    # out is (1, R) so it carries the fixed (8, 128) rank-2 layout —
    # rank-1 outputs get size-dependent XLA tilings (T(512)/T(1024)/…)
    # that a fixed Mosaic block size can't match. The (1, TILE_R) block
    # is revisited across the word grid for accumulation.
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    block = jnp.bitwise_and(mat_ref[:], src_ref[:])  # (TILE_R, TILE_W)
    partial = jnp.sum(
        jax.lax.population_count(block).astype(jnp.int32), axis=1
    )
    out_ref[:] += partial[None, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def intersection_counts_matrix_pallas(src, mat, *, interpret: bool = False):
    """popcount(src & row) per row: u32[W], u32[R, W] -> i32[R].

    R must be a multiple of TILE_R and W of TILE_W (the executor pads
    the staged matrix; padding rows score 0 and are sliced off by the
    caller).
    """
    r, w = mat.shape
    grid = (r // TILE_R, w // TILE_W)
    out = pl.pallas_call(
        _scores_kernel,
        out_shape=jax.ShapeDtypeStruct((1, r), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, TILE_W), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (TILE_R, TILE_W), lambda i, j: (i, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, TILE_R), lambda i, j: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(src.reshape(1, w), mat)
    return out[0]


def _batched_scores_kernel(q_static, srcs_ref, mat_ref, out_ref):
    # Grid (R/TILE_R, W/TILE_W), j innermost: the (TILE_R, TILE_W) mat
    # block is fetched from HBM once per (i, j) and reused for all Q
    # sources — the whole point of batching. out is (Q, TILE_R), index
    # (i, j) -> (0, i): constant across consecutive j steps, the safe
    # Pallas revisit/accumulate pattern.
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    mat = mat_ref[:]  # (TILE_R, TILE_W)
    acc = []
    for q in range(q_static):  # static unroll; Q is bucketed small
        block = jnp.bitwise_and(mat, srcs_ref[q, :][None, :])
        acc.append(
            jnp.sum(jax.lax.population_count(block).astype(jnp.int32), axis=1)
        )
    out_ref[:] += jnp.stack(acc, axis=0)  # (Q, TILE_R)


@functools.partial(jax.jit, static_argnames=("interpret",))
def intersection_counts_matrix_batch_pallas(srcs, mat, *, interpret: bool = False):
    """Batched scoring: u32[Q, W], u32[R, W] -> i32[Q, R].

    R must be a multiple of TILE_R and W of TILE_W (see pad_for_pallas).
    Q is static per compilation — callers bucket Q (pad sources with
    zeros; a zero source scores 0 everywhere) to bound recompiles.
    """
    q, w = srcs.shape
    if q > 512:
        # the kernel unrolls the Q loop; beyond ~512 Mosaic compile
        # time explodes — chunk larger batches at the call site
        raise ValueError(f"batch too large for kernel unroll: {q} > 512")
    r, _ = mat.shape
    grid = (r // TILE_R, w // TILE_W)
    return pl.pallas_call(
        functools.partial(_batched_scores_kernel, q),
        out_shape=jax.ShapeDtypeStruct((q, r), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((q, TILE_W), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (TILE_R, TILE_W), lambda i, j: (i, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (q, TILE_R), lambda i, j: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(srcs, mat)


def _groupby_planes_kernel(p_static, planes_ref, groups_ref, out_ref):
    # Grid (K/TILE_R, W/TILE_W), j innermost: each (TILE_R, TILE_W)
    # group block is fetched from HBM once per (i, j) and reused for
    # all P bit planes pinned in VMEM — the segmented-reduce shape of a
    # GroupBy panel (segment = (plane, group) pair). out is (P, TILE_R)
    # at index (0, i): constant across consecutive j steps, the safe
    # revisit/accumulate pattern.
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    grp = groups_ref[:]  # (TILE_R, TILE_W)
    acc = []
    for p in range(p_static):  # static unroll; P = bit_depth+1 stays small
        block = jnp.bitwise_and(grp, planes_ref[p, :][None, :])
        acc.append(
            jnp.sum(jax.lax.population_count(block).astype(jnp.int32), axis=1)
        )
    out_ref[:] += jnp.stack(acc, axis=0)  # (P, TILE_R)


@functools.partial(jax.jit, static_argnames=("interpret",))
def groupby_plane_counts_pallas(planes, groups, *, interpret: bool = False):
    """Segmented GroupBy×BSI reduction: planes u32[P, W], groups
    u32[K, W] -> i32[P, K].

    K (the panel's cross-product size) is the streaming axis; the few
    bit planes stay resident in VMEM for the whole scan, so each group
    block crosses HBM exactly once regardless of bit depth. K must be a
    multiple of TILE_R and W of TILE_W (pad_for_pallas; zero-padded
    groups score 0 everywhere and are sliced off by the caller). The
    jit fallback is ops.packed.groupby_plane_counts (note the
    transposed [K, P] output there).
    """
    p, w = planes.shape
    if p > 512:
        # the kernel unrolls the plane loop; bit depth is ≤ 64 in
        # practice but guard the Mosaic compile-time cliff anyway
        raise ValueError(f"plane batch too large for kernel unroll: {p} > 512")
    k, _ = groups.shape
    grid = (k // TILE_R, w // TILE_W)
    return pl.pallas_call(
        functools.partial(_groupby_planes_kernel, p),
        out_shape=jax.ShapeDtypeStruct((p, k), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((p, TILE_W), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (TILE_R, TILE_W), lambda i, j: (i, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (p, TILE_R), lambda i, j: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(planes, groups)


def _expand_runs_kernel(starts_ref, ends_ref, out_ref):
    # One (1, TILE_W) word tile per grid step; every run clamps its
    # [start, end] bit interval against each word's 32-bit span and
    # ORs in the overlap mask. Runs are few (RLE containers cap at
    # 2048 intervals) while words are many, so the run loop stays
    # sequential and the word axis rides the VPU lanes.
    i = pl.program_id(0)
    full = jnp.uint32(0xFFFFFFFF)
    wid = i * TILE_W + jax.lax.broadcasted_iota(jnp.int32, (1, TILE_W), 1)
    word_lo = wid * 32
    word_hi = word_lo + 31
    starts = starts_ref[:]
    ends = ends_ref[:]

    def body(k, acc):
        lo = jnp.maximum(starts[0, k], word_lo)
        hi = jnp.minimum(ends[0, k], word_hi)
        sb = jnp.clip(lo - word_lo, 0, 31).astype(jnp.uint32)
        eb = jnp.clip(hi - word_lo, 0, 31).astype(jnp.uint32)
        m = (full << sb) & (full >> (31 - eb))
        return acc | jnp.where(lo <= hi, m, jnp.uint32(0))

    out_ref[:] = jax.lax.fori_loop(
        0, starts.shape[1], body, jnp.zeros((1, TILE_W), jnp.uint32)
    )


@functools.partial(jax.jit, static_argnames=("num_words", "interpret"))
def expand_runs_pallas(run_starts, run_ends, num_words: int, *, interpret: bool = False):
    """On-device roaring RLE expansion: i32[N] inclusive global bit
    endpoints -> packed u32[num_words] (array-container positions ride
    along as width-1 runs). num_words must be a multiple of TILE_W (a
    row is 32768 words, so stacked rows always are); pad the run list
    with start > end — an empty interval contributes nothing. The jit
    scatter fallback (ops.packed.expand_blocks) covers CPU/interpret
    mode and dense bitmap containers."""
    n = run_starts.shape[0]
    grid = (num_words // TILE_W,)
    out = pl.pallas_call(
        _expand_runs_kernel,
        out_shape=jax.ShapeDtypeStruct((1, num_words), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, TILE_W), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(run_starts.reshape(1, n), run_ends.reshape(1, n))
    return out[0]


def pad_for_pallas(mat):
    """Pad rows to TILE_R and words to TILE_W multiples."""
    import numpy as np

    r, w = mat.shape
    rp = (-r) % TILE_R
    wp = (-w) % TILE_W
    if rp or wp:
        mat = np.pad(mat, ((0, rp), (0, wp)))
    return mat, r
