"""Incremental delta-apply kernels — in-place maintenance of staged state.

A mutation used to cold-invalidate every HBM block staged for its
fragment (the stager keyed entries by generation), so one ``set_bit``
forced a full host rebuild + re-upload of, e.g., a 537 MB dense matrix.
The reference absorbs writes with an op log layered over the mmapped
roaring file (reference fragment.go:66-110); these kernels are the
device-side analog: the fragment's delta log (core/fragment.py) replays
onto the already-resident arrays as one scatter update.

Host side, a delta batch collapses to per-word OR / AND-NOT masks
(``coalesce_bit_updates`` — last op per bit wins, then bits combine per
word). Device side, ``apply_word_updates`` gathers the touched words,
applies ``(w | or_mask) & ~andnot_mask``, and scatters them back — one
fused gather/scatter pass over K words instead of a full-block upload.
Update counts are padded to powers of two with out-of-range indices
(scatter ``mode="drop"`` discards them) so the XLA compile cache holds
log2 distinct kernel shapes, the same bucketing trick as the stager's
pow2 row padding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def coalesce_bit_updates(
    word_idx: np.ndarray, bit_idx: np.ndarray, is_set: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Collapse an ordered bit-delta stream to per-word update masks.

    word_idx[i] is the flat u32-word index of delta i, bit_idx[i] its
    bit within that word (0..31), is_set[i] True for set / False for
    clear. Later deltas override earlier ones bit-wise (the log is
    ordered), so the LAST op per (word, bit) wins; surviving set bits
    OR-combine into or_mask and surviving clears into andnot_mask.

    Returns (idx i32[K], or_mask u32[K], andnot_mask u32[K]) with idx
    unique. The new word value is ``(old | or_mask) & ~andnot_mask`` —
    or_mask and andnot_mask are disjoint by construction, so the apply
    order inside the kernel doesn't matter.
    """
    key = word_idx.astype(np.int64) * 32 + bit_idx.astype(np.int64)
    # keep the last occurrence of each (word, bit) — same idiom as
    # fragment.import_value's last-write-wins dedup
    _, last_rev = np.unique(key[::-1], return_index=True)
    keep = key.size - 1 - last_rev
    k = key[keep]
    s = np.asarray(is_set)[keep]
    words = k >> 5
    bits = (k & 31).astype(np.uint32)
    uniq_words, inv = np.unique(words, return_inverse=True)
    or_mask = np.zeros(uniq_words.size, dtype=np.uint32)
    andnot_mask = np.zeros(uniq_words.size, dtype=np.uint32)
    bitmask = (np.uint32(1) << bits).astype(np.uint32)
    np.bitwise_or.at(or_mask, inv[s], bitmask[s])
    np.bitwise_or.at(andnot_mask, inv[~s], bitmask[~s])
    return uniq_words.astype(np.int32), or_mask, andnot_mask


def coalesce_position_updates(
    positions: np.ndarray, is_set: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Wave form of ``coalesce_bit_updates``: positions are flat
    fragment bit positions (row * SHARD_WIDTH + col), the coordinate an
    ingest write wave carries, rather than pre-split (word, bit)
    pairs. One coalesce per wave regardless of how many rows it
    touches."""
    pos = np.asarray(positions, dtype=np.int64)
    return coalesce_bit_updates(
        pos >> 5, (pos & 31).astype(np.int64), np.asarray(is_set, dtype=bool)
    )


def apply_position_wave(words, positions, is_set):
    """One coalesced multi-bit device scatter for a whole write wave:
    coalesce + pad + jit scatter in a single call against a staged
    block of any shape. The pow2 padding keeps wave sizes from minting
    new compile-cache entries per wave."""
    idx, or_mask, andnot_mask = coalesce_position_updates(positions, is_set)
    total_words = int(np.prod(words.shape))
    idx, or_mask, andnot_mask = pad_updates(idx, or_mask, andnot_mask, total_words)
    return apply_word_updates(words, idx, or_mask, andnot_mask)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def pad_updates(
    idx: np.ndarray,
    or_mask: np.ndarray,
    andnot_mask: np.ndarray,
    total_words: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad an update batch to the next power of two. Padding rows carry
    idx = total_words — out of range, so the scatter drops them — and
    zero masks, so even a clamped gather of them is a no-op."""
    k = idx.size
    target = _next_pow2(max(k, 1))
    if target == k:
        return idx, or_mask, andnot_mask
    pad = target - k
    return (
        np.concatenate([idx, np.full(pad, total_words, dtype=np.int32)]),
        np.concatenate([or_mask, np.zeros(pad, dtype=np.uint32)]),
        np.concatenate([andnot_mask, np.zeros(pad, dtype=np.uint32)]),
    )


@jax.jit
def apply_word_updates(words, idx, or_mask, andnot_mask):
    """Scatter-apply per-word masks to a staged block of any shape.

    words: u32[...]; idx i32[K] indexes the FLATTENED word array
    (out-of-range = padding, dropped by the scatter); returns a new
    array of the same shape — staged arrays stay immutable, so batched
    scorers coalescing on array identity see the update as a fresh key.
    """
    flat = words.reshape(-1)
    cur = flat[idx]  # OOB gathers clamp; their updates are dropped below
    new = (cur | or_mask) & jnp.bitwise_not(andnot_mask)
    return flat.at[idx].set(new, mode="drop").reshape(words.shape)


@jax.jit
def apply_word_updates_2d(words, shard_idx, word_idx, or_mask, andnot_mask):
    """Shard-stack form: words u32[S, M] with per-update (shard, word)
    coordinates, for [S, ...] stacks whose leading dim may be placed
    over a mesh axis — scattering along the trailing dims avoids the
    full flatten of the sharded axis. Out-of-range shard_idx (== S)
    marks padding."""
    cur = words[shard_idx, word_idx]
    new = (cur | or_mask) & jnp.bitwise_not(andnot_mask)
    return words.at[shard_idx, word_idx].set(new, mode="drop")
