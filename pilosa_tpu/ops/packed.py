"""Packed-word bitmap kernels — the TPU data plane (L0 compute).

A fragment row (2^20 columns, reference fragment.go:47-48) is staged in
device memory as 32,768 packed ``uint32`` words (TPUs have no native
64-bit integers; the CPU engine's uint64 words reinterpret losslessly as
little-endian uint32 pairs). The reference's per-container Go loops
(reference roaring/roaring.go:1836-2449) become word-wise vector ops +
``lax.population_count`` here: on TPU the VPU processes 8x128 lanes of
these per cycle and XLA fuses whole Intersect/Union chains into a single
HBM pass.

All kernels keep shapes static (row width fixed per shard) and treat row
*values* — including range predicates — as traced arguments, so a query
stream with varying rows/predicates never recompiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Words per shard-row on device: 2^20 bits / 32.
SHARD_WIDTH = 1 << 20
WORDS_PER_ROW = SHARD_WIDTH // 32
# Words per 2^16-bit container block: the sparse-staging granule.
CONTAINER_WORDS = (1 << 16) // 32
CONTAINERS_PER_ROW = SHARD_WIDTH >> 16  # 16


def u64_to_u32(words64: np.ndarray) -> np.ndarray:
    """Reinterpret uint64 packed words as uint32 device words (little-endian:
    bit p of the row lands in u32 word p>>5, bit p&31)."""
    return words64.view("<u8").view("<u4")


def u32_to_u64(words32: np.ndarray) -> np.ndarray:
    return words32.view("<u4").view("<u8")


# -- elementwise boolean algebra --------------------------------------------
# Tiny named wrappers so lowered call trees read like the PQL ops they
# implement (reference executor.go:704-1000). XLA fuses chains of these.


def and_(a, b):
    return jnp.bitwise_and(a, b)


def or_(a, b):
    return jnp.bitwise_or(a, b)


def xor_(a, b):
    return jnp.bitwise_xor(a, b)


def andnot(a, b):
    """a AND NOT b — the Difference op."""
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def not_(a):
    return jnp.bitwise_not(a)


# -- popcount ----------------------------------------------------------------


@jax.jit
def count_bits(words) -> jax.Array:
    """Total set bits in a packed word array (any shape) -> int32 scalar."""
    pc = jax.lax.population_count(words)
    return jnp.sum(pc.astype(jnp.int32))


@jax.jit
def count_bits_rows(mat) -> jax.Array:
    """Per-row popcount: u32[R, W] -> i32[R]."""
    pc = jax.lax.population_count(mat)
    return jnp.sum(pc.astype(jnp.int32), axis=-1)


@jax.jit
def intersection_count(a, b) -> jax.Array:
    """popcount(a & b) without materialising the intersection
    (reference roaring.go:344 IntersectionCount)."""
    return count_bits(jnp.bitwise_and(a, b))


@jax.jit
def intersection_counts_matrix(src, mat) -> jax.Array:
    """TopN scoring kernel: popcount(src & row) for every row.

    src: u32[W]; mat: u32[R, W] -> i32[R]. One HBM pass over the
    fragment matrix; replaces the reference's per-candidate
    ``Src.IntersectionCount(f.row(id))`` heap loop (fragment.go:985).
    """
    pc = jax.lax.population_count(jnp.bitwise_and(mat, src[None, :]))
    return jnp.sum(pc.astype(jnp.int32), axis=-1)


@functools.partial(jax.jit, static_argnames=("num_rows",))
def sparse_intersection_counts(src, blocks, block_row, block_slot, num_rows: int):
    """TopN scoring over block-sparse candidate rows.

    Dense staging materialises every candidate row at 128 KB regardless
    of sparsity (SURVEY.md §7 hard part 2); at the 1B-row scale most of
    those words are zero. Here only nonempty 2^16-bit container blocks
    are staged: ``blocks`` u32[B, 2048] with coordinate arrays
    ``block_row`` i32[B] (candidate index) and ``block_slot`` i32[B]
    (which of the row's 16 container positions). The kernel gathers the
    matching src block, popcounts the AND, and segment-sums per row —
    bit-identical to the dense matrix pass because absent blocks
    contribute zero to an intersection.

    src: u32[W]; returns i32[num_rows] (num_rows static — callers pad
    candidate counts to powers of two to bound recompiles).
    """
    src_blk = src.reshape(-1, CONTAINER_WORDS)[block_slot]
    pc = jax.lax.population_count(jnp.bitwise_and(blocks, src_blk))
    per_block = jnp.sum(pc.astype(jnp.int32), axis=-1)
    return jax.ops.segment_sum(per_block, block_row, num_segments=num_rows)


@functools.partial(jax.jit, static_argnames=("num_rows",))
def sparse_intersection_counts_stacked(
    srcs, blocks, block_row, block_slot, block_shard, num_rows: int
):
    """Cross-shard TopN scoring in ONE dispatch.

    Per-shard sequential kernel launches round-trip the host once per
    shard — on a tunneled chip that is S × RTT per query. Here every
    shard's candidate blocks are concatenated (block_shard says which
    shard a block belongs to, block_row is a GLOBAL segment id =
    shard_index * chunk + local candidate index) and one gather +
    popcount + segment-sum serves the whole index — the single-device
    analog of the reference's per-node scatter-gather collapsing into
    one program (reference executor.go:1444-1593).

    srcs: u32[S, W]; blocks: u32[B, 2048]; returns i32[num_rows].
    """
    per_shard = srcs.reshape(srcs.shape[0], -1, CONTAINER_WORDS)
    src_blk = per_shard[block_shard, block_slot]
    pc = jax.lax.population_count(jnp.bitwise_and(blocks, src_blk))
    per_block = jnp.sum(pc.astype(jnp.int32), axis=-1)
    return jax.ops.segment_sum(per_block, block_row, num_segments=num_rows)


@functools.partial(
    jax.jit, static_argnames=("num_rows", "n_shards", "chunk")
)
def sparse_intersection_counts_stacked_mat(
    srcs,
    blocks,
    block_row,
    block_slot,
    block_shard,
    num_rows: int,
    n_shards: int,
    chunk: int,
):
    """Matrix form of the stacked cross-shard scorer: i32[n_shards,
    chunk] trimmed and reshaped ON DEVICE, so a caller (the fused
    whole-query program) transfers exactly the per-shard score head —
    never the flat padded vector the host would otherwise slice after
    fetching. num_rows/n_shards/chunk are static; the stacked staging
    keeps num_rows == n_shards * chunk exact, so the slice is a
    shape-level guarantee, not a copy."""
    flat = sparse_intersection_counts_stacked(
        srcs, blocks, block_row, block_slot, block_shard, num_rows
    )
    return flat[: n_shards * chunk].reshape(n_shards, chunk)


_BATCH_GROUP = 8  # queries scored per block-stream pass (footprint knob)


@functools.partial(jax.jit, static_argnames=("num_rows",))
def sparse_intersection_counts_stacked_batch(
    srcs_q, blocks, block_row, block_slot, block_shard, num_rows: int
):
    """Concurrent-query batch of the stacked cross-shard scoring: the
    staged candidate blocks stream from HBM once per GROUP of query
    sources (the serving-throughput lever at the 1B-row scale, where
    the block set is hundreds of MB and each extra query would
    otherwise re-read it). A pure lax.map over queries re-read the
    block set per query — measured 147 ms vs 75 ms at Q=32 on the
    1B/64-shard config; vectorizing groups of 8 inside the map keeps
    the peak gather footprint bounded while amortizing the stream.

    srcs_q: u32[Q, S, W]; blocks: u32[B, 2048]; returns i32[Q, num_rows].
    """
    q = srcs_q.shape[0]
    group = min(_BATCH_GROUP, q)
    if q % group:
        # q is pow2-padded by the batcher; any stray remainder falls
        # back to the per-query sweep rather than a mid-shape compile
        return jax.lax.map(
            lambda s: sparse_intersection_counts_stacked(
                s, blocks, block_row, block_slot, block_shard, num_rows
            ),
            srcs_q,
        )
    per_shard = srcs_q.reshape(q, srcs_q.shape[1], -1, CONTAINER_WORDS)

    def one_group(g):
        src_blk = g[:, block_shard, block_slot]  # [G, B, W]
        pc = jax.lax.population_count(jnp.bitwise_and(blocks[None], src_blk))
        per_block = jnp.sum(pc.astype(jnp.int32), axis=-1)  # [G, B]
        return jax.vmap(
            lambda pb: jax.ops.segment_sum(pb, block_row, num_segments=num_rows)
        )(per_block)

    gs = per_shard.reshape(q // group, group, *per_shard.shape[1:])
    return jax.lax.map(one_group, gs).reshape(q, num_rows)


@functools.partial(jax.jit, static_argnames=("num_rows",))
def sparse_intersection_counts_stacked_batch_list(
    srcs, blocks, block_row, block_slot, block_shard, num_rows: int
):
    """List-of-sources form: stacks inside the jit so a coalesced batch
    costs ONE dispatch RPC instead of stack + kernel (each Python-level
    dispatch is a serialized ~70 ms round-trip on a tunneled chip).
    srcs: [u32[S, W]] * Q (Q static via the arg structure)."""
    return sparse_intersection_counts_stacked_batch(
        jnp.stack(srcs), blocks, block_row, block_slot, block_shard, num_rows
    )


@jax.jit
def intersection_counts_matrix_batch(srcs, mat) -> jax.Array:
    """Batched TopN scoring: popcount(src_q & row_r) for every (q, r).

    srcs: u32[Q, W]; mat: u32[R, W] -> i32[Q, R]. One logical pass over
    the fragment matrix serves all Q query sources — the concurrent-
    query analog of intersection_counts_matrix (a server batches
    concurrent TopN sources the way a TPU inference server batches
    requests). lax.map keeps the peak footprint at one (R, W) popcount
    buffer instead of the (Q, R, W) a vmap would materialize; the
    Pallas version (ops.pallas_kernels) tiles it properly on real TPU.
    """
    return jax.lax.map(lambda s: intersection_counts_matrix(s, mat), srcs)


@jax.jit
def intersection_counts_matrix_batch_list(srcs, mat) -> jax.Array:
    """List-of-sources form of the dense batch scorer: stacks inside
    the jit so a coalesced batch costs one dispatch RPC (see
    sparse_intersection_counts_stacked_batch_list)."""
    return intersection_counts_matrix_batch(jnp.stack(srcs), mat)


# -- GroupBy segmented reductions (device-resident analytics) ----------------
#
# A dashboard GroupBy panel is the cross product of its dimensions' row
# bitmaps. Instead of K = ΠR_d point queries (K launches, K plan-cache
# probes, K transports), the per-dimension row stacks are staged once
# and ONE fused program materialises the K group bitmaps in HBM and
# segment-reduces them: popcount per group for Count aggregates, per
# (group, plane) intersection popcounts for Sum aggregates. Group order
# is product order (first dimension slowest), so the host maps counts
# back to row-id tuples by pure arithmetic. The [K, Wf] group transient
# never leaves HBM — callers charge it to the HBM admission governor.


@jax.jit
def combine_groups(dims, filt):
    """Cross-product AND of per-dimension row stacks.

    dims: tuple of u32[R_d, Wf] (rows of one dimension, words flattened
    across the shard batch); filt: u32[Wf] or None, ANDed into every
    group. Returns u32[ΠR_d, Wf] in product order.
    """
    acc = dims[0]
    if filt is not None:
        acc = jnp.bitwise_and(acc, filt[None, :])
    for d in dims[1:]:
        acc = jnp.bitwise_and(acc[:, None, :], d[None, :, :])
        acc = acc.reshape(-1, acc.shape[-1])
    return acc


@jax.jit
def groupby_counts(dims, filt):
    """Count-aggregate GroupBy: per-group popcounts i32[ΠR_d] in one
    dispatch (cross product + segmented popcount fused by XLA)."""
    return count_bits_rows(combine_groups(dims, filt))


@jax.jit
def groupby_plane_counts(groups, planes):
    """Sum-aggregate inner reduction: groups u32[K, Wf] × planes
    u32[P, Wf] → i32[K, P] per-(group, plane) intersection popcounts.
    lax.map over the few planes bounds the transient to one [K, Wf]
    popcount buffer (the group matrix is the big axis). The Pallas
    version (ops.pallas_kernels.groupby_plane_counts_pallas) tiles the
    same reduction for real TPU."""
    res = jax.lax.map(
        lambda p: jnp.sum(
            jax.lax.population_count(jnp.bitwise_and(groups, p[None, :])).astype(
                jnp.int32
            ),
            axis=-1,
        ),
        planes,
    )
    return res.T


@jax.jit
def groupby_sum_reduce(dims, filt, planes):
    """Fused Sum-aggregate GroupBy: one dispatch yielding
    (counts i32[K], plane_counts i32[K, P]). counts[k] is the group's
    column count; plane_counts[k, i] feeds the host's arbitrary-
    precision Σ counts<<i sum assembly (plane P-1 is the not-null row,
    giving the group's non-null value count)."""
    groups = combine_groups(dims, filt)
    return count_bits_rows(groups), groupby_plane_counts(groups, planes)


# -- fold a stack of rows with one op ---------------------------------------


@functools.partial(jax.jit, static_argnames=("op",))
def fold_rows(mat, op: str) -> jax.Array:
    """Reduce u32[K, W] along axis 0 with a boolean op.

    Used for Intersect/Union/Xor over K child rows in one fused pass
    (reference executeIntersectShard chains pairwise; a tree reduce is
    equivalent for these associative ops and vectorises better).
    """
    if op == "and":
        return jax.lax.reduce(mat, jnp.uint32(0xFFFFFFFF), jnp.bitwise_and, (0,))
    if op == "or":
        return jax.lax.reduce(mat, jnp.uint32(0), jnp.bitwise_or, (0,))
    if op == "xor":
        return jax.lax.reduce(mat, jnp.uint32(0), jnp.bitwise_xor, (0,))
    raise ValueError(f"unknown fold op: {op}")


@jax.jit
def count_and_fold(mat) -> jax.Array:
    """popcount(AND-fold of rows) — the Count(Intersect(...)) fast path."""
    return count_bits(fold_rows(mat, "and"))


def device_put_rows(words64_rows: np.ndarray, device=None) -> jax.Array:
    """Stage host uint64-packed rows [R, W64] as device u32[R, 2*W64]."""
    r = words64_rows.shape[0] if words64_rows.ndim == 2 else 1
    w32 = words64_rows.reshape(r, -1).view("<u4")
    return jax.device_put(w32, device)


# -- on-device roaring expansion (tiered staging, ISSUE 17) ------------------
#
# Cold blocks cross PCIe at roaring size instead of packed-word size:
# the host uploads the raw container coordinates (array positions, RLE
# run endpoints, dense bitmap words) and ONE fused scatter program
# expands them to packed u32 words on device. Coordinates are global
# bit offsets into the output (row_index * SHARD_WIDTH + slot * 2^16 +
# local), so one dispatch serves a whole stacked block. All
# contributions are bitwise-disjoint (containers own disjoint word
# ranges; positions/runs within a container are unique/disjoint), so
# scatter-add IS bitwise-or — exact, and add lowers to the cheap
# combiner everywhere. Padding convention (ops/delta.py pad_updates):
# positions pad with 0xFFFFFFFF and word indexes pad with num_words,
# both of which land out of bounds and drop under mode="drop".

_FULL32 = 0xFFFFFFFF


@functools.partial(jax.jit, static_argnames=("num_words",))
def expand_blocks(positions, run_starts, run_ends, dense, dense_word, num_words: int):
    """Expand compressed roaring buffers to packed words on device.

    positions: u32[P] global bit offsets of array-container bits (pad
    0xFFFFFFFF); run_starts/run_ends: u32[N] inclusive global bit
    endpoints of RLE runs (pad with starts > ends); dense: u32[D, 2048]
    raw bitmap-container words with dense_word: i32[D] global word
    offsets (pad num_words). Returns u32[num_words]; callers reshape to
    (rows, WORDS_PER_ROW). num_words must stay below 2^27 so the
    0xFFFFFFFF position pad is out of bounds after >> 5 (67M words for
    the 2047-row i32 coordinate guard — callers clamp).
    """
    words = jnp.zeros((num_words,), jnp.uint32)
    # array containers: one bit per position
    widx = (positions >> 5).astype(jnp.int32)
    mask = jnp.uint32(1) << (positions & 31)
    words = words.at[widx].add(mask, mode="drop")
    # RLE runs, decomposed: partial head/tail word masks scattered by
    # index, full interior words via a +1/-1 diff array + cumsum
    valid = run_starts <= run_ends
    ws = (run_starts >> 5).astype(jnp.int32)
    we = (run_ends >> 5).astype(jnp.int32)
    sbit = run_starts & 31
    ebit = run_ends & 31
    same = ws == we
    head = jnp.uint32(_FULL32) << sbit
    tail = jnp.uint32(_FULL32) >> (31 - ebit)
    oob = jnp.int32(num_words)
    words = words.at[jnp.where(valid, ws, oob)].add(
        head & jnp.where(same, tail, jnp.uint32(_FULL32)), mode="drop"
    )
    words = words.at[jnp.where(valid & ~same, we, oob)].add(tail, mode="drop")
    interior = valid & (we > ws + 1)
    diff = jnp.zeros((num_words + 1,), jnp.int32)
    pad = jnp.int32(num_words + 1)
    diff = diff.at[jnp.where(interior, ws + 1, pad)].add(1, mode="drop")
    diff = diff.at[jnp.where(interior, we, pad)].add(-1, mode="drop")
    cover = jnp.cumsum(diff)[:num_words] > 0
    words = words | jnp.where(cover, jnp.uint32(_FULL32), jnp.uint32(0))
    # dense bitmap containers: raw word blocks at their word offsets
    didx = dense_word[:, None] + jnp.arange(dense.shape[1], dtype=jnp.int32)[None, :]
    return words.at[didx].add(dense, mode="drop")


# -- dispatch-engine support ------------------------------------------------


@functools.partial(jax.jit, donate_argnums=0)
def _zeros_like_donated(buf) -> jax.Array:
    return jnp.zeros_like(buf)


def zeros_like_donated(buf) -> jax.Array:
    """Re-zero a reusable device scratch buffer, donating the old one.

    On TPU/GPU the donated input aliases the output, so a drained
    scratch (e.g. the batcher's pow2 pad lanes) is recycled in place
    instead of allocating fresh HBM every wave. CPU ignores donation
    (and warns), so fall back to a plain zeros_like there.
    """
    db = getattr(buf, "devices", None)
    platform = ""
    try:
        if db is not None:
            platform = next(iter(buf.devices())).platform
    except BaseException:
        platform = ""
    if platform in ("", "cpu"):
        return jnp.zeros_like(buf)
    return _zeros_like_donated(buf)


def materialize_all(arrays: list) -> list:
    """np.asarray over a heterogeneous list of device results.

    One fetch loop for a dispatch wave's outputs: each asarray blocks
    until that computation is done, so later items' device work
    overlaps earlier items' transfers.
    """
    return [np.asarray(a) for a in arrays]
