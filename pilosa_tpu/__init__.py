"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch rebuild of the capabilities of Pilosa (the reference
distributed bitmap index, see SURVEY.md): a sharded boolean-matrix index
queried through PQL, executed as XLA computations over packed-word bitmaps
staged in TPU HBM rather than Go loops over roaring containers.

Layering (mirrors SURVEY.md §1):
  L0 roaring/   — CPU source-of-truth bitmap engine + reference file format
  L0 ops/       — packed-word XLA/Pallas kernels (the TPU data plane)
  L1 core/      — holder → index → field → view → fragment storage tree
  L2 core/row   — cross-shard query-result rows
  L3 pql/       — PQL parser/AST
  L4 executor/  — PQL call tree → per-shard kernels + map/reduce
  L5 parallel/  — shard placement, device mesh, cluster, replication
  L6/7 server/  — programmatic API + HTTP + server runtime
  L8 cli/       — command line
"""

__version__ = "0.1.0"

# Width of a single shard in columns (bits). Matches the reference's
# compile-time constant (reference fragment.go:47-48).
SHARD_WIDTH = 1 << 20
