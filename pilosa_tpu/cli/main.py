"""Command-line interface (L8) — reference cmd/ + ctl/.

Subcommands: server, import, export, check, inspect, metrics, events,
config, generate-config. Config precedence: flags > env (PILOSA_TPU_*)
> TOML file (reference cmd/root.go:90-146).

Run as ``python -m pilosa_tpu <subcommand>``.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import signal
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime

from pilosa_tpu import __version__


def main(argv=None) -> int:
    from pilosa_tpu.utils.jaxplatform import bootstrap

    bootstrap()
    parser = argparse.ArgumentParser(
        prog="pilosa_tpu", description="TPU-native distributed bitmap index"
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("server", help="run a server node")
    p.add_argument("-c", "--config", help="TOML config file")
    p.add_argument("-d", "--data-dir", help="data directory")
    p.add_argument("-b", "--bind", help="host:port to bind")
    p.add_argument("--device-policy", choices=["never", "auto", "always"])
    p.add_argument(
        "--mesh-devices",
        help="SPMD mesh size over the shard axis: a count or 'all' (default off)",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        default=None,
        help="multihost serving: jax.distributed bootstrap + gang-dispatched "
        "SPMD over one global mesh (rank 0 serves HTTP; other ranks follow)",
    )
    p.add_argument(
        "--coordinator-address",
        help="jax.distributed coordinator host:port (same on every rank; "
        "rank 0 hosts it)",
    )
    p.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="this rank's process id, 0..N-1 (0 = serving leader)",
    )
    p.add_argument(
        "--num-processes",
        type=int,
        default=None,
        help="total process count in the multihost deployment",
    )
    p.add_argument("--cluster-disabled", action="store_true", default=None)
    p.add_argument("--coordinator", action="store_true", default=None)
    p.add_argument("--coordinator-host")
    p.add_argument("--replicas", type=int)
    p.add_argument("--hosts", help="comma-separated static cluster hosts")
    p.add_argument("--verbose", action="store_true", default=None)
    p.add_argument("--tls-certificate", help="TLS certificate path (enables https)")
    p.add_argument("--tls-certificate-key", help="TLS certificate key path")
    p.add_argument(
        "--tls-skip-verify",
        action="store_true",
        default=None,
        help="clients skip TLS peer verification",
    )
    p.set_defaults(fn=cmd_server)

    p = sub.add_parser("import", help="bulk-import CSV bits or values")
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--field", required=True)
    p.add_argument("--create", action="store_true", help="create schema if missing")
    p.add_argument(
        "--field-type", default="set", help="field type when creating (set/int/time)"
    )
    p.add_argument("--field-min", type=int, default=0)
    p.add_argument("--field-max", type=int, default=0)
    p.add_argument("--time-quantum", default="")
    p.add_argument(
        "--values",
        action="store_true",
        help="rows are col,value pairs for an int field",
    )
    # reference default: 10M-bit import buffer (ctl/import.go:84).
    # Every batch pays a snapshot per touched fragment, so a small
    # default made big imports quadratic-ish (measured: 2M bits in 20
    # batches spent ~90 s re-snapshotting growing fragments)
    p.add_argument("--batch-size", type=int, default=10_000_000)
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_import)

    p = sub.add_parser("export", help="export a field as CSV")
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--field", required=True)
    p.add_argument("-o", "--output", help="output file (default stdout)")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser(
        "backup",
        help="download a full-holder backup archive (schema + every "
        "fragment, with a per-entry checksum manifest)",
    )
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument("-o", "--output", required=True, help="archive file to write")
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser(
        "restore",
        help="restore a holder backup archive; the whole archive is "
        "checksum-verified before any byte is applied",
    )
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument("archive", help="archive file written by backup")
    p.set_defaults(fn=cmd_restore)

    p = sub.add_parser(
        "check",
        help="run the invariant checker over source trees, or verify "
        "integrity of fragment files",
    )
    p.add_argument(
        "files",
        nargs="*",
        help="directories / .py files → invariant checker; fragment "
        "files → integrity check; no args → check the whole repo",
    )
    p.add_argument(
        "--strict",
        action="store_true",
        help="also fail on suppression hygiene (unknown rule ids, "
        "reasonless disables)",
    )
    p.add_argument(
        "--repair",
        action="store_true",
        help="fragment files only: truncate a torn op-log tail in place "
        "(offline repair; the snapshot base and every intact op survive)",
    )
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("inspect", help="dump container layout of a fragment file")
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser(
        "metrics",
        help="fetch a node's Prometheus /metrics (or recent query traces)",
    )
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument(
        "--traces",
        action="store_true",
        help="fetch /debug/traces (recent query span trees) instead",
    )
    p.add_argument(
        "--pipeline",
        action="store_true",
        help="fetch /debug/pipeline (serving-pipeline queue/shed/batch "
        "snapshot) instead",
    )
    p.add_argument(
        "--cache",
        action="store_true",
        help="fetch /debug/plancache (plan result-cache hit/invalidation/"
        "bytes snapshot) instead",
    )
    p.add_argument(
        "--dispatch",
        action="store_true",
        help="fetch /debug/dispatch (continuous-batching dispatch engine "
        "wave/queue/idle snapshot) instead",
    )
    p.add_argument(
        "--fleet",
        action="store_true",
        help="fetch the fleet-aggregated exposition (/metrics?fleet=true, "
        "gang/federation leaders only) instead",
    )
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "events",
        help="fetch a node's lifecycle event journal (/debug/events)",
    )
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument(
        "--kind",
        help="only events of this kind (e.g. gang.transition, gang.degrade, "
        "gang.reform, client.retry_exhausted)",
    )
    p.add_argument(
        "--since",
        type=int,
        default=0,
        help="only events with a sequence number above this",
    )
    p.add_argument(
        "--follow",
        action="store_true",
        help="tail the journal live: print new events as JSONL, polling "
        "from the last seen seq (Ctrl-C to stop)",
    )
    p.add_argument(
        "--interval",
        type=float,
        default=1.0,
        help="poll interval in seconds for --follow",
    )
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser(
        "debug-bundle",
        help="capture a node's forensics bundle (/debug/bundle) to a tar: "
        "config, status, metrics, traces, events tail, heat snapshot, "
        "governor/dispatch/fusion stats",
    )
    p.add_argument("--host", default="http://localhost:10101")
    p.add_argument(
        "-o", "--output", default="pilosa-debug-bundle.tar",
        help="output tar path",
    )
    p.set_defaults(fn=cmd_debug_bundle)

    p = sub.add_parser("config", help="print the effective configuration")
    p.add_argument("-c", "--config", help="TOML config file")
    p.set_defaults(fn=cmd_config)

    p = sub.add_parser("generate-config", help="print the default configuration")
    p.set_defaults(fn=cmd_generate_config)

    args = parser.parse_args(argv)
    return args.fn(args)


def _load_config(args):
    from pilosa_tpu.server import Config

    cfg = Config.from_toml(args.config) if getattr(args, "config", None) else Config()
    cfg.apply_env()
    return cfg


def cmd_server(args) -> int:
    from pilosa_tpu.server import Server

    cfg = _load_config(args)
    if args.data_dir:
        cfg.data_dir = args.data_dir
    if args.bind:
        cfg.bind = args.bind
    if args.device_policy:
        cfg.device_policy = args.device_policy
    if args.mesh_devices:
        cfg.mesh_devices = args.mesh_devices
    if args.verbose is not None:
        cfg.verbose = args.verbose
    if args.cluster_disabled is not None:
        cfg.cluster.disabled = args.cluster_disabled
    if args.coordinator is not None:
        cfg.cluster.coordinator = args.coordinator
        cfg.cluster.disabled = False
    if args.coordinator_host:
        cfg.cluster.coordinator_host = args.coordinator_host
        cfg.cluster.disabled = False
    if args.replicas:
        cfg.cluster.replicas = args.replicas
    if args.hosts:
        cfg.cluster.hosts = args.hosts.split(",")
        cfg.cluster.disabled = False
    if args.tls_certificate:
        cfg.tls.certificate_path = args.tls_certificate
    if args.tls_certificate_key:
        cfg.tls.certificate_key_path = args.tls_certificate_key
    if args.tls_skip_verify is not None:
        cfg.tls.skip_verify = args.tls_skip_verify
    if args.distributed is not None:
        cfg.distributed_enabled = args.distributed
    if args.coordinator_address:
        cfg.distributed_coordinator = args.coordinator_address
        cfg.distributed_enabled = True
    if args.process_id is not None:
        cfg.distributed_process_id = args.process_id
    if args.num_processes is not None:
        cfg.distributed_num_processes = args.num_processes

    server = Server(cfg)
    server.open()
    stop = []
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    try:
        if server.multihost is not None and server.multihost.rank != 0:
            # follower rank: the worker loop IS the serving loop —
            # blocks until the leader's poison pill (clean shutdown)
            # or leader loss (deadline-fenced abort)
            reason = server.serve_follower()
            print(f"multihost follower stopped: {reason}", file=sys.stderr)
        else:
            while not stop:
                time.sleep(0.2)
    finally:
        server.close()
    return 0


def _post(host, path, body, is_json=True, timeout: float = 60) -> dict:
    data = json.dumps(body).encode() if is_json else body
    req = urllib.request.Request(host + path, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _try_native_csv(path):
    """(rows, cols) u64 arrays via the native parser, or None. Probes
    the first few KB before committing: a file the fast path cannot
    take (timestamps, quoting — fully supported by the csv loop) costs
    a 4 KB read, not a full-file slurp; the qualifying file parses
    straight from an mmap, so peak memory is the output arrays."""
    import mmap as _mmap

    from pilosa_tpu import native_bridge

    try:
        with open(path, "rb") as bf:
            mm = _mmap.mmap(bf.fileno(), 0, access=_mmap.ACCESS_READ)
    except (OSError, ValueError):  # unmmappable (empty file, pipe)
        return None
    try:
        head = mm[:4096]
        if len(head) == 4096:
            cut = head.rfind(b"\n")
            if cut < 0:
                return None  # one huge line: not this format
            head = head[: cut + 1]
        if native_bridge.parse_csv_pairs(head) is None:
            return None
        try:
            return native_bridge.parse_csv_pairs(mm)
        except MemoryError:
            # output arrays (~16 B/pair) didn't fit: the csv loop
            # streams in batch_size chunks and completes where the
            # one-shot arrays cannot
            return None
    finally:
        mm.close()


def cmd_import(args) -> int:
    host = args.host if args.host.startswith("http") else f"http://{args.host}"
    if args.create:
        try:
            _post(host, f"/index/{args.index}", {})
        except urllib.error.HTTPError as e:
            if e.code != 409:
                raise
        opts = {"type": args.field_type}
        if args.field_type == "int":
            opts.update(min=args.field_min, max=args.field_max)
        if args.time_quantum:
            opts["timeQuantum"] = args.time_quantum
        try:
            _post(host, f"/index/{args.index}/field/{args.field}", {"options": opts})
        except urllib.error.HTTPError as e:
            if e.code != 409:
                raise

    def flush(rows, cols, timestamps):
        if not cols:
            return
        # the server json-decodes the body and runs merge+snapshot
        # before responding; scale the timeout with the batch (a 10M-bit
        # reference-default batch is ~150 MB of JSON) instead of letting
        # a fixed 60 s abort a large import mid-way
        timeout = max(60.0, 60.0 + len(cols) / 20_000)
        if args.values:
            # value-mode CSV is columnID,value (reference
            # ctl/import.go:404-415), so the first CSV field — parsed
            # into `rows` — is the column id and the second the value
            _post(
                host,
                f"/index/{args.index}/field/{args.field}/import-value",
                {"columnIDs": rows, "values": cols},
                timeout=timeout,
            )
        else:
            body = {"rowIDs": rows, "columnIDs": cols}
            if any(t for t in timestamps):
                body["timestamps"] = timestamps
            _post(
                host,
                f"/index/{args.index}/field/{args.field}/import",
                body,
                timeout=timeout,
            )

    total = 0
    for path in args.files:
        if path != "-" and args.batch_size > 0:
            # native fast path: strict numeric 2-column CSV parses at
            # C speed (native/bitmap_kernels.cpp pt_parse_csv_pairs);
            # any deviation — timestamps, quoting, junk — returns None
            # and the Python csv loop below handles it with proper
            # per-line errors (reference ctl/import.go semantics)
            parsed = _try_native_csv(path)
            if parsed is not None:
                a, b = parsed
                for lo in range(0, len(a), args.batch_size):
                    hi = min(lo + args.batch_size, len(a))
                    # the strict format has no timestamp column; flush
                    # skips the key for an empty list
                    flush(a[lo:hi].tolist(), b[lo:hi].tolist(), [])
                    total += hi - lo
                continue
        f = sys.stdin if path == "-" else open(path)
        rows, cols, timestamps = [], [], []
        try:
            for lineno, record in enumerate(csv.reader(f), 1):
                if not record or not record[0].strip():
                    continue
                try:
                    a = int(record[0])
                    b = int(record[1])
                except (ValueError, IndexError) as e:
                    print(f"{path}:{lineno}: bad record {record!r}: {e}", file=sys.stderr)
                    return 1
                rows.append(a)
                cols.append(b)
                ts = 0
                if len(record) > 2 and record[2].strip():
                    ts = int(
                        datetime.strptime(
                            record[2].strip(), "%Y-%m-%dT%H:%M"
                        ).timestamp()
                    )
                timestamps.append(ts)
                if len(cols) >= args.batch_size:
                    flush(rows, cols, timestamps)
                    total += len(cols)
                    rows, cols, timestamps = [], [], []
        finally:
            if f is not sys.stdin:
                f.close()
        flush(rows, cols, timestamps)
        total += len(cols)
    print(f"imported {total} records", file=sys.stderr)
    return 0


def cmd_export(args) -> int:
    host = args.host if args.host.startswith("http") else f"http://{args.host}"
    with urllib.request.urlopen(host + "/internal/shards/max", timeout=60) as resp:
        max_shards = json.loads(resp.read()).get("standard", {})
    max_shard = max_shards.get(args.index, 0)
    out = open(args.output, "w") if args.output else sys.stdout
    try:
        for shard in range(max_shard + 1):
            url = f"{host}/export?index={args.index}&field={args.field}&shard={shard}"
            with urllib.request.urlopen(url, timeout=60) as resp:
                out.write(resp.read().decode())
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


def cmd_backup(args) -> int:
    """Stream GET /backup to a file (reference ctl/backup.go)."""
    host = args.host if args.host.startswith("http") else f"http://{args.host}"
    host = host.rstrip("/")
    r = urllib.request.Request(host + "/backup", method="GET")
    with urllib.request.urlopen(r, timeout=600) as resp:
        data = resp.read()
    with open(args.output, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    print(f"backup: wrote {len(data)} bytes to {args.output}")
    return 0


def cmd_restore(args) -> int:
    """POST an archive to /restore (reference ctl/restore.go). The
    server verifies the manifest before applying; a refusal (400)
    exits non-zero with the server's reason."""
    host = args.host if args.host.startswith("http") else f"http://{args.host}"
    host = host.rstrip("/")
    with open(args.archive, "rb") as f:
        data = f.read()
    r = urllib.request.Request(host + "/restore", data=data, method="POST")
    try:
        with urllib.request.urlopen(r, timeout=600) as resp:
            body = json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            reason = json.loads(e.read() or b"{}").get("error", str(e))
        except Exception:
            reason = str(e)
        print(f"restore: REFUSED: {reason}", file=sys.stderr)
        return 1
    print(f"restore: applied ({body.get('fragments', 0)} fragments)")
    return 0


def _open_lazy(path):
    """Mmap-open a roaring file: check/inspect of a 1B-scale fragment
    (~15.6M containers) must stream, not materialize one Python object
    per container. Same open semantics as the fragment runtime."""
    from pilosa_tpu.roaring import Bitmap

    return Bitmap.open_mmap_file(path)


def cmd_check(args) -> int:
    """Dispatch by path kind: source trees / .py files go to the
    invariant checker (analysis/lint.py); anything else keeps the
    original fragment-file integrity check (reference ctl/check.go).
    No paths at all means lint the whole repo — the CI gate."""
    import os

    code_paths = [
        p for p in args.files if os.path.isdir(p) or p.endswith(".py")
    ]
    frag_paths = [p for p in args.files if p not in code_paths]
    if not args.files:
        code_paths = None  # checker default: the repo root
    rc = 0
    if code_paths is None or code_paths:
        rc = max(rc, _check_code(code_paths, strict=args.strict))
    if frag_paths:
        rc = max(rc, _check_fragments(frag_paths, repair=args.repair))
    return rc


def _check_code(paths, strict: bool) -> int:
    from pilosa_tpu.analysis import lint

    findings = lint.check_paths(paths, strict=strict)
    for f in findings:
        print(f.format(), file=sys.stderr)
    n_files = len(lint.iter_py_files(paths or [lint.repo_root()]))
    if findings:
        print(
            f"check: {len(findings)} finding(s) in {n_files} files",
            file=sys.stderr,
        )
        return 1
    print(f"check: clean ({n_files} files)")
    return 0


def _check_fragments(files, repair: bool = False) -> int:
    rc = 0
    for path in files:
        if path.endswith(".cache") or path.endswith(".snapshotting"):
            continue
        try:
            # byte-level integrity first (digest trailer + op-log CRC
            # walk): a rotted base or torn tail must exit non-zero
            # BEFORE the container walk can trip over decoded garbage
            err = _check_file_bytes(path, repair=repair)
            if err is not None:
                raise ValueError(err)
            b = _open_lazy(path)
            # container-level invariants (streaming: one ephemeral
            # decode at a time)
            n_containers = 0
            for key in b._iter_keys_sorted():
                c = b.containers[key]
                p = c.positions()
                if p.size != c.n:
                    raise ValueError(
                        f"container {key}: cardinality mismatch {p.size} != {c.n}"
                    )
                if p.size > 1 and not (p[:-1] < p[1:]).all():
                    raise ValueError(f"container {key}: positions not sorted/unique")
                n_containers += 1
            # validate the sidecar BEFORE printing the fragment's ok
            # line: a corrupt sidecar must not leave 'path: ok' on
            # stdout for a path that exits 1
            occ = _check_occ_sidecar(path, b)
            print(f"{path}: ok (bits={b.count()}, containers={n_containers}, ops={b.op_n})")
            if occ is not None:
                print(f"{path}.occ: {occ}")
        except Exception as e:
            print(f"{path}: FAILED: {e}", file=sys.stderr)
            rc = 1
    return rc


def _check_file_bytes(path: str, repair: bool = False) -> "str | None":
    """Offline byte-level verification (reference ctl/check.go, extended
    for the checksummed snapshot format): the blake2b digest trailer
    over the base, then a CRC/framing walk of the op-log tail. Returns
    an error string (→ exit 1), or None. With ``repair``, a torn tail
    is truncated in place at the last valid record boundary — the
    exact cut crash recovery would make at the next open, done offline
    so the file verifies clean NOW."""
    from pilosa_tpu.roaring import bitmap as bm

    with open(path, "rb") as f:
        data = f.read()
    if len(data) < bm.HEADER_BASE_SIZE:
        return None  # empty/new fragment: nothing to verify
    try:
        base_end = bm.snapshot_base_end(data)
    except Exception as e:
        return f"snapshot header unparseable: {e}"
    if bm.has_digest_trailer(data, base_end):
        if not bm.verify_digest_trailer(data, base_end):
            return "snapshot digest mismatch (base bytes rotted)"
    ops_offset = bm.ops_offset_of(data)
    valid_end, n_ops = bm.scan_op_log(data, ops_offset)
    if valid_end < len(data):
        torn = len(data) - valid_end
        if not repair:
            return (
                f"op log torn/corrupt at byte {valid_end} "
                f"({torn} trailing bytes; --repair truncates them)"
            )
        with open(path, "r+b") as f:
            f.truncate(valid_end)
            f.flush()
            os.fsync(f.fileno())
        print(
            f"{path}: repaired (truncated {torn} torn bytes; "
            f"{n_ops} intact ops kept)"
        )
    return None


def _check_occ_sidecar(path: str, b) -> "str | None":
    """Validate a .occ occupancy sidecar against the fragment it
    accelerates: the mmap store's loader applies the staleness stamp
    (size/mtime/base) exactly as the serving path would, then the keys
    and prefix sums are recomputed from the file and compared. Returns
    a status string, or None when no sidecar exists / the store isn't
    mmap-backed."""
    import os as _os

    import numpy as np

    if not _os.path.exists(path + ".occ"):
        return None
    store = getattr(b, "containers", None)
    if not hasattr(store, "_occ_sidecar_load"):
        return None
    got = store._occ_sidecar_load()
    if got is None:
        return "stale (stamp mismatch; serving ignores it — safe to delete)"
    from pilosa_tpu.roaring.mmapstore import occ_arrays

    keys, cs = occ_arrays(*store.keys_and_counts())
    if np.array_equal(got[0], keys) and np.array_equal(got[1], cs):
        return f"ok (containers={keys.size}, bits={int(cs[-1]) if cs.size else 0})"
    # a sidecar that PASSES the staleness stamp but disagrees with the
    # file would poison sparse staging — that is a real integrity
    # failure, not a stale-and-ignored accelerator
    raise ValueError(
        ".occ sidecar passes the staleness stamp but disagrees with the file"
    )


def cmd_inspect(args) -> int:
    """Dump container layout (reference ctl/inspect.go)."""
    names = {1: "array", 2: "bitmap", 3: "run"}
    for path in args.files:
        b = _open_lazy(path)
        print(f"{path}: bits={b.count()} containers={len(b.containers)} opN={b.op_n}")
        print(f"{'KEY':>12} {'TYPE':>8} {'N':>8} {'SIZE':>8}")
        for key in b._iter_keys_sorted():
            c = b.containers[key]
            print(f"{key:>12} {names.get(c.typ, '?'):>8} {c.n:>8} {c.size():>8}")
    return 0


def cmd_metrics(args) -> int:
    """Dump a node's observability surface: Prometheus text from
    /metrics, the recent-trace ring buffer with --traces, the
    serving-pipeline snapshot with --pipeline, the plan result-cache
    snapshot with --cache, or the dispatch-engine snapshot with
    --dispatch."""
    host = args.host if args.host.startswith("http") else f"http://{args.host}"
    if getattr(args, "dispatch", False):
        path = "/debug/dispatch"
    elif getattr(args, "cache", False):
        path = "/debug/plancache"
    elif args.pipeline:
        path = "/debug/pipeline"
    elif args.traces:
        path = "/debug/traces"
    else:
        path = "/metrics"
    if path != "/metrics":
        with urllib.request.urlopen(host + path, timeout=60) as resp:
            print(json.dumps(json.loads(resp.read().decode()), indent=2))
        return 0
    if getattr(args, "fleet", False):
        path = "/metrics?fleet=true"
    with urllib.request.urlopen(host + path, timeout=60) as resp:
        print(resp.read().decode(), end="")
    return 0


def cmd_events(args) -> int:
    """Dump a node's lifecycle event journal: gang state transitions,
    degrades, re-formations, and retry exhaustions, each stamped with
    seq/time/trace/gang/rank/epoch. ``--follow`` tails the journal
    live, paging from the durable backing via ``since=<last seq>``."""
    host = args.host if args.host.startswith("http") else f"http://{args.host}"

    def fetch(since: int) -> list:
        query = []
        if args.kind:
            query.append(f"kind={urllib.parse.quote(args.kind)}")
        if since:
            query.append(f"since={since}")
        path = "/debug/events" + (("?" + "&".join(query)) if query else "")
        with urllib.request.urlopen(host + path, timeout=60) as resp:
            return json.loads(resp.read().decode()).get("events", [])

    if not getattr(args, "follow", False):
        evs = fetch(args.since)
        print(json.dumps({"events": evs}, indent=2))
        return 0
    since = args.since
    try:
        while True:
            for ev in fetch(since):
                print(json.dumps(ev, separators=(",", ":")), flush=True)
                if ev.get("seq", 0) > since:
                    since = ev["seq"]
            time.sleep(max(0.05, args.interval))
    except KeyboardInterrupt:
        return 0


def cmd_debug_bundle(args) -> int:
    """Stream GET /debug/bundle to a tar file — everything an incident
    writeup needs from a live (or about-to-die) node in one capture."""
    host = args.host if args.host.startswith("http") else f"http://{args.host}"
    host = host.rstrip("/")
    r = urllib.request.Request(host + "/debug/bundle", method="GET")
    with urllib.request.urlopen(r, timeout=120) as resp:
        data = resp.read()
    with open(args.output, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    print(f"debug-bundle: wrote {len(data)} bytes to {args.output}")
    return 0


def cmd_config(args) -> int:
    cfg = _load_config(args)
    print(cfg.to_toml(), end="")
    return 0


def cmd_generate_config(args) -> int:
    from pilosa_tpu.server import Config

    print(Config().to_toml(), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
