"""Command line (L8)."""

from pilosa_tpu.cli.main import main

__all__ = ["main"]
