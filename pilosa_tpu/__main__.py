"""``python -m pilosa_tpu`` entry point."""

import sys

from pilosa_tpu.cli.main import main

sys.exit(main())
