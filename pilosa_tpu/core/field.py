"""Field — container of views + typed options (reference field.go).

Types: ``set`` (plain rows), ``int`` (bit-sliced integers with one
bsiGroup named after the field), ``time`` (set + per-quantum views).
"""

from __future__ import annotations

import json
import os
import threading
from datetime import datetime
from typing import Iterable, Optional

import numpy as np

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.fragment import _sized
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.timequantum import views_by_time
from pilosa_tpu.core.view import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD, View

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"

DEFAULT_CACHE_TYPE = cache_mod.CACHE_TYPE_RANKED
DEFAULT_CACHE_SIZE = cache_mod.DEFAULT_CACHE_SIZE


class FieldOptions:
    """reference FieldOptions (field.go:1111-1120)."""

    def __init__(
        self,
        type: str = FIELD_TYPE_SET,
        cache_type: str = DEFAULT_CACHE_TYPE,
        cache_size: int = DEFAULT_CACHE_SIZE,
        min: int = 0,
        max: int = 0,
        time_quantum: str = "",
        keys: bool = False,
    ) -> None:
        self.type = type
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.min = min
        self.max = max
        self.time_quantum = time_quantum
        self.keys = keys

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "cacheType": self.cache_type,
            "cacheSize": self.cache_size,
            "min": self.min,
            "max": self.max,
            "timeQuantum": self.time_quantum,
            "keys": self.keys,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FieldOptions":
        return cls(
            type=d.get("type", FIELD_TYPE_SET),
            cache_type=d.get("cacheType", DEFAULT_CACHE_TYPE),
            cache_size=d.get("cacheSize", DEFAULT_CACHE_SIZE),
            min=d.get("min", 0),
            max=d.get("max", 0),
            time_quantum=d.get("timeQuantum", ""),
            keys=d.get("keys", False),
        )


class BSIGroup:
    """Bit-sliced integer group (reference bsiGroup, field.go:1218-1299)."""

    def __init__(self, name: str, min_val: int, max_val: int) -> None:
        self.name = name
        self.min = min_val
        self.max = max_val

    def bit_depth(self) -> int:
        """reference BitDepth: smallest i with max-min < 2^i."""
        for i in range(63):
            if self.max - self.min < (1 << i):
                return i
        return 63

    def base_value(self, op: str, value: int) -> tuple[int, bool]:
        """Map an absolute predicate onto the stored base-offset encoding
        (reference baseValue, field.go). Returns (base_value, out_of_range)."""
        base = 0
        if op in (">", ">="):
            if value > self.max:
                return 0, True
            if value > self.min:
                base = value - self.min
        elif op in ("<", "<="):
            if value < self.min:
                return 0, True
            if value > self.max:
                base = self.max - self.min
            else:
                base = value - self.min
        elif op in ("==", "!="):
            if value < self.min or value > self.max:
                return 0, True
            base = value - self.min
        return base, False

    def base_value_between(self, lo: int, hi: int) -> tuple[int, int, bool]:
        if hi < self.min or lo > self.max:
            return 0, 0, True
        base_min = lo - self.min if lo > self.min else 0
        if hi > self.max:
            base_max = self.max - self.min
        elif hi > self.min:
            base_max = hi - self.min
        else:
            base_max = 0
        return base_min, base_max, False


class Field:
    def __init__(
        self,
        path: Optional[str],
        index: str,
        name: str,
        options: Optional[FieldOptions] = None,
        row_attr_store=None,
        broadcaster=None,
    ) -> None:
        self.path = path
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        self.row_attr_store = row_attr_store
        self.broadcaster = broadcaster
        self.views: dict[str, View] = {}
        self.bsi_groups: dict[str, BSIGroup] = {}
        self.mu = threading.RLock()
        if self.options.type == FIELD_TYPE_INT:
            self.bsi_groups[name] = BSIGroup(name, self.options.min, self.options.max)

    # -- lifecycle --

    def open(self) -> None:
        if self.path:
            os.makedirs(self.path, exist_ok=True)
            self._load_meta()
            views_dir = os.path.join(self.path, "views")
            if os.path.isdir(views_dir):
                for vname in sorted(os.listdir(views_dir)):
                    v = self._new_view(vname)
                    v.open()
                    self.views[vname] = v
        if self.options.type == FIELD_TYPE_INT and self.name not in self.bsi_groups:
            self.bsi_groups[self.name] = BSIGroup(
                self.name, self.options.min, self.options.max
            )

    def close(self) -> None:
        for v in self.views.values():
            v.close()

    def _meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def save_meta(self) -> None:
        if not self.path:
            return
        os.makedirs(self.path, exist_ok=True)
        with open(self._meta_path(), "w") as f:
            json.dump(self.options.to_dict(), f)

    def _load_meta(self) -> None:
        try:
            with open(self._meta_path(), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            self.save_meta()
            return
        try:
            self.options = FieldOptions.from_dict(json.loads(raw))
        except (ValueError, UnicodeDecodeError):
            # reference data dir: .meta is a protobuf FieldOptions
            from pilosa_tpu.utils.protometa import decode_field_options

            self.options = FieldOptions.from_dict(decode_field_options(raw))

    # -- accessors --

    def type(self) -> str:
        return self.options.type

    def time_quantum(self) -> str:
        return self.options.time_quantum

    def bsi_group(self, name: str) -> Optional[BSIGroup]:
        return self.bsi_groups.get(name)

    def _new_view(self, name: str) -> View:
        return View(
            os.path.join(self.path, "views", name) if self.path else None,
            self.index,
            self.name,
            name,
            cache_type=self.options.cache_type,
            cache_size=self.options.cache_size,
            row_attr_store=self.row_attr_store,
            broadcaster=self.broadcaster,
        )

    def view(self, name: str) -> Optional[View]:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        with self.mu:
            v = self.views.get(name)
            if v is None:
                v = self._new_view(name)
                v.open()
                self.views[name] = v
            return v

    def available_shards(self) -> list[int]:
        shards: set[int] = set()
        for v in self.views.values():
            shards.update(v.fragments)
        return sorted(shards)

    def max_shard(self) -> int:
        shards = self.available_shards()
        return shards[-1] if shards else 0

    # -- row / bit ops --

    def row(self, row_id: int) -> Row:
        if self.type() not in (FIELD_TYPE_SET, FIELD_TYPE_TIME):
            raise ValueError(f"row method unsupported for field type: {self.type()}")
        v = self.view(VIEW_STANDARD)
        if v is None:
            return Row()
        return v.row(row_id)

    def set_bit(self, row_id: int, col_id: int, t: Optional[datetime] = None) -> bool:
        """reference Field.SetBit (field.go:683-719): standard view plus
        time-quantum fan-out."""
        changed = False
        v = self.create_view_if_not_exists(VIEW_STANDARD)
        changed |= v.set_bit(row_id, col_id)
        if t is None:
            return changed
        for subname in views_by_time(VIEW_STANDARD, t, self.time_quantum()):
            sv = self.create_view_if_not_exists(subname)
            changed |= sv.set_bit(row_id, col_id)
        return changed

    def clear_bit(self, row_id: int, col_id: int) -> bool:
        """reference Field.ClearBit (field.go:722-764): clear standard
        view, then walk time views hierarchically, skipping subtrees
        whose parent was already clear."""
        v = self.view(VIEW_STANDARD)
        if v is None:
            raise ValueError("clearing missing view")
        changed = v.clear_bit(row_id, col_id)
        if len(self.views) == 1:
            return changed
        last_size = 0
        level = 0
        skip_above = 1 << 62
        for view in self._all_time_views_sorted_by_quantum():
            if last_size < len(view.name):
                level += 1
            elif last_size > len(view.name):
                level -= 1
            if level < skip_above:
                c = view.clear_bit(row_id, col_id)
                changed = c
                skip_above = (level + 1) if not c else (1 << 62)
            last_size = len(view.name)
        return changed

    def _all_time_views_sorted_by_quantum(self) -> list[View]:
        """Time views ordered coarse→fine, depth-first (reference
        allTimeViewsSortedByQuantum, field.go:766+)."""
        names = sorted(
            n for n in self.views if n.startswith(VIEW_STANDARD + "_")
        )
        return [self.views[n] for n in names]

    # -- BSI ops --

    def bsi_view_name(self) -> str:
        return VIEW_BSI_GROUP_PREFIX + self.name

    def value(self, col_id: int) -> tuple[int, bool]:
        bsig = self.bsi_group(self.name)
        if bsig is None:
            raise ValueError(f"bsiGroup not found: {self.name}")
        v = self.view(self.bsi_view_name())
        if v is None:
            return 0, False
        val, exists = v.value(col_id, bsig.bit_depth())
        if not exists:
            return 0, False
        return val + bsig.min, True

    def set_value(self, col_id: int, value: int) -> bool:
        bsig = self.bsi_group(self.name)
        if bsig is None:
            raise ValueError(f"bsiGroup not found: {self.name}")
        if value < bsig.min or value > bsig.max:
            raise ValueError(
                f"value {value} out of range [{bsig.min}, {bsig.max}]"
            )
        v = self.create_view_if_not_exists(self.bsi_view_name())
        return v.set_value(col_id, bsig.bit_depth(), value - bsig.min)

    # -- bulk import (reference Import:960-1071) --

    def import_bits(
        self,
        row_ids: Iterable[int],
        column_ids: Iterable[int],
        timestamps: Optional[Iterable[Optional[datetime]]] = None,
    ) -> None:
        """Group (row, col, ts) by (view, shard) then bulk-import each
        fragment."""
        rows = np.asarray(_sized(row_ids), dtype=np.uint64)
        cols = np.asarray(_sized(column_ids), dtype=np.uint64)
        tss = list(timestamps) if timestamps is not None else None
        if rows.size != cols.size or (tss is not None and len(tss) != rows.size):
            raise ValueError("row/col/timestamp length mismatch")
        if rows.size == 0:
            # no views created on an empty import (reference Import
            # groups first and only touches views with data)
            return
        q = self.time_quantum()

        def import_group(vname: str, rs, cs) -> None:
            view = self.create_view_if_not_exists(vname)
            shards = cs // np.uint64(SHARD_WIDTH)
            order = np.argsort(shards, kind="stable")
            shards, rs, cs = shards[order], rs[order], cs[order]
            uniq, starts = np.unique(shards, return_index=True)
            bounds = np.append(starts, shards.size)
            for k, shard in enumerate(uniq):
                frag = view.create_fragment_if_not_exists(int(shard))
                frag.bulk_import(rs[bounds[k] : bounds[k + 1]], cs[bounds[k] : bounds[k + 1]])

        if tss is None or not any(t is not None for t in tss):
            # fast path: vectorised single-view grouping by shard
            import_group(VIEW_STANDARD, rows, cols)
            return
        # timestamped bits fan out to quantum views; group per view name
        if not q:
            raise ValueError("time quantum not set in field")
        per_view: dict[str, list[int]] = {VIEW_STANDARD: list(range(rows.size))}
        for i, t in enumerate(tss):
            if t is None:
                continue
            for vname in views_by_time(VIEW_STANDARD, t, q):
                per_view.setdefault(vname, []).append(i)
        for vname in sorted(per_view):
            sel = np.asarray(per_view[vname], dtype=np.int64)
            import_group(vname, rows[sel], cols[sel])

    def import_values(
        self, column_ids: Iterable[int], values: Iterable[int]
    ) -> None:
        bsig = self.bsi_group(self.name)
        if bsig is None:
            raise ValueError(f"bsiGroup not found: {self.name}")
        cols = np.asarray(_sized(column_ids), dtype=np.uint64)
        vals = np.asarray(_sized(values), dtype=np.int64)
        if cols.size != vals.size:
            raise ValueError("column/value mismatch")
        if cols.size == 0:
            return  # no views created on an empty import
        if int(vals.min()) < bsig.min or int(vals.max()) > bsig.max:
            bad = vals[(vals < bsig.min) | (vals > bsig.max)][0]
            raise ValueError(
                f"value {int(bad)} out of range [{bsig.min}, {bsig.max}]"
            )
        offsets = (vals - bsig.min).astype(np.uint64)
        shards = cols // np.uint64(SHARD_WIDTH)
        order = np.argsort(shards, kind="stable")
        shards, cols, offsets = shards[order], cols[order], offsets[order]
        uniq, starts = np.unique(shards, return_index=True)
        bounds = np.append(starts, shards.size)
        view = self.create_view_if_not_exists(self.bsi_view_name())
        for k, shard in enumerate(uniq):
            frag = view.create_fragment_if_not_exists(int(shard))
            frag.import_value(
                cols[bounds[k] : bounds[k + 1]],
                offsets[bounds[k] : bounds[k + 1]],
                bsig.bit_depth(),
            )
