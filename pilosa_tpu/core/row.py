"""Row — a cross-shard query-result bitmap (L2).

Mirrors the reference's Row/RowSegment (reference row.go:27-35,309-324):
a sorted list of per-shard segments, each a roaring bitmap holding
*absolute* column positions for one shard of 2^20 columns. Set algebra
pairs up segments by shard (reference's merge-iterator, row.go:436-478).
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.roaring import Bitmap


class Row:
    """Query-result bitmap spanning shards."""

    __slots__ = ("segments", "_count", "attrs", "keys")

    def __init__(self, *columns: int) -> None:
        # shard -> Bitmap of absolute column positions within that shard
        self.segments: dict[int, Bitmap] = {}
        self._count: Optional[int] = None
        self.attrs: dict = {}
        self.keys: list[str] = []
        for c in columns:
            self.set_bit(c)

    @classmethod
    def from_segment(cls, shard: int, bitmap: Bitmap) -> "Row":
        r = cls()
        r.segments[shard] = bitmap
        return r

    # -- mutation (used when materialising rows / merging) --

    def set_bit(self, col: int) -> bool:
        shard = col // SHARD_WIDTH
        seg = self.segments.get(shard)
        if seg is None:
            seg = Bitmap()
            self.segments[shard] = seg
        changed = seg.add_no_oplog(col)
        if changed:
            self._count = None
        return changed

    def clear_bit(self, col: int) -> bool:
        shard = col // SHARD_WIDTH
        seg = self.segments.get(shard)
        if seg is None:
            return False
        changed = seg.remove_no_oplog(col)
        if changed:
            self._count = None
        return changed

    def invalidate_count(self) -> None:
        self._count = None

    # -- set algebra (segment-pairwise, reference row.go:87-237) --

    def intersect(self, other: "Row") -> "Row":
        out = Row()
        for shard in self.segments.keys() & other.segments.keys():
            out.segments[shard] = self.segments[shard].intersect(other.segments[shard])
        return out

    def union(self, other: "Row") -> "Row":
        out = Row()
        for shard in self.segments.keys() | other.segments.keys():
            a = self.segments.get(shard)
            b = other.segments.get(shard)
            if a is None:
                out.segments[shard] = b.clone()
            elif b is None:
                out.segments[shard] = a.clone()
            else:
                out.segments[shard] = a.union(b)
        return out

    def difference(self, other: "Row") -> "Row":
        out = Row()
        for shard, a in self.segments.items():
            b = other.segments.get(shard)
            out.segments[shard] = a.clone() if b is None else a.difference(b)
        return out

    def xor(self, other: "Row") -> "Row":
        out = Row()
        for shard in self.segments.keys() | other.segments.keys():
            a = self.segments.get(shard)
            b = other.segments.get(shard)
            if a is None:
                out.segments[shard] = b.clone()
            elif b is None:
                out.segments[shard] = a.clone()
            else:
                out.segments[shard] = a.xor(b)
        return out

    def intersection_count(self, other: "Row") -> int:
        n = 0
        for shard in self.segments.keys() & other.segments.keys():
            n += self.segments[shard].intersection_count(other.segments[shard])
        return n

    # -- accessors --

    def count(self) -> int:
        if self._count is None:
            self._count = sum(s.count() for s in self.segments.values())
        return self._count

    def any(self) -> bool:
        return any(s.any() for s in self.segments.values())

    def columns(self) -> np.ndarray:
        """All set columns as a sorted uint64 array."""
        parts = [
            self.segments[shard].slice_all() for shard in sorted(self.segments)
        ]
        parts = [p for p in parts if p.size]
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def includes_column(self, col: int) -> bool:
        seg = self.segments.get(col // SHARD_WIDTH)
        return seg is not None and seg.contains(col)

    def shard_segment(self, shard: int) -> Optional[Bitmap]:
        return self.segments.get(shard)

    def merge(self, other: "Row") -> None:
        """In-place union used by the executor's cross-shard reduce
        (reference Row.Merge, row.go:251)."""
        for shard, seg in other.segments.items():
            mine = self.segments.get(shard)
            if mine is None:
                self.segments[shard] = seg
            else:
                self.segments[shard] = mine.union(seg)
        self._count = None

    def __eq__(self, other) -> bool:
        if not isinstance(other, Row):
            return NotImplemented
        return self.columns().tolist() == other.columns().tolist()

    def __repr__(self) -> str:
        return f"Row(count={self.count()}, shards={sorted(self.segments)})"


def union_rows(rows: Iterable[Row]) -> Row:
    """n-ary union (reference Union(rows []*Row), row.go:301)."""
    out = Row()
    for r in rows:
        out = out.union(r)
    return out
