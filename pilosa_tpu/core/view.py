"""View — container of fragments by shard (reference view.go).

View names: ``standard``, time-quantum subviews ``standard_2017…``, and
``bsig_<field>`` for bit-sliced integer groups (reference view.go:30-35).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.row import Row
from pilosa_tpu.core import cache as cache_mod

VIEW_STANDARD = "standard"
VIEW_BSI_GROUP_PREFIX = "bsig_"


def view_path(index_path: str, field: str, view: str) -> str:
    return os.path.join(index_path, field, "views", view)


class View:
    def __init__(
        self,
        path: Optional[str],
        index: str,
        field: str,
        name: str,
        cache_type: str = cache_mod.CACHE_TYPE_RANKED,
        cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
        row_attr_store=None,
        broadcaster: Optional[Callable[[str, int], None]] = None,
    ) -> None:
        self.path = path
        self.index = index
        self.field = field
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.row_attr_store = row_attr_store
        # called with (index, shard) when a new max shard appears
        # (reference view.go:216-247 CreateShardMessage broadcast)
        self.broadcaster = broadcaster
        self.fragments: dict[int, Fragment] = {}
        self.mu = threading.RLock()

    # -- lifecycle --

    def open(self) -> None:
        """Register on-disk fragments WITHOUT opening them: a holder
        tree with thousands of fragments opens in O(touched) — each
        fragment mmaps and parses on first access (reference keeps
        startup cheap the same way via zero-copy mmap open,
        fragment.go:167-224; we go one step lazier)."""
        if not self.path:
            return
        frag_dir = os.path.join(self.path, "fragments")
        os.makedirs(frag_dir, exist_ok=True)
        for name in sorted(os.listdir(frag_dir)):
            if name.endswith(".cache") or name.endswith(".snapshotting"):
                continue
            try:
                shard = int(name)
            except ValueError:
                continue
            self.fragments[shard] = self._new_fragment(shard)

    def close(self) -> None:
        for f in self.fragments.values():
            f.close()  # no-op for never-opened fragments

    def _fragment_path(self, shard: int) -> Optional[str]:
        if not self.path:
            return None
        return os.path.join(self.path, "fragments", str(shard))

    def _new_fragment(self, shard: int) -> Fragment:
        return Fragment(
            self._fragment_path(shard),
            self.index,
            self.field,
            self.name,
            shard,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
            row_attr_store=self.row_attr_store,
        )

    def fragment(self, shard: int) -> Optional[Fragment]:
        frag = self.fragments.get(shard)
        return frag.ensure_open() if frag is not None else None

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        with self.mu:
            frag = self.fragments.get(shard)
            if frag is None:
                if self.path:
                    os.makedirs(os.path.join(self.path, "fragments"), exist_ok=True)
                prev_max = max(self.fragments) if self.fragments else -1
                frag = self._new_fragment(shard)
                frag.open()
                self.fragments[shard] = frag
                if shard > prev_max and self.broadcaster:
                    self.broadcaster(self.index, shard)
        # open() discovery registers fragments UNOPENED (lazy startup);
        # mutating one before its first open would hit the empty
        # placeholder Bitmap — with no op-log attached — and the first
        # ensure_open() would then replace storage with the mmapped
        # file, silently discarding (acked!) writes. Open outside the
        # view lock: fragment opens are slow (mmap + recovery scan) and
        # ensure_open is a flag check once open.
        return frag.ensure_open()

    def available_shards(self) -> list[int]:
        return sorted(self.fragments)

    # -- routed ops (reference view.go:289-330) --

    def row(self, row_id: int) -> Row:
        out = Row()
        for shard in sorted(self.fragments):
            out.merge(self.fragments[shard].ensure_open().row(row_id))
        return out

    def set_bit(self, row_id: int, column_id: int) -> bool:
        shard = column_id // SHARD_WIDTH
        return self.create_fragment_if_not_exists(shard).set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.fragment(column_id // SHARD_WIDTH)
        if frag is None:
            return False
        return frag.clear_bit(row_id, column_id)

    def value(self, column_id: int, bit_depth: int) -> tuple[int, bool]:
        frag = self.fragment(column_id // SHARD_WIDTH)
        if frag is None:
            return 0, False
        return frag.value(column_id, bit_depth)

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        shard = column_id // SHARD_WIDTH
        return self.create_fragment_if_not_exists(shard).set_value(
            column_id, bit_depth, value
        )
