"""(row, column) pair iterators (reference iterator.go:24-194).

The reference threads these through its block-merge and import paths;
our equivalents of those paths are vectorized (set/ndarray based, see
parallel/cluster.py sync and core/fragment.py bulk import), so these
classes exist as the public streaming surface over pair data — parity
with the reference's iterator API for callers that consume fragments
pair-at-a-time without materializing full position arrays.

Iterator protocol: ``seek(row_id, col_id)`` positions at the first pair
>= (row_id, col_id) in (row, col) lexicographic order; ``next_pair()``
returns ``(row_id, col_id, eof)`` with ``eof=True`` once exhausted.
"""

from __future__ import annotations

from typing import Optional

from pilosa_tpu import SHARD_WIDTH


class SliceIterator:
    """Iterate over parallel row/column id lists (reference
    sliceIterator, iterator.go:86-124). Input must already be sorted by
    (row, col)."""

    def __init__(self, row_ids, column_ids) -> None:
        if len(row_ids) != len(column_ids):
            raise ValueError("row/column slice length mismatch")
        self.row_ids = row_ids
        self.column_ids = column_ids
        self.i = 0

    def seek(self, row_id: int, col_id: int) -> None:
        lo, hi = 0, len(self.row_ids)
        while lo < hi:
            mid = (lo + hi) // 2
            pair = (self.row_ids[mid], self.column_ids[mid])
            if pair < (row_id, col_id):
                lo = mid + 1
            else:
                hi = mid
        self.i = lo

    def next_pair(self):
        if self.i >= len(self.row_ids):
            return 0, 0, True
        r, c = self.row_ids[self.i], self.column_ids[self.i]
        self.i += 1
        return int(r), int(c), False

    def __iter__(self):
        while True:
            r, c, eof = self.next_pair()
            if eof:
                return
            yield r, c


class LimitIterator:
    """Cap an iterator at n pairs (reference limitIterator,
    iterator.go:126-151)."""

    def __init__(self, itr, limit: int) -> None:
        self.itr = itr
        self.limit = limit
        self.n = 0

    def seek(self, row_id: int, col_id: int) -> None:
        self.itr.seek(row_id, col_id)

    def next_pair(self):
        if self.n >= self.limit:
            return 0, 0, True
        r, c, eof = self.itr.next_pair()
        if not eof:
            self.n += 1
        return r, c, eof

    def __iter__(self):
        while True:
            r, c, eof = self.next_pair()
            if eof:
                return
            yield r, c


class BufIterator:
    """Single-slot pushback wrapper (reference bufIterator,
    iterator.go:29-84): ``unread()`` pushes the last pair back so the
    next ``next_pair()`` re-returns it; ``peek()`` is next+unread."""

    def __init__(self, itr) -> None:
        self.itr = itr
        self._buf: Optional[tuple] = None
        self._full = False

    def seek(self, row_id: int, col_id: int) -> None:
        self._full = False
        self.itr.seek(row_id, col_id)

    def next_pair(self):
        if self._full:
            self._full = False
            return self._buf
        self._buf = self.itr.next_pair()
        return self._buf

    def peek(self):
        out = self.next_pair()
        self.unread()
        return out

    def unread(self) -> None:
        if self._full:
            raise RuntimeError("BufIterator: buffer full")
        self._full = True

    def __iter__(self):
        while True:
            r, c, eof = self.next_pair()
            if eof:
                return
            yield r, c


class RoaringIterator:
    """Iterate a fragment-layout roaring bitmap as (row, col) pairs
    (reference roaringIterator, iterator.go:153-194): position
    ``pos = row * SHARD_WIDTH + col`` (fragment.go:1935)."""

    def __init__(self, bitmap) -> None:
        # Materialized positions stay sorted, giving (row, col) order
        # for free; fragments cap rows so this is block-merge sized.
        self._pos = bitmap.slice_all()
        self.i = 0

    def seek(self, row_id: int, col_id: int) -> None:
        import numpy as np

        target = row_id * SHARD_WIDTH + col_id
        self.i = int(np.searchsorted(self._pos, target, side="left"))

    def next_pair(self):
        if self.i >= len(self._pos):
            return 0, 0, True
        v = int(self._pos[self.i])
        self.i += 1
        return v // SHARD_WIDTH, v % SHARD_WIDTH, False

    def __iter__(self):
        while True:
            r, c, eof = self.next_pair()
            if eof:
                return
            yield r, c
