"""Holder — root container of indexes (reference holder.go)."""

from __future__ import annotations

import os
import shutil
import threading
import uuid
from typing import Optional

from pilosa_tpu.core.index import Index, _validate_name


class Holder:
    def __init__(self, path: Optional[str] = None, broadcaster=None, new_attr_store=None) -> None:
        self.path = path
        self.broadcaster = broadcaster
        self.new_attr_store = new_attr_store
        self.indexes: dict[str, Index] = {}
        self.mu = threading.RLock()
        self.opened = False

    # -- lifecycle (reference Open:93-149) --

    def open(self) -> None:
        with self.mu:
            if self.path:
                os.makedirs(self.path, exist_ok=True)
                for name in sorted(os.listdir(self.path)):
                    ipath = os.path.join(self.path, name)
                    if not os.path.isdir(ipath) or name.startswith("."):
                        continue
                    idx = self._new_index(name)
                    idx.open()
                    self.indexes[name] = idx
            self.opened = True

    def close(self) -> None:
        with self.mu:
            for idx in self.indexes.values():
                idx.close()
            self.opened = False

    def has_data(self) -> bool:
        return bool(self.indexes)

    # -- node id persistence (reference loadNodeID:518) --

    def load_node_id(self) -> str:
        if not self.path:
            return uuid.uuid4().hex[:16]
        os.makedirs(self.path, exist_ok=True)
        id_path = os.path.join(self.path, ".id")
        try:
            with open(id_path) as f:
                node_id = f.read().strip()
                if node_id:
                    return node_id
        except FileNotFoundError:
            pass
        node_id = uuid.uuid4().hex[:16]
        with open(id_path, "w") as f:
            f.write(node_id)
        return node_id

    # -- indexes --

    def _new_index(self, name: str) -> Index:
        column_attrs = None
        if self.new_attr_store is not None:
            p = os.path.join(self.path, name, ".data") if self.path else None
            column_attrs = self.new_attr_store(p)
        return Index(
            os.path.join(self.path, name) if self.path else None,
            name,
            column_attr_store=column_attrs,
            broadcaster=self.broadcaster,
            new_attr_store=self.new_attr_store,
        )

    def index(self, name: str) -> Optional[Index]:
        return self.indexes.get(name)

    def create_index(self, name: str, keys: bool = False) -> Index:
        with self.mu:
            if name in self.indexes:
                raise ValueError(f"index already exists: {name}")
            return self._create_index(name, keys)

    def create_index_if_not_exists(self, name: str, keys: bool = False) -> Index:
        with self.mu:
            idx = self.indexes.get(name)
            if idx is not None:
                return idx
            return self._create_index(name, keys)

    def _create_index(self, name: str, keys: bool) -> Index:
        _validate_name(name)
        idx = self._new_index(name)
        idx.keys = keys
        idx.open()
        idx.save_meta()
        self.indexes[name] = idx
        return idx

    def delete_index(self, name: str) -> None:
        with self.mu:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise ValueError(f"index not found: {name}")
            idx.close()
            if idx.path and os.path.isdir(idx.path):
                shutil.rmtree(idx.path)

    # -- convenience lookups (reference holder.go fragment accessors) --

    def field(self, index: str, field: str):
        idx = self.index(index)
        return idx.field(field) if idx else None

    def view(self, index: str, field: str, view: str):
        f = self.field(index, field)
        return f.view(view) if f else None

    def fragment(self, index: str, field: str, view: str, shard: int):
        v = self.view(index, field, view)
        return v.fragment(shard) if v else None

    # -- schema sync (reference Schema:213 / applySchema:233) --

    def schema(self) -> list[dict]:
        out = []
        for iname in sorted(self.indexes):
            idx = self.indexes[iname]
            fields = []
            for fname in sorted(idx.fields):
                f = idx.fields[fname]
                fields.append(
                    {
                        "name": fname,
                        "options": f.options.to_dict(),
                        "views": sorted(f.views),
                    }
                )
            out.append({"name": iname, "keys": idx.keys, "fields": fields})
        return out

    def apply_schema(self, schema: list[dict]) -> None:
        """Merge a remote schema (create anything missing)."""
        from pilosa_tpu.core.field import FieldOptions

        with self.mu:
            for ischema in schema:
                idx = self.create_index_if_not_exists(
                    ischema["name"], ischema.get("keys", False)
                )
                for fschema in ischema.get("fields", []):
                    field = idx.create_field_if_not_exists(
                        fschema["name"],
                        FieldOptions.from_dict(fschema.get("options", {})),
                    )
                    for vname in fschema.get("views", []):
                        field.create_view_if_not_exists(vname)
