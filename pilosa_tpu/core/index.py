"""Index — container of fields + column attributes (reference index.go)."""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from pilosa_tpu.core.field import Field, FieldOptions


class Index:
    def __init__(
        self,
        path: Optional[str],
        name: str,
        keys: bool = False,
        column_attr_store=None,
        broadcaster=None,
        new_attr_store=None,
    ) -> None:
        self.path = path
        self.name = name
        self.keys = keys
        self.column_attrs = column_attr_store
        self.broadcaster = broadcaster
        self.new_attr_store = new_attr_store  # factory: path -> attr store
        self.fields: dict[str, Field] = {}
        self.remote_max_shard = 0  # reference index.go:214-237
        self.mu = threading.RLock()

    # -- lifecycle --

    def open(self) -> None:
        if self.path:
            os.makedirs(self.path, exist_ok=True)
            self._load_meta()
            for name in sorted(os.listdir(self.path)):
                fpath = os.path.join(self.path, name)
                if not os.path.isdir(fpath) or name.startswith("."):
                    continue
                f = self._new_field(name)
                f.open()
                self.fields[name] = f

    def close(self) -> None:
        for f in self.fields.values():
            f.close()

    def _meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def save_meta(self) -> None:
        if not self.path:
            return
        with open(self._meta_path(), "w") as f:
            json.dump({"keys": self.keys}, f)

    def _load_meta(self) -> None:
        try:
            with open(self._meta_path(), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            self.save_meta()
            return
        try:
            self.keys = json.loads(raw).get("keys", False)
        except (ValueError, UnicodeDecodeError):
            # reference data dir: .meta is a protobuf IndexMeta
            from pilosa_tpu.utils.protometa import decode_index_meta

            self.keys = decode_index_meta(raw)["keys"]

    # -- fields --

    def _field_attr_store(self, name: str):
        if self.new_attr_store is None:
            return None
        if self.path:
            return self.new_attr_store(os.path.join(self.path, name, ".data"))
        return self.new_attr_store(None)

    def _new_field(self, name: str, options: Optional[FieldOptions] = None) -> Field:
        return Field(
            os.path.join(self.path, name) if self.path else None,
            self.name,
            name,
            options=options,
            row_attr_store=self._field_attr_store(name),
            broadcaster=self.broadcaster,
        )

    def field(self, name: str) -> Optional[Field]:
        return self.fields.get(name)

    def create_field(self, name: str, options: Optional[FieldOptions] = None) -> Field:
        with self.mu:
            if name in self.fields:
                raise ValueError(f"field already exists: {name}")
            return self._create_field_if_not_exists(name, options)

    def create_field_if_not_exists(
        self, name: str, options: Optional[FieldOptions] = None
    ) -> Field:
        with self.mu:
            f = self.fields.get(name)
            if f is not None:
                return f
            return self._create_field_if_not_exists(name, options)

    def _create_field_if_not_exists(
        self, name: str, options: Optional[FieldOptions]
    ) -> Field:
        _validate_name(name)
        f = self._new_field(name, options)
        f.open()
        f.save_meta()
        self.fields[name] = f
        return f

    def delete_field(self, name: str) -> None:
        with self.mu:
            f = self.fields.pop(name, None)
            if f is None:
                raise ValueError(f"field not found: {name}")
            f.close()
            if f.path and os.path.isdir(f.path):
                import shutil

                shutil.rmtree(f.path)

    # -- shards --

    def max_shard(self) -> int:
        """Max shard across all fields, including gossip-propagated remote
        max (reference index.go:214-237)."""
        m = 0
        for f in self.fields.values():
            m = max(m, f.max_shard())
        return max(m, self.remote_max_shard)

    def set_remote_max_shard(self, n: int) -> None:
        self.remote_max_shard = max(self.remote_max_shard, n)

    def available_shards(self) -> list[int]:
        shards: set[int] = set()
        for f in self.fields.values():
            shards.update(f.available_shards())
        return sorted(shards)


def _validate_name(name: str) -> None:
    """reference validateName: lowercase alnum + dash/underscore, must
    start with a letter."""
    import re

    if not re.fullmatch(r"[a-z][a-z0-9_-]{0,63}", name):
        raise ValueError(f"invalid index or field name: {name!r}")
