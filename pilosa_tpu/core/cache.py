"""TopN row-count caches (reference cache.go).

The rank cache bounds which rows are *eligible* TopN candidates — its
threshold/trim behavior is part of the reference's observable TopN
semantics, so it is reproduced here exactly (thresholdFactor 1.1,
maxEntries trim, count-descending ranking, 10s invalidation debounce).
"""

from __future__ import annotations

import json
import time
from collections import OrderedDict
from typing import Optional

from pilosa_tpu.utils import metrics

# reference cache.go:29-31
THRESHOLD_FACTOR = 1.1
# reference field.go:38-44
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_NONE = "none"
DEFAULT_CACHE_SIZE = 50000

# reference rankCache.invalidate's hard-coded debounce (cache.go:233-241)
INVALIDATE_DEBOUNCE_SECONDS = 10.0


def sort_pairs(pairs: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Count-descending, id-ascending tiebreak.

    The reference uses Go's unstable sort with count-only comparison
    (cache.go:342); ties are therefore unspecified there — we pin them
    to ascending id for determinism.

    Vectorized for big inputs: recalculate() sorts 50k entries per
    fragment on the open path (64 fragments at the 1B scale), and a
    per-element key lambda was the single largest line in the warm-open
    profile. lexsort(ids asc, then counts desc stable) = the same
    (-count, id) order.
    """
    if len(pairs) < 1024:
        return sorted(pairs, key=lambda p: (-p[1], p[0]))
    import numpy as np

    ids = np.fromiter((p[0] for p in pairs), dtype=np.int64, count=len(pairs))
    counts = np.fromiter((p[1] for p in pairs), dtype=np.int64, count=len(pairs))
    order = np.lexsort((ids, -counts))
    return list(zip(ids[order].tolist(), counts[order].tolist()))


def pairs_arrays(pairs):
    """(ids int64[L], counts int64[L]) from a list of (id, count)."""
    import numpy as np

    ids = np.fromiter((p[0] for p in pairs), dtype=np.int64, count=len(pairs))
    cnts = np.fromiter((p[1] for p in pairs), dtype=np.int64, count=len(pairs))
    return ids, cnts


class Rankings(list):
    """Rankings snapshot (a list of (id, count) pairs) carrying its own
    memo of per-slice id tuples. The memo lives ON the snapshot — not
    on the cache — so a concurrent recalculate() swapping the cache's
    rankings can never hand a caller ids inconsistent with the pairs
    list it is iterating."""

    def chunk_ids(self, lo: int, hi: int) -> tuple[int, ...]:
        memo = getattr(self, "_memo", None)
        if memo is None:
            memo = self._memo = {}
        t = memo.get((lo, hi))
        if t is None:
            # a racing duplicate build produces an identical tuple — benign
            t = tuple(p[0] for p in self[lo:hi])
            memo[(lo, hi)] = t
        return t

    def chunk_arrays(self, lo: int, hi: int):
        """(ids int64[L], counts int64[L]) for self[lo:hi], memoized on
        the snapshot (same rationale as chunk_ids): the vectorized
        cross-shard TopN walk consumes candidate ids/counts as numpy
        arrays per shard per chunk on every query."""
        memo = getattr(self, "_np_memo", None)
        if memo is None:
            memo = self._np_memo = {}
        t = memo.get((lo, hi))
        if t is None:
            t = memo[(lo, hi)] = pairs_arrays(self[lo:hi])
        return t


class RankCache:
    """Sorted top-K cache (reference rankCache, cache.go:136-286)."""

    def __init__(self, max_entries: int) -> None:
        self.max_entries = max_entries
        self.threshold_buffer = int(THRESHOLD_FACTOR * max_entries)
        self.entries: dict[int, int] = {}
        self.rankings: list[tuple[int, int]] = Rankings()
        self.threshold_value = 0
        self._update_time = 0.0
        self._dirty = False

    def add(self, id_: int, n: int) -> None:
        if n < self.threshold_value:
            return
        self.entries[id_] = n
        self._dirty = True
        self.invalidate()

    def bulk_add(self, id_: int, n: int) -> None:
        if n < self.threshold_value:
            return
        self.entries[id_] = n
        self._dirty = True

    def get(self, id_: int) -> int:
        n = self.entries.get(id_)
        if n is None:
            metrics.count(metrics.CACHE_MISSES)
            return 0
        metrics.count(metrics.CACHE_HITS)
        return n

    def remove(self, id_: int) -> None:
        if self.entries.pop(id_, None) is not None:
            self.rankings = Rankings(p for p in self.rankings if p[0] != id_)
            self._dirty = True

    def __len__(self) -> int:
        return len(self.entries)

    def ids(self) -> list[int]:
        return sorted(self.entries)

    def restore(self, ids, counts) -> None:
        """Bulk-load (id, count) pairs at open — C-speed dict build +
        one recalculate instead of 50k bulk_add calls (the open path
        at 64 fragments × 50k cached rows)."""
        ids = ids.tolist() if hasattr(ids, "tolist") else ids
        counts = counts.tolist() if hasattr(counts, "tolist") else counts
        self.entries.update(zip(map(int, ids), map(int, counts)))
        self.recalculate()

    def invalidate(self) -> None:
        # the reference recalculates whenever the debounce window has
        # passed (cache.go:233-241) even if nothing changed; on an
        # unmodified cache the re-sort is a semantic no-op, and on the
        # read path (topBitmapPairs) it cost ~34 ms of GIL per 50k-entry
        # fragment — measured as the dominant serialization at c8 on the
        # 1B/64-shard config. Skipping it when clean is bit-identical.
        if not self._dirty:
            return
        if time.monotonic() - self._update_time < INVALIDATE_DEBOUNCE_SECONDS:
            return
        self.recalculate()

    def recalculate(self) -> None:
        self._dirty = False
        rankings = sort_pairs(list(self.entries.items()))
        remove_items: list[tuple[int, int]] = []
        if len(rankings) > self.max_entries:
            self.threshold_value = rankings[self.max_entries][1]
            remove_items = rankings[self.max_entries :]
            rankings = rankings[: self.max_entries]
        else:
            self.threshold_value = 1
        self.rankings = Rankings(rankings)
        self._update_time = time.monotonic()
        if len(self.entries) > self.threshold_buffer:
            for id_, _ in remove_items:
                self.entries.pop(id_, None)

    def top(self) -> list[tuple[int, int]]:
        return self.rankings

    def clear(self) -> None:
        self.entries.clear()
        self.rankings = Rankings()
        self.threshold_value = 0
        self._update_time = 0.0
        self._dirty = False


class LRUCache:
    """LRU row-count cache (reference lruCache over lru/lru.go)."""

    def __init__(self, max_entries: int) -> None:
        self.max_entries = max_entries
        self._lru: OrderedDict[int, int] = OrderedDict()

    def add(self, id_: int, n: int) -> None:
        if id_ in self._lru:
            self._lru.move_to_end(id_)
        self._lru[id_] = n
        if self.max_entries and len(self._lru) > self.max_entries:
            self._lru.popitem(last=False)

    bulk_add = add

    def restore(self, ids, counts) -> None:
        for i, c in zip(ids, counts):
            self.add(int(i), int(c))

    def get(self, id_: int) -> int:
        n = self._lru.get(id_)
        if n is None:
            metrics.count(metrics.CACHE_MISSES)
            return 0
        self._lru.move_to_end(id_)
        metrics.count(metrics.CACHE_HITS)
        return n

    def remove(self, id_: int) -> None:
        self._lru.pop(id_, None)

    def __len__(self) -> int:
        return len(self._lru)

    def ids(self) -> list[int]:
        return sorted(self._lru)

    def invalidate(self) -> None:
        pass

    def recalculate(self) -> None:
        pass

    def top(self) -> list[tuple[int, int]]:
        return sort_pairs(list(self._lru.items()))

    def clear(self) -> None:
        self._lru.clear()


class NopCache:
    """No-op cache (cache type \"none\")."""

    def add(self, id_: int, n: int) -> None:
        pass

    bulk_add = add

    def restore(self, ids, counts) -> None:
        pass

    def get(self, id_: int) -> int:
        return 0

    def remove(self, id_: int) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def ids(self) -> list[int]:
        return []

    def invalidate(self) -> None:
        pass

    def recalculate(self) -> None:
        pass

    def top(self) -> list[tuple[int, int]]:
        return []

    def clear(self) -> None:
        pass


def new_cache(cache_type: str, cache_size: int):
    if cache_type == CACHE_TYPE_RANKED:
        return RankCache(cache_size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(cache_size)
    if cache_type == CACHE_TYPE_NONE:
        return NopCache()
    raise ValueError(f"unknown cache type: {cache_type}")


def encode_cache(ids: list[int]) -> bytes:
    """The reference's .cache protobuf bytes
    (internal/private.proto Cache{repeated uint64 IDs = 1}, packed)."""
    from pilosa_tpu.utils.protometa import _write_tag, _write_varint

    out = bytearray()
    if ids:
        buf = bytearray()
        for v in ids:
            _write_varint(buf, int(v))
        _write_tag(out, 1, 2)
        _write_varint(out, len(buf))
        out += buf
    return bytes(out)


def write_cache(path: str, ids: list[int]) -> None:
    # write-then-rename: a crash mid-flush must never leave a truncated
    # .cache that chokes the next startup (the periodic flush loop
    # exists precisely to survive crashes)
    import os

    tmp = path + ".flushing"
    with open(tmp, "wb") as f:
        f.write(encode_cache(ids))
    os.replace(tmp, path)


def read_cache(path: str) -> Optional[list[int]]:
    try:
        with open(path, "rb") as f:
            return decode_cache(f.read())
    except FileNotFoundError:
        return None


def _decode_packed_varints(payload: bytes) -> list[int]:
    """Vectorized decode of concatenated uvarints: one masked
    shift-or round per varint BYTE POSITION (≤10) instead of a Python
    loop per byte — the .cache open path decodes 50k ids in ~1 ms."""
    import numpy as np

    b = np.frombuffer(payload, dtype=np.uint8)
    if b.size == 0:
        return []
    ends = np.nonzero((b & 0x80) == 0)[0]
    if ends.size == 0 or ends[-1] != b.size - 1:
        raise ValueError("cache file: packed ids overrun field")
    starts = np.empty_like(ends)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lens = ends - starts + 1
    if int(lens.max()) > 10:
        # a u64 uvarint is at most 10 bytes; longer means corruption —
        # numpy's >=64-bit shifts would silently decode it to garbage
        # where the scalar reader raised (callers rebuild the cache)
        raise ValueError("cache file: varint too long")
    vals = np.zeros(ends.size, dtype=np.uint64)
    for j in range(int(lens.max())):
        take = lens > j
        byte = b[starts[take] + j].astype(np.uint64) & np.uint64(0x7F)
        vals[take] |= byte << np.uint64(7 * j)
    return vals.tolist()


def decode_cache(data: bytes) -> list[int]:
    """Decode .cache bytes: reference protobuf, or the JSON this
    framework wrote before adopting the reference format."""
    from pilosa_tpu.utils.protometa import _read_varint

    if not data:
        return []
    if data[:1] == b"[":  # legacy JSON
        return json.loads(data.decode())
    ids: list[int] = []
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field_no, wire = key >> 3, key & 7
        if wire == 2:
            ln, i = _read_varint(data, i)
            end = i + ln
            if field_no == 1:
                ids.extend(_decode_packed_varints(data[i:end]))
            i = end  # skip unknown length-delimited fields
        elif wire == 0:
            v, i = _read_varint(data, i)
            if field_no == 1:
                ids.append(v)
        else:
            raise ValueError(f"unsupported wire type in cache file: {wire}")
    return ids
