"""Storage tree (L1/L2): holder → index → field → view → fragment; Row."""

from pilosa_tpu.core.fragment import Fragment, TopOptions, pos
from pilosa_tpu.core.field import BSIGroup, Field, FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.iterator import (
    BufIterator,
    LimitIterator,
    RoaringIterator,
    SliceIterator,
)
from pilosa_tpu.core.row import Row, union_rows
from pilosa_tpu.core.view import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD, View

__all__ = [
    "BSIGroup",
    "BufIterator",
    "Field",
    "FieldOptions",
    "Fragment",
    "Holder",
    "Index",
    "LimitIterator",
    "RoaringIterator",
    "Row",
    "SliceIterator",
    "TopOptions",
    "VIEW_BSI_GROUP_PREFIX",
    "VIEW_STANDARD",
    "View",
    "pos",
    "union_rows",
]
