"""Fragment — one shard of one field-view (L1).

Mirrors the reference's fragment (reference fragment.go): a bitmap over
positions ``pos = rowID * 2^20 + (columnID % 2^20)`` backed by one
roaring file whose tail doubles as an append-only op log, snapshotted
once the op count passes MAX_OP_N (reference fragment.go:62-64,
1399-1468). Row materialisation is a container-level OffsetRange + clone
(reference fragment.go:330-359).

TPU integration: the fragment is the CPU source of truth; it exports
packed-word row matrices / BSI plane stacks for HBM staging, keeps a
``generation`` counter, and logs single-bit mutations in a bounded
device-delta log so the stager can patch staged blocks forward
(scatter-update kernels, ops/delta.py) instead of invalidating them on
every write (SURVEY.md §7 step 3; the device-side analog of the
reference's op log over the mmapped roaring file).
"""

from __future__ import annotations

import hashlib
import heapq
import math
import mmap
import os
import threading
import time
from collections import deque
from typing import Iterable, Optional

import numpy as np

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.roaring import Bitmap
from pilosa_tpu.roaring import bitmap as bitmap_mod
from pilosa_tpu.core.row import Row
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.utils import events, metrics

# reference fragment.go:55-64
HASH_BLOCK_SIZE = 100
MAX_OP_N = 2000

# Bound on the per-fragment device-delta log (entries, i.e. single-bit
# mutations since the oldest replayable snapshot). The log is what lets
# the HBM stager patch already-resident arrays instead of re-uploading
# whole blocks on every write (executor/stager.py); once a staged
# snapshot falls more than this many mutations behind, the stager full-
# rebuilds anyway, so keeping more buys nothing. Overridable per process
# via the `stager-delta-log-max` config knob (server/server.py sets the
# class attribute).
DELTA_LOG_MAX = 4096

# Bulk imports at or under this many positions route through the
# batched delta path (one OP_BATCH group-commit append + one device
# scatter) instead of the merge+snapshot path that `_delta_reset()`s
# and forces staged blocks to full-rebuild — the bulk-import cliff.
# Overridable per process via the `ingest-delta-max-batch` config knob
# (server/server.py sets the module attribute).
DELTA_MAX_BATCH = 512

DEFAULT_MIN_THRESHOLD = 1  # reference executor.go defaultMinThreshold


# -- storage fault injection (tests/dryruns only) ----------------------------

STORAGE_FAULTS_ENV = "PILOSA_TPU_STORAGE_FAULTS"


class StorageFaultSpec:
    """Deterministic fault schedule for the fragment op-log write path,
    parsed from the ``storage-faults`` config knob (or
    ``PILOSA_TPU_STORAGE_FAULTS``): ``fsync_fail_every=N`` raises EIO
    on every Nth fsync (the record reached the page cache but
    durability is unproven), ``torn_at=N`` tears the first append that
    would push the cumulative appended byte count past N — only a
    prefix reaches the file, then EIO (a partial sector landing before
    power loss), ``enospc_after=K`` fails every append after the first
    K with ENOSPC, writing nothing. No RNG — crash-recovery tests
    reproduce exactly. Injected failures journal ``ingest.fault``.

    Integrity faults (PR 15): ``corrupt_at=K`` flips one byte at file
    offset K of the next snapshot base as it is written (a latent write
    corruption the digest trailer must catch), ``bitrot=N`` flips one
    on-disk base byte right before every Nth digest verification (a
    latent sector flip under the mmap the scrubber must catch), and
    ``snapshot_kill=pre|post`` hard-kills the process (os._exit) inside
    ``snapshot()`` immediately before/after the atomic os.replace — the
    crash-atomicity property test's kill switch."""

    __slots__ = (
        "fsync_fail_every",
        "torn_at",
        "enospc_after",
        "corrupt_at",
        "bitrot",
        "snapshot_kill",
        "_fsyncs",
        "_bytes",
        "_appends",
        "_torn_done",
        "_corrupt_done",
        "_verifies",
        "_mu",
    )

    def __init__(
        self,
        fsync_fail_every: int = 0,
        torn_at: int = 0,
        enospc_after: int = 0,
        corrupt_at: int = 0,
        bitrot: int = 0,
        snapshot_kill: str = "",
    ) -> None:
        self.fsync_fail_every = fsync_fail_every
        self.torn_at = torn_at
        self.enospc_after = enospc_after
        self.corrupt_at = corrupt_at
        self.bitrot = bitrot
        self.snapshot_kill = snapshot_kill
        self._fsyncs = 0
        self._bytes = 0
        self._appends = 0
        self._torn_done = False
        self._corrupt_done = False
        self._verifies = 0
        self._mu = threading.Lock()

    @classmethod
    def parse(cls, text: str) -> "StorageFaultSpec":
        spec = cls()
        for part in (text or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, _, value = part.partition("=")
            key = key.strip()
            if key in (
                "fsync_fail_every",
                "torn_at",
                "enospc_after",
                "corrupt_at",
                "bitrot",
            ):
                setattr(spec, key, int(value))
            elif key == "snapshot_kill":
                value = value.strip()
                if value not in ("pre", "post"):
                    raise ValueError(
                        f"snapshot_kill must be 'pre' or 'post', got {value!r}"
                    )
                spec.snapshot_kill = value
            else:
                raise ValueError(f"unknown storage fault knob: {key!r}")
        return spec

    def __bool__(self) -> bool:
        return bool(
            self.fsync_fail_every
            or self.torn_at
            or self.enospc_after
            or self.corrupt_at
            or self.bitrot
            or self.snapshot_kill
        )

    def _injected(self, fault: str) -> None:
        metrics.count(metrics.INGEST_FAULTS_INJECTED, fault=fault)
        events.record(events.INGEST_FAULT, fault=fault)

    def write(self, f, rec: bytes) -> None:
        """Append ``rec`` under the fault schedule; raises OSError on an
        injected failure (a torn write lands its prefix first)."""
        with self._mu:
            self._appends += 1
            n_appends = self._appends
            start = self._bytes
            self._bytes += len(rec)
            tear = (
                self.torn_at
                and not self._torn_done
                and start < self.torn_at < start + len(rec)
            )
            if tear:
                self._torn_done = True
        if self.enospc_after and n_appends > self.enospc_after:
            self._injected("enospc")
            raise OSError(28, "No space left on device (injected)")
        if tear:
            f.write(rec[: self.torn_at - start])
            f.flush()
            os.fsync(f.fileno())  # the torn prefix really lands
            self._injected("torn_write")
            raise OSError(5, f"torn write at byte {self.torn_at} (injected)")
        f.write(rec)

    def fsync(self, fd: int) -> None:
        with self._mu:
            self._fsyncs += 1
            fail = (
                self.fsync_fail_every
                and self._fsyncs % self.fsync_fail_every == 0
            )
        if fail:
            self._injected("fsync_fail")
            raise OSError(5, "fsync failed (injected)")
        os.fsync(fd)

    def corrupt_offset(self, size: int) -> Optional[int]:
        """Byte offset to flip in the snapshot base being written (once
        per schedule), or None. Only offsets inside the base corrupt —
        the point is a flip the digest trailer must catch."""
        with self._mu:
            if not self.corrupt_at or self._corrupt_done:
                return None
            if not (0 <= self.corrupt_at < size):
                return None
            self._corrupt_done = True
        self._injected("corrupt_write")
        return self.corrupt_at

    def bitrot_due(self) -> bool:
        """True on every Nth digest verification — the caller flips one
        on-disk base byte before verifying (latent sector rot)."""
        with self._mu:
            if not self.bitrot:
                return False
            self._verifies += 1
            due = self._verifies % self.bitrot == 0
        if due:
            self._injected("bitrot")
        return due

    def kill_point(self, phase: str) -> None:
        """Hard-kill (no atexit, no flush) when the schedule names this
        snapshot phase — simulates power loss at the worst moments."""
        if self.snapshot_kill == phase:
            os._exit(137)


# Process-wide injected fault schedule (None = clean). Installed by the
# server from the `storage-faults` config knob; tests install directly.
FAULTS: Optional[StorageFaultSpec] = None


def install_storage_faults(text: str = "") -> None:
    """Parse and install the process-wide storage fault schedule; an
    empty spec (or empty text) clears it."""
    global FAULTS
    text = text or os.environ.get(STORAGE_FAULTS_ENV, "")
    spec = StorageFaultSpec.parse(text)
    FAULTS = spec if spec else None


class FragmentQuarantinedError(Exception):
    """Raised by reads/writes on a quarantined fragment: verification
    found corruption, so serving from it could return poisoned bits.
    Maps to a clean HTTP 503 + Retry-After (never a wrong answer);
    clients back off while repair pulls a healthy replica copy."""

    status = 503
    retry_after = 2

    def __init__(self, index: str, field: str, view: str, shard: int, reason: str):
        super().__init__(
            f"fragment {index}/{field}/{view}/{shard} quarantined: {reason}"
        )
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.reason = reason


def pos(row_id: int, column_id: int) -> int:
    """reference fragment.go:1935."""
    return row_id * SHARD_WIDTH + (column_id % SHARD_WIDTH)


def _sized(it):
    """Materialize one-shot iterables so np.asarray sees a sequence
    (the import signatures advertise Iterable)."""
    return it if hasattr(it, "__len__") else list(it)


class TopOptions:
    """reference topOptions (fragment.go:1046-1058)."""

    def __init__(
        self,
        n: int = 0,
        src: Optional[Row] = None,
        row_ids: Optional[list[int]] = None,
        min_threshold: int = DEFAULT_MIN_THRESHOLD,
        filter_name: str = "",
        filter_values: Optional[list] = None,
        tanimoto_threshold: int = 0,
    ) -> None:
        self.n = n
        self.src = src
        self.row_ids = row_ids or []
        self.min_threshold = min_threshold
        self.filter_name = filter_name
        self.filter_values = filter_values or []
        self.tanimoto_threshold = tanimoto_threshold


class Fragment:
    """One (index, field, view, shard) bitmap fragment."""

    def __init__(
        self,
        path: Optional[str],
        index: str,
        field: str,
        view: str,
        shard: int,
        cache_type: str = cache_mod.CACHE_TYPE_RANKED,
        cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
        row_attr_store=None,
    ) -> None:
        self.path = path
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.cache_type = cache_type
        self.cache = cache_mod.new_cache(cache_type, cache_size)
        self.row_attr_store = row_attr_store

        self.storage = Bitmap()
        self.op_n = 0
        self.max_op_n = MAX_OP_N
        self.max_row_id = 0
        self.generation = 0  # bumped on every mutation; device-stager key
        # Device-delta log: (generation, pos, is_set) per single-bit
        # mutation, so the HBM stager can replay writes onto staged
        # arrays instead of rebuilding them (snapshot + delta model).
        # _delta_floor: staged snapshots at/after this generation can be
        # patched forward. _delta_synced: the generation the log is
        # authoritative through — any generation bump that bypasses
        # _delta_append/_delta_reset (e.g. a raw restore assigning
        # .generation) desyncs it and deltas_since answers None until
        # the next tracked mutation re-anchors the log.
        self.delta_log_max = DELTA_LOG_MAX
        self.delta_max_batch = DELTA_MAX_BATCH
        self._delta_log: deque[tuple[int, int, bool]] = deque()
        self._delta_floor = 0
        self._delta_synced = 0
        self.checksums: dict[int, bytes] = {}
        self.mu = threading.RLock()
        self._row_cache: dict[int, Row] = {}
        self._op_file = None
        # set when a failed append could not be repaired in-place: the
        # tail is in an unknown state, so appends are refused until
        # snapshot() rebuilds the file (fsyncgate-style containment)
        self._op_log_dirty = False
        self._open = False
        # occupancy index cache keyed by generation (mmap stores cache
        # internally; dict stores would otherwise rebuild O(N log N)
        # per query in the auto-policy estimate)
        self._occ: Optional[tuple] = None
        # integrity quarantine: set when verification found corruption.
        # Reads/writes raise FragmentQuarantinedError (503) until repair
        # replaces the data; the generation bump at quarantine time
        # fences plan/device caches off the poisoned content.
        self.quarantined = False
        self.quarantine_reason = ""

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> None:
        with self.mu:
            if self._open:
                return
            if self.path and os.path.exists(self.path):
                self._load_storage()
            if self.path and not os.path.exists(self.path):
                # Initialise new files with an empty snapshot header so the
                # trailing op log always follows a valid roaring prefix
                # (reference openStorage, fragment.go:167-224).
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                with open(self.path, "wb") as f:
                    self.storage.write_to(f)
            if self.path:
                self._op_file = open(self.path, "ab")
                self.storage.op_writer = self._op_file
            self._recompute_max_row_id()
            self._open_cache()
            self._open = True

    def ensure_open(self) -> "Fragment":
        """Open on first touch (lazy holder trees open fragments in
        O(touched), matching the reference's mmap-cheap startup)."""
        if not self._open:
            self.open()
        return self

    def _load_storage(self) -> None:
        """Mmap the roaring file and parse lazily: headers become numpy
        views over the map, payloads decode on demand, the op-log tail
        replays into the overlay (reference openStorage,
        fragment.go:167-224). The mmap stays alive for as long as the
        storage references it (numpy buffer export); no explicit close.

        Crash recovery runs FIRST: a torn op-log tail (a record cut by
        SIGKILL or a torn sector write) is truncated to the last fully
        valid record before the map is created, so every acknowledged
        (fsynced) write replays and un-acked partials vanish instead of
        failing the open."""
        if os.path.getsize(self.path) == 0:
            return
        try:
            self._recover_storage_tail()
        except Exception:
            # a rotted header/meta region can make even the recovery
            # scan unparseable — that is corruption, not a crash
            self._set_quarantined("snapshot header unparseable at open")
            return
        if os.path.getsize(self.path) == 0:
            return
        if not self._verify_snapshot_digest():
            # Never parse (let alone serve) a base that fails its
            # digest: leave storage empty and quarantine — reads 503
            # until repair pulls a healthy replica copy.
            self._set_quarantined("snapshot digest mismatch at open")
            return
        self.storage = Bitmap.open_mmap_file(self.path)
        self.op_n = self.storage.op_n

    def _recover_storage_tail(self) -> None:
        """Validate the length-framed, checksummed op-log tail and
        truncate anything past the last intact record. The snapshot
        prefix is written atomically (tmp + fsync + rename), so only
        the append-only tail can tear; a file too short to hold even
        the snapshot header can hold no acknowledged op and resets to
        empty. The scan maps the file read-only and closes the map
        before truncating — no live views reference it."""
        size = os.path.getsize(self.path)
        if size < bitmap_mod.HEADER_BASE_SIZE:
            valid_end, n_ops = 0, 0
        else:
            with open(self.path, "rb") as f:
                mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                try:
                    ops_off = bitmap_mod.ops_offset_of(mm)
                    valid_end, n_ops = bitmap_mod.scan_op_log(mm, ops_off)
                finally:
                    mm.close()
        if valid_end >= size:
            return
        truncated = size - valid_end
        os.truncate(self.path, valid_end)
        metrics.count(metrics.INGEST_RECOVERY_REPLAYS)
        metrics.count(metrics.INGEST_RECOVERY_TRUNCATED_BYTES, truncated)
        events.record(
            events.INGEST_RECOVERY,
            index=self.index,
            field=self.field,
            shard=self.shard,
            truncated_bytes=truncated,
            replayed_ops=n_ops,
        )

    # -- integrity: digest verification + quarantine (PR 15) -----------------

    def check_serving(self) -> None:
        """Raise when verification has found corruption: a quarantined
        fragment must never serve (or accept) bits — a clean 503 beats
        a silent wrong answer."""
        if self.quarantined:
            raise FragmentQuarantinedError(
                self.index,
                self.field,
                self.view,
                self.shard,
                self.quarantine_reason,
            )

    def _set_quarantined(self, reason: str) -> None:
        """Mark corrupt (caller holds mu, or is inside open()). The
        generation bump fences plan/device caches off the poisoned
        content: it bypasses the delta log, so staged snapshots can
        never patch forward from it."""
        if self.quarantined:
            return
        self.quarantined = True
        self.quarantine_reason = reason
        self.generation += 1
        self._row_cache.clear()
        self.checksums.clear()
        self._occ = None
        metrics.count(metrics.SCRUB_QUARANTINED)
        events.record(
            events.SCRUB_QUARANTINE,
            index=self.index,
            field=self.field,
            view=self.view,
            shard=self.shard,
            reason=reason,
        )

    def quarantine(self, reason: str) -> None:
        with self.mu:
            self._set_quarantined(reason)

    def clear_quarantine(self) -> None:
        """Lift the quarantine after repair replaced the data (the
        repair path bumps generation + delta_reset itself)."""
        with self.mu:
            self.quarantined = False
            self.quarantine_reason = ""

    def _verify_snapshot_digest(self) -> bool:
        """True when the on-disk snapshot base matches its digest
        trailer — or the file predates the checksummed format (no
        trailer). Re-reads the file rather than trusting a live mmap,
        so rot under the map is seen. The ``bitrot`` storage fault
        injects here: one base byte flips on disk before the check."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        if len(data) < bitmap_mod.HEADER_BASE_SIZE:
            return True  # recovery resets short files to empty
        try:
            end = bitmap_mod.snapshot_base_end(data)
        except Exception:
            return False  # unparseable header/metas: corrupt
        if not bitmap_mod.has_digest_trailer(data, end):
            return True  # legacy file: nothing to verify against
        spec = FAULTS
        if spec is not None and spec.bitrot_due():
            self._flip_disk_byte(max(0, end - 1))
            with open(self.path, "rb") as f:
                data = f.read()
        return bitmap_mod.verify_digest_trailer(data, end)

    def _flip_disk_byte(self, off: int) -> None:
        """Flip one byte of the on-disk file in place (bitrot fault).
        Goes through the page cache, so live mmaps see it — exactly
        the silent-corruption-under-the-map failure mode."""
        with open(self.path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            if not b:
                return
            f.seek(off)
            f.write(bytes([b[0] ^ 0x01]))
            f.flush()
            os.fsync(f.fileno())

    def verify_integrity(self, deep: bool = False) -> Optional[str]:
        """Scrub this fragment; returns a reason string when corruption
        was found (the fragment is quarantined first) or None when
        clean. Checks, cheapest first: (1) snapshot digest trailer vs a
        fresh re-read of the base bytes, (2) op-log tail CRC walk, (3)
        ``deep``: re-parse the file and compare block checksums against
        the live in-memory storage (catches rot under the mmap that
        landed after open). Holds mu throughout so no reader can race
        a flip-then-verify window and serve poisoned bits."""
        if not self.path:
            return None
        with self.mu:
            if self.quarantined:
                return self.quarantine_reason
            if not os.path.exists(self.path):
                return None
            if self._op_file:
                # the scan below reads the file: flush buffered appends
                # so a half-buffered record isn't mistaken for a tear
                try:
                    self._op_file.flush()
                except OSError:
                    pass
            if not self._verify_snapshot_digest():
                self._set_quarantined("snapshot digest mismatch")
                return self.quarantine_reason
            try:
                with open(self.path, "rb") as f:
                    data = f.read()
                ops_off = bitmap_mod.ops_offset_of(data)
                valid_end, _ = bitmap_mod.scan_op_log(data, ops_off)
            except Exception:
                self._set_quarantined("op log unreadable")
                return self.quarantine_reason
            if valid_end < len(data):
                self._set_quarantined(
                    f"op log CRC mismatch at byte {valid_end}"
                )
                return self.quarantine_reason
            if deep and self.storage.is_mmap_backed():
                try:
                    fresh = Bitmap.unmarshal_binary(data)
                except Exception:
                    self._set_quarantined("snapshot base unparseable")
                    return self.quarantine_reason
                if self._blocks_of(fresh) != self.blocks():
                    self._set_quarantined(
                        "on-disk blocks diverge from memory"
                    )
                    return self.quarantine_reason
            return None

    def close(self) -> None:
        with self.mu:
            if self._op_file:
                self.flush_cache()
                self._op_file.close()
                self._op_file = None
                self.storage.op_writer = None
            self._open = False

    def _recompute_max_row_id(self) -> None:
        k = self.storage.max_key()
        self.max_row_id = (k << 16) // SHARD_WIDTH if k is not None else 0

    def cache_path(self) -> Optional[str]:
        return self.path + ".cache" if self.path else None

    def _open_cache(self) -> None:
        """Restore cached row ids with a recount (reference openCache,
        fragment.go:227-266). The recount is a vectorised pass over the
        container occupancy index — no row materialisation."""
        p = self.cache_path()
        if not p or self.quarantined:
            return  # cache rebuilds after repair
        ids = cache_mod.read_cache(p)
        if not ids:
            return
        counts = self.row_counts_for(np.asarray(ids, dtype=np.uint64))
        # restore() recalculates UNCONDITIONALLY: a debounced
        # invalidate() can be silently skipped when something touched
        # this cache before the lazy open (e.g. /recalculate-caches
        # sweeping unopened fragments stamps the debounce clock with
        # empty rankings) — the restore is authoritative and must
        # rebuild the rankings
        self.cache.restore(ids, counts)

    def _row_key_spans(
        self, row_ids: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """(keys, cumsum, lo, hi): each row's container-key range located
        in ONE occupancy snapshot (row r spans keys [r*16, (r+1)*16));
        callers must not mix arrays from separate snapshots — a mutation
        between calls can change the index length."""
        self.check_serving()
        occ = self._occ
        if occ is None or occ[0] != self.generation:
            # capture the generation BEFORE reading: if a writer bumps
            # it mid-read we cache under the OLD tag and refresh on the
            # next call, instead of pinning a stale snapshot to the new
            # generation
            gen = self.generation
            keys, cs = self.storage.occupancy()
            self._occ = occ = (gen, keys, cs)
        _, keys, cs = occ
        first = row_ids.astype(np.uint64) * np.uint64(SHARD_WIDTH >> 16)
        last = (row_ids.astype(np.uint64) + np.uint64(1)) * np.uint64(
            SHARD_WIDTH >> 16
        )
        if keys.dtype != np.uint64:
            # occupancy downcasts keys (with a 16-key margin) — clamp
            # out-of-range rows to the dtype max; they bisect past every
            # real key, so lo == hi and the row counts 0
            cap = np.uint64(np.iinfo(keys.dtype).max)
            first = np.minimum(first, cap)
            last = np.minimum(last, cap)
        first = first.astype(keys.dtype)
        last = last.astype(keys.dtype)
        return keys, cs, np.searchsorted(keys, first), np.searchsorted(keys, last)

    def row_counts_for(self, row_ids: np.ndarray) -> np.ndarray:
        """Per-row bit counts for many rows from container cardinalities
        alone — O(R log N) over the cached occupancy index, no payload
        decode."""
        _, cs, lo, hi = self._row_key_spans(row_ids)
        return cs[hi].astype(np.int64) - cs[lo].astype(np.int64)

    def flush_cache(self) -> None:
        p = self.cache_path()
        if p:
            # snapshot ids under the fragment lock (concurrent writers
            # mutate cache entries); write_cache itself is atomic
            with self.mu:
                ids = self.cache.ids()
            cache_mod.write_cache(p, ids)

    # -- row materialisation -------------------------------------------------

    def row(self, row_id: int) -> Row:
        with self.mu:
            return self._unprotected_row(row_id)

    def _unprotected_row(self, row_id: int, update_cache: bool = True) -> Row:
        self.check_serving()
        r = self._row_cache.get(row_id)
        if r is not None:
            return r
        data = self.storage.offset_range(
            self.shard * SHARD_WIDTH, row_id * SHARD_WIDTH, (row_id + 1) * SHARD_WIDTH
        ).clone()
        r = Row.from_segment(self.shard, data)
        if update_cache:
            self._row_cache[row_id] = r
        return r

    def row_ids(self) -> list[int]:
        """All rows with at least one bit (container key >> 4 = row id,
        since 2^20/2^16 = 16 containers per row)."""
        keys, _ = self.storage.keys_and_counts()
        return np.unique(keys >> np.uint64(4)).tolist()

    # -- bit ops -------------------------------------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        with self.mu:
            return self._unprotected_set_bit(row_id, column_id)

    def _check_pos(self, row_id: int, column_id: int) -> int:
        min_col = self.shard * SHARD_WIDTH
        if not (min_col <= column_id < min_col + SHARD_WIDTH):
            raise ValueError("column out of bounds")
        return pos(row_id, column_id)

    def _unprotected_set_bit(self, row_id: int, column_id: int) -> bool:
        self.check_serving()
        p = self._check_pos(row_id, column_id)
        if not self.storage.add(p):
            return False
        self.generation += 1
        self._delta_append(p, True)
        self.checksums.pop(row_id // HASH_BLOCK_SIZE, None)
        self._increment_op_n()
        row = self._unprotected_row(row_id)
        row.set_bit(column_id)
        self.cache.add(row_id, row.count())
        if row_id > self.max_row_id:
            self.max_row_id = row_id
        return True

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        with self.mu:
            return self._unprotected_clear_bit(row_id, column_id)

    def _unprotected_clear_bit(self, row_id: int, column_id: int) -> bool:
        self.check_serving()
        p = self._check_pos(row_id, column_id)
        if not self.storage.remove(p):
            return False
        self.generation += 1
        self._delta_append(p, False)
        self.checksums.pop(row_id // HASH_BLOCK_SIZE, None)
        self._increment_op_n()
        row = self._unprotected_row(row_id)
        row.clear_bit(column_id)
        self.cache.add(row_id, row.count())
        return True

    def bit(self, row_id: int, column_id: int) -> bool:
        self.check_serving()
        return self.storage.contains(self._check_pos(row_id, column_id))

    def _increment_op_n(self) -> None:
        self.op_n += 1
        if self.op_n > self.max_op_n:
            self.snapshot()

    # -- group-committed write waves (server/ingest.py) ----------------------

    def apply_bit_batch(self, row_ids, column_ids, is_set=None) -> int:
        """Apply many single-bit mutations as ONE durable write wave:
        every changed bit lands in a single length-framed, checksummed
        OP_BATCH append followed by ONE fsync (group commit), the
        device-delta log gains the whole wave under ONE generation bump
        (one plan-cache invalidation, one stager scatter), and each
        touched row recounts once. ``is_set`` defaults to all-True.
        Returns the number of bits that actually changed. Raises
        OSError when the append or fsync fails (real or injected) —
        the caller must NOT acknowledge the wave; the fragment is
        left unmodified, so retrying the wave is safe."""
        rows = np.asarray(_sized(row_ids), dtype=np.uint64)
        cols = np.asarray(_sized(column_ids), dtype=np.uint64)
        if is_set is None:
            sets = np.ones(rows.size, dtype=bool)
        else:
            sets = np.asarray(_sized(is_set), dtype=bool)
        if rows.size != cols.size or rows.size != sets.size:
            raise ValueError("row/column/is_set length mismatch")
        if rows.size == 0:
            return 0
        with self.mu:
            self.check_serving()
            pairs = [
                (self._check_pos(r, c), bool(s), int(r))
                for r, c, s in zip(rows.tolist(), cols.tolist(), sets.tolist())
            ]
            return self._apply_op_wave(pairs)

    def _apply_op_wave(self, pairs: list[tuple[int, bool, int]]) -> int:
        """Apply (position, is_set, row_id) mutations in arrival order
        as one group-committed wave. Called with mu held. Write-ahead
        order: the wave's changed ops are computed against the current
        bits, appended and fsynced FIRST, and only then applied in
        memory — a failed append leaves the fragment untouched, so a
        client retry of the nacked wave recomputes the identical ops
        and re-appends them. (Without this, a retry after a failed
        append would see every bit already set, log nothing, and get
        acked with nothing in the fsynced log — losing the write on
        the next crash.)"""
        ops: list[tuple[int, int]] = []
        deltas: list[tuple[int, bool]] = []
        touched: set[int] = set()
        pending: dict[int, bool] = {}  # intra-wave state (clear-then-set pairs)
        for p, s, r in pairs:
            cur = pending.get(p)
            if cur is None:
                cur = self.storage.contains(p)
            if cur == s:
                continue
            pending[p] = s
            ops.append((bitmap_mod.OP_ADD if s else bitmap_mod.OP_REMOVE, p))
            deltas.append((p, s))
            touched.add(r)
        if not ops:
            return 0
        self._append_op_batch(ops)  # raises -> nothing mutated, clean nack
        for op, p in ops:
            if op == bitmap_mod.OP_ADD:
                self.storage.add_no_oplog(p)
            else:
                self.storage.remove_no_oplog(p)
        self.generation += 1
        self._delta_extend(deltas)
        for r in touched:
            self._row_cache.pop(r, None)
            self.checksums.pop(r // HASH_BLOCK_SIZE, None)
        counts = self.row_counts_for(
            np.fromiter(touched, dtype=np.uint64, count=len(touched))
        )
        for row_id, cnt in zip(touched, counts):
            # drop first: bulk_add's threshold guard would keep a
            # stale higher count for rows the wave cleared
            self.cache.remove(row_id)
            if cnt > 0:
                self.cache.bulk_add(row_id, int(cnt))
        self.cache.invalidate()
        top = max(touched)
        if top > self.max_row_id:
            self.max_row_id = top
        self.op_n += len(ops)
        self.storage.op_n += len(ops)
        if self.op_n > self.max_op_n:
            self.snapshot()
        return len(ops)

    def _append_op_batch(self, ops: list[tuple[int, int]]) -> None:
        """One OP_BATCH append + ONE fsync for the whole wave — the
        group commit. Storage faults (if installed) inject here.

        A failed append leaves a partial or un-durable record at the
        tail; LATER appends must not land behind it (the recovery
        scan stops at the first invalid record, which would strand
        every acked wave after it). So on ANY failure — write OR
        fsync, since after a real fsync EIO the kernel may already
        have discarded the dirty pages — the log invariant is
        restored in-place: truncate back to the pre-append offset
        before re-raising the nack. If the repair itself fails the
        log is poisoned and the next wave rebuilds the whole file
        via snapshot() before it may append."""
        if self._op_log_dirty:
            # fsyncgate aftermath: a failed repair left the tail in an
            # unknown state. snapshot() rebuilds the file wholesale
            # (atomic tmp + fsync + rename) and clears the flag; if it
            # raises, this wave nacks and the log stays poisoned.
            self.snapshot()
        f = self._op_file
        if f is None:
            if self.path and self._open:
                raise OSError(5, "fragment op log unavailable")
            return
        rec = bitmap_mod.marshal_op_batch(ops)
        spec = FAULTS
        start = f.tell()
        try:
            if spec is not None:
                spec.write(f, rec)
            else:
                f.write(rec)
            f.flush()
            t0 = time.monotonic()
            if spec is not None:
                spec.fsync(f.fileno())
            else:
                os.fsync(f.fileno())
        except BaseException:
            self._repair_op_log_tail(f, start)
            raise
        metrics.observe(metrics.INGEST_FSYNC_SECONDS, time.monotonic() - t0)

    def _repair_op_log_tail(self, f, start: int) -> None:
        """Drop whatever landed past the pre-append offset after a
        failed wave append, then fsync the truncate so the repaired
        tail is itself durable. Never raises: a repair failure (or a
        flush that lost bytes BEFORE this wave's record, leaving an
        unknowable tail) poisons the log instead, so no further
        appends are admitted until snapshot() rebuilds the file."""
        try:
            try:
                f.flush()
            except OSError:
                pass  # the truncate below drops whatever couldn't land
            size = os.path.getsize(self.path)
            if size < start:
                # bytes buffered before this wave never reached the
                # file: the tail may end in a partial earlier record
                # at an offset we cannot recover from f's buffer
                self._op_log_dirty = True
                return
            if size > start:
                os.truncate(self.path, start)
                os.fsync(f.fileno())
            # resync the buffered writer: tell() must report the real
            # tail, or the NEXT failed wave would truncate to a stale
            # larger offset and extend the file with a zero gap
            f.seek(0, os.SEEK_END)
        except BaseException:
            self._op_log_dirty = True

    # -- device-delta log (snapshot + delta staging model) -------------------

    def _delta_append(self, p: int, is_set: bool) -> None:
        """Record one single-bit mutation; called with mu held, AFTER
        the generation bump it describes."""
        if self.generation != self._delta_synced + 1:
            # untracked generation bumps happened since the last logged
            # mutation (external restore, etc.) — nothing older than
            # this write is provably replayable
            self._delta_log.clear()
            self._delta_floor = self.generation - 1
        self._delta_log.append((self.generation, p, is_set))
        self._delta_synced = self.generation
        if len(self._delta_log) > self.delta_log_max:
            dropped_gen, _, _ = self._delta_log.popleft()
            self._delta_floor = dropped_gen

    def _delta_extend(self, entries: list[tuple[int, bool]]) -> None:
        """Batch form of :meth:`_delta_append`: the whole write wave
        lands under ONE generation — the plan cache invalidates once
        and the stager absorbs the wave as one coalesced scatter.
        Called with mu held, AFTER the single generation bump."""
        if self.generation != self._delta_synced + 1:
            self._delta_log.clear()
            self._delta_floor = self.generation - 1
        self._delta_synced = self.generation
        if len(entries) >= self.delta_log_max:
            # the wave alone overflows the log: snapshots staged at any
            # earlier generation full-rebuild, ones at THIS generation
            # (staged after the wave) replay nothing — both provable
            self._delta_log.clear()
            self._delta_floor = self.generation
            return
        g = self.generation
        for p, s in entries:
            self._delta_log.append((g, p, s))
        while len(self._delta_log) > self.delta_log_max:
            dropped_gen, _, _ = self._delta_log.popleft()
            self._delta_floor = dropped_gen

    def _delta_reset(self) -> None:
        """Invalidate the log after a wholesale content change (bulk
        import, block merge, restore): staged snapshots older than the
        current generation must full-rebuild. Called with mu held,
        AFTER the generation bump."""
        self._delta_log.clear()
        self._delta_floor = self._delta_synced = self.generation

    def delta_reset(self) -> None:
        """Public form for callers that replace storage outright (e.g.
        the fragment-restore API) — pairs with their generation bump."""
        with self.mu:
            self._delta_reset()

    def deltas_since(
        self, gen: int
    ) -> Optional[tuple[np.ndarray, np.ndarray, int]]:
        """Mutations between snapshot generation ``gen`` and now, as
        (positions uint64[N], is_set bool[N], current_generation) in log
        order, or None when the log cannot prove continuity (snapshot
        older than the truncation floor, an untracked generation bump,
        or a bulk rewrite since ``gen``). An empty N with a newer
        current_generation happens only after content-preserving bumps
        (snapshot()) and is a valid "nothing to replay" answer."""
        with self.mu:
            cur = self.generation
            if cur != self._delta_synced or gen < self._delta_floor or gen > cur:
                return None
            entries = [(p, s) for g, p, s in self._delta_log if g > gen]
            if not entries:
                return np.empty(0, dtype=np.uint64), np.empty(0, dtype=bool), cur
            pos = np.fromiter(
                (p for p, _ in entries), dtype=np.uint64, count=len(entries)
            )
            is_set = np.fromiter(
                (s for _, s in entries), dtype=bool, count=len(entries)
            )
            return pos, is_set, cur

    # -- BSI value ops (reference fragment.go:467-836) -----------------------

    def value(self, column_id: int, bit_depth: int) -> tuple[int, bool]:
        with self.mu:
            if not self.bit(bit_depth, column_id):
                return 0, False
            v = 0
            for i in range(bit_depth):
                if self.bit(i, column_id):
                    v |= 1 << i
            return v, True

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        with self.mu:
            changed = False
            for i in range(bit_depth):
                if (value >> i) & 1:
                    changed |= self._unprotected_set_bit(i, column_id)
                else:
                    changed |= self._unprotected_clear_bit(i, column_id)
            changed |= self._unprotected_set_bit(bit_depth, column_id)
            return changed

    def sum(self, filter_row: Optional[Row], bit_depth: int) -> tuple[int, int]:
        row = self.row(bit_depth)
        count = row.intersection_count(filter_row) if filter_row is not None else row.count()
        total = 0
        for i in range(bit_depth):
            r = self.row(i)
            cnt = r.intersection_count(filter_row) if filter_row is not None else r.count()
            total += (1 << i) * cnt
        return total, count

    def min(self, filter_row: Optional[Row], bit_depth: int) -> tuple[int, int]:
        consider = self.row(bit_depth)
        if filter_row is not None:
            consider = consider.intersect(filter_row)
        if consider.count() == 0:
            return 0, 0
        vmin = 0
        count = 0
        for ii in reversed(range(bit_depth)):
            row = self.row(ii)
            x = consider.difference(row)
            count = x.count()
            if count > 0:
                consider = x
            else:
                vmin += 1 << ii
                if ii == 0:
                    count = consider.count()
        return vmin, count

    def max(self, filter_row: Optional[Row], bit_depth: int) -> tuple[int, int]:
        consider = self.row(bit_depth)
        if filter_row is not None:
            consider = consider.intersect(filter_row)
        if consider.count() == 0:
            return 0, 0
        vmax = 0
        count = 0
        for ii in reversed(range(bit_depth)):
            row = self.row(ii)
            x = row.intersect(consider)
            count = x.count()
            if count > 0:
                vmax += 1 << ii
                consider = x
            elif ii == 0:
                count = consider.count()
        return vmax, count

    def range_op(self, op: str, bit_depth: int, predicate: int) -> Row:
        if op == "==":
            return self.range_eq(bit_depth, predicate)
        if op == "!=":
            return self.range_neq(bit_depth, predicate)
        if op in ("<", "<="):
            return self.range_lt(bit_depth, predicate, op == "<=")
        if op in (">", ">="):
            return self.range_gt(bit_depth, predicate, op == ">=")
        raise ValueError(f"invalid range operation: {op}")

    def range_eq(self, bit_depth: int, predicate: int) -> Row:
        b = self.row(bit_depth)
        for i in reversed(range(bit_depth)):
            row = self.row(i)
            if (predicate >> i) & 1:
                b = b.intersect(row)
            else:
                b = b.difference(row)
        return b

    def range_neq(self, bit_depth: int, predicate: int) -> Row:
        return self.row(bit_depth).difference(self.range_eq(bit_depth, predicate))

    def range_lt(self, bit_depth: int, predicate: int, allow_equality: bool) -> Row:
        keep = Row()
        b = self.row(bit_depth)
        leading_zeros = True
        for i in reversed(range(bit_depth)):
            row = self.row(i)
            bit = (predicate >> i) & 1
            if leading_zeros:
                if bit == 0:
                    b = b.difference(row)
                    continue
                leading_zeros = False
            if i == 0 and not allow_equality:
                if bit == 0:
                    return keep
                return b.difference(row.difference(keep))
            if bit == 0:
                b = b.difference(row.difference(keep))
                continue
            if i > 0:
                keep = keep.union(b.difference(row))
        return b

    def range_gt(self, bit_depth: int, predicate: int, allow_equality: bool) -> Row:
        b = self.row(bit_depth)
        keep = Row()
        for i in reversed(range(bit_depth)):
            row = self.row(i)
            bit = (predicate >> i) & 1
            if i == 0 and not allow_equality:
                if bit == 1:
                    return keep
                return b.difference(b.difference(row).difference(keep))
            if bit == 1:
                b = b.difference(b.difference(row).difference(keep))
                continue
            if i > 0:
                keep = keep.union(b.intersect(row))
        return b

    def not_null(self, bit_depth: int) -> Row:
        return self.row(bit_depth)

    def range_between(self, bit_depth: int, pred_min: int, pred_max: int) -> Row:
        b = self.row(bit_depth)
        keep1 = Row()
        keep2 = Row()
        for i in reversed(range(bit_depth)):
            row = self.row(i)
            bit1 = (pred_min >> i) & 1
            bit2 = (pred_max >> i) & 1
            if bit1 == 1:
                b = b.difference(b.difference(row).difference(keep1))
            elif i > 0:
                keep1 = keep1.union(b.intersect(row))
            if bit2 == 0:
                b = b.difference(row.difference(keep2))
            elif i > 0:
                keep2 = keep2.union(b.difference(row))
        return b

    # -- TopN (reference fragment.top:867-1002) ------------------------------

    def top(self, opt: TopOptions) -> list[tuple[int, int]]:
        """Returns [(row_id, count)] ranked descending, reproducing the
        reference's ranked-cache + threshold-pruning walk."""
        pairs = self._top_bitmap_pairs(opt.row_ids)
        n = 0 if opt.row_ids else opt.n

        filters = None
        if opt.filter_name and opt.filter_values:
            filters = set()
            for v in opt.filter_values:
                filters.add(v if not isinstance(v, list) else tuple(v))

        tanimoto_threshold = 0
        min_tanimoto = max_tanimoto = 0.0
        src_count = 0
        if opt.tanimoto_threshold > 0 and opt.src is not None:
            tanimoto_threshold = opt.tanimoto_threshold
            src_count = opt.src.count()
            min_tanimoto = float(src_count * tanimoto_threshold) / 100
            max_tanimoto = float(src_count * 100) / float(tanimoto_threshold)

        results: list[tuple[int, int]] = []  # min-heap of (count, row_id)
        for row_id, cnt in pairs:
            if cnt <= 0:
                continue
            if tanimoto_threshold > 0:
                if float(cnt) <= min_tanimoto or float(cnt) >= max_tanimoto:
                    continue
            elif cnt < opt.min_threshold:
                continue
            if filters is not None:
                attr = (
                    self.row_attr_store.attrs(row_id) if self.row_attr_store else None
                )
                if not attr:
                    continue
                value = attr.get(opt.filter_name)
                if value is None or value not in filters:
                    continue

            if n == 0 or len(results) < n:
                count = cnt
                if opt.src is not None:
                    count = opt.src.intersection_count(self.row(row_id))
                if count == 0:
                    continue
                if tanimoto_threshold > 0:
                    tanimoto = math.ceil(
                        float(count * 100) / float(cnt + src_count - count)
                    )
                    if tanimoto <= float(tanimoto_threshold):
                        continue
                elif count < opt.min_threshold:
                    continue
                heapq.heappush(results, (count, row_id))
                if n > 0 and len(results) == n and opt.src is None:
                    break
                continue

            threshold = results[0][0]
            if threshold < opt.min_threshold or cnt < threshold:
                break
            count = opt.src.intersection_count(self.row(row_id))
            if count < threshold:
                continue
            heapq.heappush(results, (count, row_id))

        out = []
        while results:
            count, row_id = heapq.heappop(results)
            out.append((row_id, count))
        out.reverse()
        return out

    def _top_bitmap_pairs(self, row_ids: list[int]) -> list[tuple[int, int]]:
        """reference topBitmapPairs (fragment.go:1004-1044)."""
        if self.cache_type == cache_mod.CACHE_TYPE_NONE:
            return self.cache.top()
        if not row_ids:
            with self.mu:
                self.cache.invalidate()
                return self.cache.top()
        pairs = []
        missing = []
        for row_id in row_ids:
            n = self.cache.get(row_id)
            if n > 0:
                pairs.append((row_id, n))
            else:
                missing.append(row_id)
        if missing:
            # vectorised recount from the occupancy index — same number
            # as row(id).count() without materialising the rows
            counts = self.row_counts_for(np.asarray(missing, dtype=np.uint64))
            pairs += [
                (r, int(cnt)) for r, cnt in zip(missing, counts) if cnt > 0
            ]
        return cache_mod.sort_pairs(pairs)

    # -- bulk import (reference bulkImport:1296-1397) ------------------------

    def bulk_import(self, row_ids: Iterable[int], column_ids: Iterable[int]) -> None:
        """Vectorised set of many bits, bypassing the op log, then snapshot.

        The reference loops storage.Add per bit; we merge a bulk-built
        bitmap (union of sorted positions) — same result, orders of
        magnitude faster in Python, and the post-import snapshot persists
        identically.
        """
        rows = np.asarray(_sized(row_ids), dtype=np.uint64)
        cols = np.asarray(_sized(column_ids), dtype=np.uint64)
        if rows.size != cols.size:
            raise ValueError("row/column id mismatch")
        if rows.size == 0:
            return
        with self.mu:
            positions = rows * np.uint64(SHARD_WIDTH) + (
                cols % np.uint64(SHARD_WIDTH)
            )
            positions = np.unique(positions)
            if positions.size <= self.delta_max_batch:
                # small batch: the delta path (one group-commit append,
                # one generation bump, one device scatter) — routing it
                # through merge+snapshot would `_delta_reset()` and
                # force every staged block to full-rebuild (the
                # bulk-import cliff)
                self._apply_op_wave(
                    [
                        (int(p), True, int(p // np.uint64(SHARD_WIDTH)))
                        for p in positions
                    ]
                )
                return
            self.storage.merge_positions(add=positions)
            self.generation += 1
            self._delta_reset()  # bulk rewrite: staged snapshots rebuild
            self._row_cache.clear()
            self.checksums.clear()
            # recount touched rows from container cardinalities in one
            # vectorized pass — materializing each row walked the whole
            # container key space per row (observed: 65 s of a 71 s
            # 2M-bit import, O(rows × containers))
            touched = np.unique(rows)
            counts = self.row_counts_for(touched)
            for row_id, n in zip(touched.tolist(), counts.tolist()):
                self.cache.bulk_add(int(row_id), int(n))
            top = int(touched[-1])
            if top > self.max_row_id:
                self.max_row_id = top
            self.cache.invalidate()
            self.snapshot()

    def import_value(
        self, column_ids: Iterable[int], values: Iterable[int], bit_depth: int
    ) -> None:
        """Bulk BSI import (reference importValue:1363-1397), vectorised:
        clear every imported column's bit planes in one difference, then
        union in the set bits — identical to the reference's per-bit
        add/remove loop, last write winning for duplicate columns."""
        cols = np.asarray(_sized(column_ids), dtype=np.uint64)
        vals = np.asarray(_sized(values), dtype=np.uint64)
        if cols.size != vals.size:
            raise ValueError("column/value mismatch")
        if cols.size == 0:
            return
        min_col = self.shard * SHARD_WIDTH
        if int(cols.min()) < min_col or int(cols.max()) >= min_col + SHARD_WIDTH:
            raise ValueError("column out of bounds")
        with self.mu:
            # last write wins for duplicate columns (the reference's
            # sequential loop overwrites earlier values)
            _, last_idx = np.unique(cols[::-1], return_index=True)
            keep = cols.size - 1 - last_idx
            cols_l = (cols[keep] % np.uint64(SHARD_WIDTH)).astype(np.uint64)
            vals_k = vals[keep]
            sw = np.uint64(SHARD_WIDTH)
            clear_pos = []
            set_pos = []
            for i in range(bit_depth):
                base = np.uint64(i) * sw
                clear_pos.append(base + cols_l)
                mask = (vals_k >> np.uint64(i)) & np.uint64(1) == 1
                set_pos.append(base + cols_l[mask])
            nn = np.uint64(bit_depth) * sw + cols_l  # not-null plane
            set_pos.append(nn)
            set_all = np.unique(np.concatenate(set_pos))
            clear_all = (
                np.unique(np.concatenate(clear_pos)) if clear_pos else None
            )  # bit_depth == 0 (min == max) has no planes
            self.storage.merge_positions(add=set_all, remove=clear_all)
            self.generation += 1
            self._delta_reset()  # bulk rewrite: staged snapshots rebuild
            self._row_cache.clear()
            self.checksums.clear()
            self._recompute_max_row_id()
            self.snapshot()

    # -- snapshot / persistence ---------------------------------------------

    def snapshot(self) -> None:
        """Write a full roaring snapshot and truncate the op log
        (reference snapshot:1425-1468)."""
        with self.mu:
            self.generation += 1
            if self._delta_synced == self.generation - 1:
                # content-preserving bump: the snapshot changes the
                # on-disk base, not the bit set, so staged snapshots
                # remain patchable — the log stays authoritative
                self._delta_synced = self.generation
            if not self.path:
                self.op_n = 0
                self.storage.op_n = 0
                self._op_log_dirty = False
                return
            if self._op_file:
                self._op_file.close()
                self._op_file = None
            tmp = self.path + ".snapshotting"
            spec = FAULTS
            with open(tmp, "w+b") as f:
                n = self.storage.write_to(f)
                f.flush()
                f.seek(0)
                base = f.read(n)
                # digest the base BEFORE any injected corruption: the
                # corrupt_write fault models bytes rotting between the
                # digest computation and the media, which is exactly
                # what verification must catch
                trailer = bitmap_mod.make_digest_trailer(base)
                if spec is not None:
                    off = spec.corrupt_offset(n)
                    if off is not None:
                        f.seek(off)
                        f.write(bytes([base[off] ^ 0x01]))
                f.seek(n)
                f.write(trailer)
                f.flush()
                os.fsync(f.fileno())
            if spec is not None:
                spec.kill_point("pre")
            os.replace(tmp, self.path)
            if spec is not None:
                spec.kill_point("post")
            # the base just changed: the occupancy sidecar is stale by
            # construction (its stamp may even collide — equal size +
            # container count after a balanced clear/set pair), so
            # remove it; the next occupancy() regenerates it
            try:
                os.unlink(self.path + ".occ")
            except OSError:
                pass
            if self.storage.is_mmap_backed():
                # Re-map the fresh snapshot so the overlay drains back
                # into the frozen base (reference snapshot re-mmaps,
                # fragment.go:1425-1468). The old map is freed when the
                # last view into it is garbage-collected.
                self._load_storage()
            self._op_file = open(self.path, "ab")
            self.storage.op_writer = self._op_file
            self.op_n = 0
            self.storage.op_n = 0
            # the file was rebuilt wholesale: any poisoned tail is gone
            self._op_log_dirty = False

    # -- block checksums for anti-entropy (reference Blocks:1078) ------------

    def checksum(self) -> bytes:
        """Checksum of the entire fragment."""
        h = hashlib.blake2b(digest_size=16)
        for _, digest in self.blocks():
            h.update(digest)
        return h.digest()

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block_id, checksum) for each 100-row block with any bits."""
        return self._blocks_of(self.storage)

    @staticmethod
    def _blocks_of(storage) -> list[tuple[int, bytes]]:
        """blocks() over an arbitrary Bitmap — the deep scrub compares
        the live storage against a fresh re-read of the file."""
        out: dict[int, "hashlib._Hash"] = {}
        order: list[int] = []
        for key in storage._iter_keys_sorted():
            c = storage.containers[key]
            if not c.n:
                continue
            row_id = (key << 16) // SHARD_WIDTH
            block = row_id // HASH_BLOCK_SIZE
            h = out.get(block)
            if h is None:
                h = hashlib.blake2b(digest_size=16)
                out[block] = h
                order.append(block)
            h.update(key.to_bytes(8, "little"))
            h.update(c.positions().tobytes())
        return [(b, out[b].digest()) for b in order]

    def block_data(self, block_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids, column_ids) pairs for one block (reference
        fragment.rowColumnPairs path used by BlockData)."""
        start = block_id * HASH_BLOCK_SIZE * SHARD_WIDTH
        end = (block_id + 1) * HASH_BLOCK_SIZE * SHARD_WIDTH
        positions = self.storage.slice_range(start, end)
        rows = positions // np.uint64(SHARD_WIDTH)
        cols = positions % np.uint64(SHARD_WIDTH)
        return rows, cols

    def import_block_pairs(self, rows: np.ndarray, cols: np.ndarray, clear_rows=None, clear_cols=None) -> None:
        """Apply an anti-entropy block merge: set the given pairs, clear others."""
        with self.mu:
            n_pairs = len(rows) + (len(clear_rows) if clear_rows is not None else 0)
            if 0 < n_pairs <= self.delta_max_batch:
                # small merge: delta path — clears before sets, so a
                # pair in both ends set (same order as the loop below)
                wave: list[tuple[int, bool, int]] = []
                if clear_rows is not None and len(clear_rows):
                    wave += [
                        (pos(int(r), int(c)), False, int(r))
                        for r, c in zip(clear_rows, clear_cols)
                    ]
                wave += [
                    (pos(int(r), int(c)), True, int(r))
                    for r, c in zip(rows, cols)
                ]
                self._apply_op_wave(wave)
                return
            if clear_rows is not None and len(clear_rows):
                for r, c in zip(clear_rows, clear_cols):
                    p = pos(int(r), int(c))
                    self.storage.remove_no_oplog(p)
            for r, c in zip(rows, cols):
                self.storage.add_no_oplog(pos(int(r), int(c)))
            self.generation += 1
            self._delta_reset()  # block merge: staged snapshots rebuild
            self._row_cache.clear()
            self.checksums.clear()
            self._recompute_max_row_id()
            # recount touched rows so the TopN cache tracks the merged
            # state (the reference's write paths recount via cache.Add)
            touched = {int(r) for r in rows}
            if clear_rows is not None:
                touched.update(int(r) for r in clear_rows)
            if touched:
                counts = self.row_counts_for(
                    np.fromiter(touched, dtype=np.uint64, count=len(touched))
                )
                for row_id, cnt in zip(touched, counts):
                    # drop first: bulk_add's threshold guard would
                    # otherwise keep a stale higher count for rows the
                    # merge shrank or emptied
                    self.cache.remove(row_id)
                    if cnt > 0:
                        self.cache.bulk_add(row_id, int(cnt))
                self.cache.invalidate()

    # -- packed-word export for device staging -------------------------------

    def row_words(self, row_id: int) -> np.ndarray:
        """One row as packed uint64[16384] (2^20 bits)."""
        self.check_serving()
        return self.storage.to_words_range(
            row_id * SHARD_WIDTH, (row_id + 1) * SHARD_WIDTH
        )

    def packed_rows(self, row_ids: list[int]) -> np.ndarray:
        """Stack of rows: uint64[len(row_ids), 16384]."""
        out = np.zeros((len(row_ids), SHARD_WIDTH // 64), dtype=np.uint64)
        for i, r in enumerate(row_ids):
            out[i] = self.row_words(r)
        return out

    def row_matrix(self) -> tuple[list[int], np.ndarray]:
        """(row_ids, uint64[R, 16384]) for all non-empty rows — the HBM
        staging block for whole-fragment scans (TopN)."""
        ids = self.row_ids()
        return ids, self.packed_rows(ids)

    def sparse_block_count(self, row_ids: list[int]) -> int:
        """Number of nonempty container blocks across the given rows —
        the sparse-staging cost estimate (dense cost is 16 per row)."""
        _, _, lo, hi = self._row_key_spans(np.asarray(row_ids, dtype=np.uint64))
        return int((hi - lo).sum())

    def sparse_row_blocks(
        self, row_ids: list[int]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Block-sparse staging form of the given rows: only nonempty
        2^16-bit container blocks, as (blocks u64[B, 1024],
        block_row i32[B] — index into row_ids, block_slot i32[B] — the
        block's position within its row). The container occupancy index
        is the sparsity map (SURVEY.md §7 hard part 2)."""
        from pilosa_tpu.roaring.bitmap import BITMAP_N

        rids = np.asarray(row_ids, dtype=np.uint64)
        per = 16
        keys, _, lo, hi = self._row_key_spans(rids)
        counts = (hi - lo).astype(np.int64)
        B = int(counts.sum())
        blocks = np.zeros((B, BITMAP_N), dtype=np.uint64)
        block_row = np.repeat(np.arange(rids.size, dtype=np.int32), counts)
        if B == 0:
            return blocks, block_row, np.zeros(0, dtype=np.int32)
        key_idx = np.concatenate(
            [np.arange(l, h, dtype=np.int64) for l, h in zip(lo, hi) if h > l]
        )
        sel_keys = keys[key_idx]
        block_slot = (sel_keys.astype(np.int64) % per).astype(np.int32)
        store = self.storage.containers
        # fast path: for a PURE mmap store the occupancy indices ARE
        # base indices, and the native kernel expands every selected
        # container straight from the map into `blocks` — no Python
        # iteration per container (the staging pack's hot loop). The
        # snapshot length rides along so a stale occupancy snapshot
        # (taken mid-mutation by this lockless reader) can never feed
        # shifted indices to the native decode.
        if not (
            hasattr(store, "expand_base_blocks")
            and store.expand_base_blocks(key_idx, blocks, snapshot_len=keys.size)
        ):
            for j, k in enumerate(sel_keys):
                c = store.get(int(k))
                if c is not None and c.n:
                    blocks[j] = c.words()
        return blocks, block_row, block_slot

    def bsi_planes(self, bit_depth: int) -> np.ndarray:
        """uint64[bit_depth+1, 16384] plane stack (plane bit_depth = not-null)."""
        return self.packed_rows(list(range(bit_depth + 1)))

    def container_blocks(
        self, row_ids: list[int]
    ) -> tuple[list[tuple[int, int, int, np.ndarray]], int]:
        """Container-level serialization of the given rows — the T1
        (host-RAM compressed tier) block form and the compressed-upload
        payload. Returns (entries, nbytes): entries is one
        ``(row_index, slot, typ, payload)`` per nonempty container,
        where ``row_index`` indexes into ``row_ids``, ``slot`` is the
        container's position within its row (0..15), ``typ`` is the
        roaring container type, and ``payload`` is a private copy of
        its native form — uint16 positions (array), uint16 [start,
        last] pairs (run), or packed uint64[1024] words (bitmap).
        ``nbytes`` is the summed payload size, the T1 accounting unit.
        """
        from pilosa_tpu.roaring.bitmap import CONTAINER_ARRAY, CONTAINER_RUN

        rids = np.asarray(row_ids, dtype=np.uint64)
        keys, _, lo, hi = self._row_key_spans(rids)
        store = self.storage.containers
        entries: list[tuple[int, int, int, np.ndarray]] = []
        nbytes = 0
        for i, (l, h) in enumerate(zip(lo, hi)):
            for k in keys[l:h]:
                c = store.get(int(k))
                if c is None or not c.n:
                    continue
                slot = int(k) % (SHARD_WIDTH >> 16)
                if c.typ == CONTAINER_ARRAY:
                    payload = np.array(c.array, dtype=np.uint16)
                elif c.typ == CONTAINER_RUN:
                    payload = np.array(c.runs, dtype=np.uint16).reshape(-1, 2)
                else:
                    payload = np.array(c.words(), dtype=np.uint64)
                entries.append((i, slot, int(c.typ), payload))
                nbytes += payload.nbytes
        return entries, nbytes
