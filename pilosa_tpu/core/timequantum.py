"""Time quantum views (reference time.go).

A time field fans each Set out to per-granularity views
(``standard_2017``, ``standard_201701``, …); a time Range unions the
minimal covering set of views between start and end.
"""

from __future__ import annotations

from datetime import datetime, timedelta

VALID_QUANTUMS = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}

TIME_FORMAT = "%Y-%m-%dT%H:%M"  # reference TimeFormat (pilosa.go)


def parse_time_quantum(v: str) -> str:
    q = v.upper()
    if q not in VALID_QUANTUMS:
        raise ValueError(f"invalid time quantum: {v!r}")
    return q


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    """reference viewByTimeUnit (time.go:83-96)."""
    if unit == "Y":
        return f"{name}_{t.strftime('%Y')}"
    if unit == "M":
        return f"{name}_{t.strftime('%Y%m')}"
    if unit == "D":
        return f"{name}_{t.strftime('%Y%m%d')}"
    if unit == "H":
        return f"{name}_{t.strftime('%Y%m%d%H')}"
    return ""


def views_by_time(name: str, t: datetime, quantum: str) -> list[str]:
    """reference viewsByTime (time.go:99-109)."""
    out = []
    for unit in quantum:
        v = view_by_time_unit(name, t, unit)
        if v:
            out.append(v)
    return out


def _add_months(t: datetime, months: int) -> datetime:
    """Go AddDate month arithmetic, including its normalization: a day
    that doesn't exist in the target month rolls forward (Jan 29 + 1
    month = Mar 1; Feb 29 + 1 year = Mar 1). The walker probes month/
    year boundaries from arbitrary mid-walk days, so overflow is a
    reachable case, not a corner."""
    import calendar

    month = t.month - 1 + months
    year = t.year + month // 12
    month = month % 12 + 1
    last = calendar.monthrange(year, month)[1]
    if t.day <= last:
        return t.replace(year=year, month=month)
    return t.replace(year=year, month=month, day=last) + timedelta(
        days=t.day - last
    )


def _next_year_gte(t: datetime, end: datetime) -> bool:
    nxt = _add_months(t, 12)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t: datetime, end: datetime) -> bool:
    nxt = _add_months(t, 1)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _next_day_gte(t: datetime, end: datetime) -> bool:
    nxt = t + timedelta(days=1)
    return (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day) or end > nxt


def views_by_time_range(name: str, start: datetime, end: datetime, quantum: str) -> list[str]:
    """Minimal covering view set for [start, end) (reference
    viewsByTimeRange, time.go:111-184): walk up from small units to
    aligned boundaries, then down from the largest unit."""
    t = start
    has_year = "Y" in quantum
    has_month = "M" in quantum
    has_day = "D" in quantum
    has_hour = "H" in quantum
    results: list[str] = []

    # Walk up from smallest units to largest units.
    if has_hour or has_day or has_month:
        while t < end:
            if has_hour:
                if not _next_day_gte(t, end):
                    break
                elif t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = t + timedelta(hours=1)
                    continue
            if has_day:
                if not _next_month_gte(t, end):
                    break
                elif t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = t + timedelta(days=1)
                    continue
            if has_month:
                if not _next_year_gte(t, end):
                    break
                elif t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_months(t, 1)
                    continue
            break

    # Walk back down from largest units to smallest units.
    while t < end:
        if has_year and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_months(t, 12)  # Go AddDate(1,0,0): Feb 29 -> Mar 1
        elif has_month and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_months(t, 1)
        elif has_day and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t = t + timedelta(days=1)
        elif has_hour:
            results.append(view_by_time_unit(name, t, "H"))
            t = t + timedelta(hours=1)
        else:
            break

    return results
