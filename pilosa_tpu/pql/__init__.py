"""PQL language layer (L3): AST + parser."""

from pilosa_tpu.pql.ast import (
    BETWEEN,
    COND_OPS,
    EQ,
    GT,
    GTE,
    LT,
    LTE,
    NEQ,
    Call,
    Condition,
    Query,
)
from pilosa_tpu.pql.parser import ParseError, Parser, parse

__all__ = [
    "BETWEEN",
    "COND_OPS",
    "EQ",
    "GT",
    "GTE",
    "LT",
    "LTE",
    "NEQ",
    "Call",
    "Condition",
    "ParseError",
    "Parser",
    "Query",
    "parse",
]
