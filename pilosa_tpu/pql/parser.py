"""PQL parser — hand-written recursive descent over the reference's PEG
grammar (reference pql/pql.peg; the reference compiles it to a 2,850-line
parser machine, pql.peg.go — the grammar is small enough that descent is
clearer and equally fast).

Grammar summary:
    Calls    <- (Call)*
    Call     <- Set(col, args, timestamp?) / SetRowAttrs(field, row, args)
              / SetColumnAttrs(col, args) / Clear(col, args)
              / TopN(field, allargs?) / Range(timerange/conditional/arg)
              / IDENT(allargs)
    allargs  <- Call (, Call)* (, args)? / args / ε
    arg      <- field '=' value / field COND value
    COND     <- >< | <= | >= | == | != | < | >
    conditional <- int <[=] field <[=] int
"""

from __future__ import annotations

import re
from typing import Any, Optional

from pilosa_tpu.pql.ast import BETWEEN, Call, Condition, Query

_TIMESTAMP_RE = re.compile(r"\d{4}-[01]\d-[0-3]\dT\d\d:\d\d$")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_RESERVED = {"_row", "_col", "_start", "_end", "_timestamp", "_field"}
# item bare-word charset (pql.peg `item`): letters digits - _ :
_WORD_RE = re.compile(r"[A-Za-z0-9_:-]+")
_NUM_RE = re.compile(r"-?(\d+(\.\d*)?|\.\d+)")
_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9]*")


class ParseError(Exception):
    pass


class Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        self.pos = 0

    # -- low-level helpers --

    def _ws(self, newlines: bool = True) -> None:
        chars = " \t\n" if newlines else " \t"
        while self.pos < len(self.text) and self.text[self.pos] in chars:
            self.pos += 1

    def _peek(self) -> str:
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def _expect(self, s: str) -> None:
        if not self.text.startswith(s, self.pos):
            raise ParseError(
                f"expected {s!r} at position {self.pos}: "
                f"{self.text[self.pos:self.pos+20]!r}"
            )
        self.pos += len(s)

    def _try(self, s: str) -> bool:
        if self.text.startswith(s, self.pos):
            self.pos += len(s)
            return True
        return False

    def _match(self, regex: re.Pattern) -> Optional[str]:
        m = regex.match(self.text, self.pos)
        if m is None:
            return None
        self.pos = m.end()
        return m.group(0)

    # -- entry --

    def parse(self) -> Query:
        calls = []
        self._ws()
        while self.pos < len(self.text):
            calls.append(self._call())
            self._ws()
        return Query(calls)

    # -- call forms --

    def _call(self) -> Call:
        ident = self._match(_IDENT_RE)
        if ident is None:
            raise ParseError(f"expected call at position {self.pos}")
        self._ws(False)
        self._expect("(")
        self._ws(False)
        special = {
            "Set": self._set_call,
            "SetRowAttrs": self._set_row_attrs_call,
            "SetColumnAttrs": self._set_column_attrs_call,
            "Clear": self._clear_call,
            "TopN": self._topn_call,
            "Rows": self._rows_call,
            "Range": self._range_call,
        }.get(ident)
        if special is not None:
            # PEG ordered choice: if the positional form fails, backtrack
            # to the generic IDENT rule (reserved _col/_field/... args are
            # legal there) — this is how the reference round-trips
            # Call.String() for remote execution.
            save = self.pos
            try:
                call = special()
            except ParseError:
                self.pos = save
                call = self._generic_call(ident)
        else:
            call = self._generic_call(ident)
        self._ws(False)
        self._expect(")")
        self._ws(False)
        return call

    def _comma(self) -> bool:
        save = self.pos
        self._ws(False)
        if self._try(","):
            self._ws()
            return True
        self.pos = save
        return False

    def _col(self, call: Call) -> None:
        if self._peek() == '"':
            call.args["_col"] = self._quoted_string()
        else:
            n = self._match(_NUM_RE)
            if n is None or "." in n or n.startswith("-"):
                raise ParseError(f"expected column id at position {self.pos}")
            call.args["_col"] = int(n)

    def _set_call(self) -> Call:
        # Set(col, field=row[, timestamp])
        call = Call("Set")
        self._col(call)
        if not self._comma():
            raise ParseError("Set() requires arguments")
        while True:
            ts = self._try_timestamp()
            if ts is not None:
                call.args["_timestamp"] = ts
                break
            self._arg(call)
            if not self._comma():
                break
        return call

    def _try_timestamp(self) -> Optional[str]:
        save = self.pos
        w = self._match(_WORD_RE)
        if w is not None and _TIMESTAMP_RE.match(w):
            return w
        self.pos = save
        return None

    def _set_row_attrs_call(self) -> Call:
        call = Call("SetRowAttrs")
        field = self._match(_FIELD_RE)
        if field is None:
            raise ParseError("SetRowAttrs() requires a field")
        call.args["_field"] = field
        if not self._comma():
            raise ParseError("SetRowAttrs() requires a row")
        n = self._match(_NUM_RE)
        if n is None:
            raise ParseError("SetRowAttrs() requires a row id")
        call.args["_row"] = int(n)
        if self._comma():
            self._args(call)
        return call

    def _set_column_attrs_call(self) -> Call:
        call = Call("SetColumnAttrs")
        self._col(call)
        if self._comma():
            self._args(call)
        return call

    def _clear_call(self) -> Call:
        call = Call("Clear")
        self._col(call)
        if not self._comma():
            raise ParseError("Clear() requires arguments")
        self._args(call)
        return call

    def _topn_call(self) -> Call:
        call = Call("TopN")
        field = self._match(_FIELD_RE)
        if field is None:
            raise ParseError("TopN() requires a field")
        call.args["_field"] = field
        if self._comma():
            self._allargs(call)
        return call

    def _rows_call(self) -> Call:
        # Rows(field[, ids=[...]]) — a GroupBy dimension; same positional
        # field grammar as TopN
        call = Call("Rows")
        field = self._match(_FIELD_RE)
        if field is None:
            raise ParseError("Rows() requires a field")
        call.args["_field"] = field
        if self._comma():
            self._args(call)
        return call

    def _range_call(self) -> Call:
        call = Call("Range")
        # conditional: int <[=] field <[=] int
        save = self.pos
        if self._conditional(call):
            return call
        self.pos = save
        # timerange or single arg: field ('=' value , ts , ts) | COND value
        field = self._field_name()
        self._ws(False)
        op = self._cond_op()
        if op is None:
            self._expect("=")
            self._ws(False)
            value = self._value()
            if self._comma():
                start = self._timestamp_value()
                if not self._comma():
                    raise ParseError("Range() expects start and end timestamps")
                end = self._timestamp_value()
                call.args[field] = value
                call.args["_start"] = start
                call.args["_end"] = end
                return call
            call.args[field] = value
            return call
        self._ws(False)
        value = self._value()
        call.args[field] = Condition(op, value)
        return call

    def _conditional(self, call: Call) -> bool:
        """int <[=] field <[=] int → field: Condition(BETWEEN, [low, high]).

        NOTE (reference quirk, pql/ast.go:76-96 endConditional): the
        reference increments low for a strict '<' on the left but
        increments high for '<=' on the right — i.e. `a < f <= b` becomes
        BETWEEN [a+1, b+1]. Mirrored for parity.
        """
        n = self._match(re.compile(r"-?[1-9][0-9]*|0"))
        if n is None:
            return False
        self._ws(False)
        op1 = "<=" if self._try("<=") else ("<" if self._try("<") else None)
        if op1 is None:
            return False
        self._ws(False)
        field = self._match(_FIELD_RE)
        if field is None:
            return False
        self._ws(False)
        op2 = "<=" if self._try("<=") else ("<" if self._try("<") else None)
        if op2 is None:
            return False
        self._ws(False)
        m = self._match(re.compile(r"-?[1-9][0-9]*|0"))
        if m is None:
            return False
        low, high = int(n), int(m)
        if op1 == "<":
            low += 1
        if op2 == "<=":
            high += 1
        call.args[field] = Condition(BETWEEN, [low, high])
        return True

    def _generic_call(self, name: str) -> Call:
        call = Call(name)
        self._allargs(call)
        # trailing comma allowed (grammar: open allargs comma? close)
        self._comma()
        return call

    def _allargs(self, call: Call) -> None:
        # allargs <- Call (comma Call)* (comma args)? / args / sp
        self._ws(False)
        if self._peek() == ")":
            return
        if self._at_call():
            call.children.append(self._call())
            while True:
                save = self.pos
                if not self._comma():
                    break
                if self._at_call():
                    call.children.append(self._call())
                else:
                    self._args(call)
                    break
                continue
            return
        self._args(call)

    def _at_call(self) -> bool:
        """Lookahead: IDENT followed by '(' begins a nested call."""
        m = _IDENT_RE.match(self.text, self.pos)
        if m is None:
            return False
        p = m.end()
        while p < len(self.text) and self.text[p] in " \t":
            p += 1
        return p < len(self.text) and self.text[p] == "("

    # -- args --

    def _args(self, call: Call) -> None:
        while True:
            self._arg(call)
            if not self._comma():
                break
            if self._peek() == ")":
                break

    def _field_name(self) -> str:
        for r in _RESERVED:
            if self.text.startswith(r, self.pos):
                self.pos += len(r)
                return r
        f = self._match(_FIELD_RE)
        if f is None:
            raise ParseError(f"expected field name at position {self.pos}")
        return f

    def _cond_op(self) -> Optional[str]:
        for op in ("><", "<=", ">=", "==", "!=", "<", ">"):
            if self._try(op):
                return op
        return None

    def _arg(self, call: Call) -> None:
        field = self._field_name()
        self._ws(False)
        op = self._cond_op()
        if op is None:
            self._expect("=")
            self._ws(False)
            call.args[field] = self._value()
        else:
            self._ws(False)
            call.args[field] = Condition(op, self._value())

    # -- values --

    def _timestamp_value(self) -> str:
        if self._peek() in "\"'":
            q = self._peek()
            self.pos += 1
            m = self._match(_WORD_RE)
            self._expect(q)
        else:
            m = self._match(_WORD_RE)
        if m is None or not _TIMESTAMP_RE.match(m):
            raise ParseError(f"cannot parse timestamp at position {self.pos}")
        return m

    def _quoted_string(self) -> str:
        q = self._peek()
        assert q in "\"'"
        self.pos += 1
        out = []
        while self.pos < len(self.text):
            ch = self.text[self.pos]
            if ch == "\\" and self.pos + 1 < len(self.text):
                nxt = self.text[self.pos + 1]
                if nxt == "n":
                    out.append("\n")
                elif nxt in "\"'\\":
                    out.append(nxt)
                else:
                    out.append(ch + nxt)
                self.pos += 2
                continue
            if ch == q:
                self.pos += 1
                return "".join(out)
            if ch == "\n":
                break
            out.append(ch)
            self.pos += 1
        raise ParseError("unterminated string")

    def _value(self) -> Any:
        ch = self._peek()
        if ch == "[":
            self.pos += 1
            self._ws(False)
            items = []
            while self._peek() != "]":
                items.append(self._item())
                if not self._comma():
                    self._ws(False)
            self._expect("]")
            return items
        return self._item()

    def _item(self) -> Any:
        ch = self._peek()
        if ch in "\"'":
            return self._quoted_string()
        save = self.pos
        num = self._match(_NUM_RE)
        if num is not None:
            nxt = self.text[self.pos] if self.pos < len(self.text) else ""
            if not (nxt.isalnum() or nxt in "_:-"):
                return float(num) if "." in num else int(num)
            self.pos = save  # digits continue into a bare word (e.g. 2017-01-02)
        word = self._match(_WORD_RE)
        if word is None:
            raise ParseError(f"expected value at position {self.pos}")
        if word == "null":
            return None
        if word == "true":
            return True
        if word == "false":
            return False
        return word


def parse(text: str) -> Query:
    """Parse a PQL query string (reference pql.NewParser().Parse())."""
    return Parser(text).parse()
