"""PQL AST (reference pql/ast.go): Query{calls} / Call{name,args,children} /
Condition{op,value}."""

from __future__ import annotations

from typing import Any, Optional

# condition tokens (reference pql/token.go:20-32)
EQ = "=="
NEQ = "!="
LT = "<"
LTE = "<="
GT = ">"
GTE = ">="
BETWEEN = "><"

COND_OPS = (BETWEEN, LTE, GTE, EQ, NEQ, LT, GT)

# Calls that write (reference ast.go:211 WriteCallN)
WRITE_CALLS = {"Set", "SetRowAttrs", "SetColumnAttrs", "Clear", "SetValue"}


class Condition:
    """An operation & value attached to a field arg (reference ast.go:415)."""

    __slots__ = ("op", "value")

    def __init__(self, op: str, value: Any) -> None:
        self.op = op
        self.value = value

    def int_slice_value(self) -> list[int]:
        if not isinstance(self.value, list):
            raise ValueError(f"expected list condition value, got {self.value!r}")
        out = []
        for v in self.value:
            if not isinstance(v, int) or isinstance(v, bool):
                raise ValueError(f"expected int in condition list, got {v!r}")
            out.append(v)
        return out

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Condition)
            and self.op == other.op
            and self.value == other.value
        )

    def __repr__(self) -> str:
        return f"Condition({self.op!r}, {self.value!r})"

    def string_with_field(self, field: str) -> str:
        # BETWEEN prints as the `><` operator form so strings re-parse
        # without re-applying the conditional-form bound adjustments.
        return f"{field} {self.op} {format_value(self.value)}"


class Call:
    __slots__ = ("name", "args", "children")

    def __init__(
        self,
        name: str,
        args: Optional[dict[str, Any]] = None,
        children: Optional[list["Call"]] = None,
    ) -> None:
        self.name = name
        self.args = args if args is not None else {}
        self.children = children if children is not None else []

    # -- arg helpers (reference ast.go:257-330) --

    def field_arg(self) -> str:
        """The single non-underscore arg key, e.g. Set(col, field=row)."""
        for k in self.args:
            if not k.startswith("_"):
                return k
        raise ValueError("No field argument specified")

    def uint_arg(self, key: str) -> tuple[int, bool]:
        if key not in self.args:
            return 0, False
        v = self.args[key]
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"could not convert {v!r} to uint in arg {key!r}")
        return v & 0xFFFFFFFFFFFFFFFF, True

    def uint_slice_arg(self, key: str) -> tuple[list[int], bool]:
        if key not in self.args:
            return [], False
        v = self.args[key]
        if not isinstance(v, list):
            raise ValueError(f"unexpected type for arg {key!r}: {v!r}")
        out = []
        for x in v:
            if isinstance(x, bool) or not isinstance(x, int):
                raise ValueError(f"unexpected element in {key!r}: {x!r}")
            out.append(x & 0xFFFFFFFFFFFFFFFF)
        return out, True

    def string_arg(self, key: str) -> tuple[str, bool]:
        if key not in self.args:
            return "", False
        v = self.args[key]
        if not isinstance(v, str):
            raise ValueError(f"could not convert {v!r} to string in arg {key!r}")
        return v, True

    def has_condition_arg(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def keys(self) -> list[str]:
        return sorted(self.args)

    def clone(self) -> "Call":
        args = {}
        for k, v in self.args.items():
            if isinstance(v, list):
                args[k] = list(v)
            elif isinstance(v, Condition):
                args[k] = Condition(v.op, list(v.value) if isinstance(v.value, list) else v.value)
            else:
                args[k] = v
        return Call(self.name, args, [c.clone() for c in self.children])

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Call)
            and self.name == other.name
            and self.args == other.args
            and self.children == other.children
        )

    def __str__(self) -> str:
        """Serialize back to PARSEABLE PQL. The remote-execution leg
        re-sends calls as text (reference remoteExec,
        executor.go:1393-1440 sends q.String()), so every special form
        must invert its parse exactly — the internal ``_``-prefixed
        args are positional syntax, not named arguments:

          TopN(field, child?, args)      SetRowAttrs(field, row, args)
          Set(col, args, timestamp?)     Clear/SetColumnAttrs(col, args)
          Range(field=row, start, end)
        """
        name = self.name or "!UNNAMED"
        special = name in (
            "Set",
            "Clear",
            "SetColumnAttrs",
            "SetRowAttrs",
            "TopN",
            "Rows",
            "Range",
        )
        parts: list[str] = []
        positional: set[str] = set()
        if special:
            # positional grammar of the special forms; track exactly
            # which reserved args the positional syntax covers — any
            # OTHER reserved arg still renders named below (the parser
            # accepts reserved names as ordinary args), so nothing is
            # ever silently dropped from the remote leg
            if "_field" in self.args:
                parts.append(str(self.args["_field"]))  # bare, never quoted
                positional.add("_field")
                if "_row" in self.args:
                    parts.append(str(self.args["_row"]))
                    positional.add("_row")
            elif "_col" in self.args:
                parts.append(format_value(self.args["_col"]))
                positional.add("_col")
            positional.update(
                k for k in ("_start", "_end", "_timestamp") if k in self.args
            )
        parts += [str(c) for c in self.children]
        for key in self.keys():
            if key in positional:
                continue  # rendered positionally above / below
            v = self.args[key]
            if isinstance(v, Condition):
                parts.append(v.string_with_field(key))
            else:
                parts.append(f"{key}={format_value(v)}")
        if special:
            # trailing positional timestamps render bare (quoting them
            # would fail the parser's timestamp grammar)
            if "_start" in self.args:
                parts.append(str(self.args["_start"]))
            if "_end" in self.args:
                parts.append(str(self.args["_end"]))
            if "_timestamp" in self.args:
                parts.append(str(self.args["_timestamp"]))
        return f"{name}({', '.join(parts)})"

    __repr__ = __str__


class Query:
    __slots__ = ("calls",)

    def __init__(self, calls: Optional[list[Call]] = None) -> None:
        self.calls = calls or []

    def write_call_n(self) -> int:
        return sum(1 for c in self.calls if c.name in WRITE_CALLS)

    def __str__(self) -> str:
        return "".join(str(c) for c in self.calls)

    __repr__ = __str__


def format_value(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        # escape exactly what the parser's _quoted_string unescapes —
        # an unescaped quote in a value would re-parse as different PQL
        # on the remote leg (injection), or not parse at all
        s = (
            v.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        return f'"{s}"'
    if isinstance(v, list):
        return "[" + ",".join(format_value(x) for x in v) + "]"
    if isinstance(v, float):
        # positional notation only: the PQL number grammar has no
        # exponent form, so str(1e-07) would re-parse as the STRING
        # '1e-07' on the remote leg — a silent type change. Keep a
        # decimal point so integral floats (1e22) don't re-parse as int.
        from decimal import Decimal

        s = format(Decimal(repr(v)), "f")
        return s if "." in s else s + ".0"
    return str(v)
