"""ctypes binding for the native C++ bitmap kernels (native/).

Auto-builds ``native/libpilosa_kernels.so`` with g++ on first import if
missing, and degrades to numpy implementations when no compiler is
available — the roaring engine works either way, the native path just
removes temporaries and Python overhead from the hot loops.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libpilosa_kernels.so")

_lib: Optional[ctypes.CDLL] = None


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "bitmap_kernels.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            [
                "g++",
                "-O3",
                "-march=native",
                "-funroll-loops",
                "-fPIC",
                "-shared",
                "-std=c++17",
                "-o",
                _SO_PATH,
                src,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _stale() -> bool:
    """The .so predates the source — a prebuilt library from an older
    checkout would be missing newer symbols."""
    try:
        src = os.path.getmtime(os.path.join(_NATIVE_DIR, "bitmap_kernels.cpp"))
        so = os.path.getmtime(_SO_PATH)
        return src > so
    except OSError:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if (not os.path.exists(_SO_PATH) or _stale()) and not _build():
        if not os.path.exists(_SO_PATH):
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    try:
        _bind(lib)
    except AttributeError:
        # stale prebuilt .so missing a newer symbol (e.g. built before
        # the mtime check existed): rebuild once, then degrade to numpy
        # rather than crash — the module contract
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            _bind(lib)
        except (OSError, AttributeError):
            return None
    _lib = lib
    return lib


def _bind(lib: ctypes.CDLL) -> None:
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.pt_popcount.restype = ctypes.c_uint64
    lib.pt_popcount.argtypes = [u64p, ctypes.c_size_t]
    lib.pt_intersection_count.restype = ctypes.c_uint64
    lib.pt_intersection_count.argtypes = [u64p, u64p, ctypes.c_size_t]
    for name in ("pt_and", "pt_or", "pt_xor", "pt_andnot"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [u64p, u64p, u64p, ctypes.c_size_t]
    lib.pt_intersect_sorted_u16.restype = ctypes.c_size_t
    lib.pt_intersect_sorted_u16.argtypes = [
        u16p, ctypes.c_size_t, u16p, ctypes.c_size_t, u16p,
    ]
    lib.pt_intersection_count_sorted_u16.restype = ctypes.c_size_t
    lib.pt_intersection_count_sorted_u16.argtypes = [
        u16p, ctypes.c_size_t, u16p, ctypes.c_size_t,
    ]
    lib.pt_intersection_counts_matrix.restype = None
    lib.pt_intersection_counts_matrix.argtypes = [
        u64p, u64p, ctypes.c_size_t, ctypes.c_size_t, i64p,
    ]
    lib.pt_popcount_per_block.restype = None
    lib.pt_popcount_per_block.argtypes = [
        u64p, ctypes.c_size_t, ctypes.c_size_t, i64p,
    ]
    lib.pt_parse_csv_pairs.restype = ctypes.c_longlong
    lib.pt_parse_csv_pairs.argtypes = [
        ctypes.c_void_p,  # buf
        ctypes.c_size_t,  # len
        u64p,             # out a
        u64p,             # out b
        ctypes.c_size_t,  # max_out
    ]
    lib.pt_format_csv_pairs.restype = ctypes.c_longlong
    lib.pt_format_csv_pairs.argtypes = [
        u64p,             # a
        u64p,             # b
        ctypes.c_size_t,  # n
        ctypes.c_void_p,  # out
        ctypes.c_size_t,  # out_cap
    ]
    lib.pt_expand_blocks_v2.restype = ctypes.c_int
    lib.pt_expand_blocks_v2.argtypes = [
        ctypes.c_void_p,  # buf base
        ctypes.c_size_t,  # buf length (bounds-checks file-provided offsets)
        ctypes.c_void_p,  # metas base
        ctypes.POINTER(ctypes.c_uint32),
        i64p,
        ctypes.c_size_t,
        u64p,
    ]


def available() -> bool:
    return _load() is not None


def _u64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _u16p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def popcount(words: np.ndarray) -> int:
    lib = _load()
    if lib is None:
        return int(np.bitwise_count(words).sum())
    words = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib.pt_popcount(_u64p(words), words.size))


def intersection_count_words(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is None:
        return int(np.bitwise_count(a & b).sum())
    a = np.ascontiguousarray(a, dtype=np.uint64)
    b = np.ascontiguousarray(b, dtype=np.uint64)
    return int(lib.pt_intersection_count(_u64p(a), _u64p(b), a.size))


def intersect_sorted_u16(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is None:
        return np.intersect1d(a, b, assume_unique=True)
    a = np.ascontiguousarray(a, dtype=np.uint16)
    b = np.ascontiguousarray(b, dtype=np.uint16)
    out = np.empty(min(a.size, b.size), dtype=np.uint16)
    n = lib.pt_intersect_sorted_u16(_u16p(a), a.size, _u16p(b), b.size, _u16p(out))
    return out[:n]


def intersection_count_sorted_u16(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is None:
        return int(np.intersect1d(a, b, assume_unique=True).size)
    a = np.ascontiguousarray(a, dtype=np.uint16)
    b = np.ascontiguousarray(b, dtype=np.uint16)
    return int(lib.pt_intersection_count_sorted_u16(_u16p(a), a.size, _u16p(b), b.size))


def intersection_counts_matrix(src: np.ndarray, mat: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is None:
        return np.bitwise_count(mat & src[None, :]).sum(axis=1).astype(np.int64)
    src = np.ascontiguousarray(src, dtype=np.uint64)
    mat = np.ascontiguousarray(mat, dtype=np.uint64)
    out = np.empty(mat.shape[0], dtype=np.int64)
    lib.pt_intersection_counts_matrix(
        _u64p(src), _u64p(mat), mat.shape[0], mat.shape[1], _i64p(out)
    )
    return out


def popcount_per_block(words: np.ndarray, words_per_block: int) -> np.ndarray:
    lib = _load()
    n_blocks = words.size // words_per_block
    if lib is None:
        return (
            np.bitwise_count(words.reshape(n_blocks, words_per_block))
            .sum(axis=1)
            .astype(np.int64)
        )
    words = np.ascontiguousarray(words, dtype=np.uint64)
    out = np.empty(n_blocks, dtype=np.int64)
    lib.pt_popcount_per_block(_u64p(words), n_blocks, words_per_block, _i64p(out))
    return out


def parse_csv_pairs(data: bytes):
    """Parse strict ``<u64>,<u64>`` CSV lines into two u64 arrays —
    the import fast path (minutes of per-line Python at 2^30-bit
    imports). Returns (a, b) numpy arrays, or None when the native
    library is absent OR the data deviates in any way (quoting,
    spaces, a third/timestamp field, overflow): the caller re-parses
    with the Python csv path, which owns error reporting."""
    lib = _load()
    if lib is None or len(data) == 0:
        return None
    # accept any buffer (bytes, mmap) without copying
    buf = np.frombuffer(data, dtype=np.uint8)
    # every pair needs >= 4 bytes ("a,b\n"), so this bounds the output
    max_out = buf.size // 4 + 1
    a = np.empty(max_out, dtype=np.uint64)
    b = np.empty(max_out, dtype=np.uint64)
    n = lib.pt_parse_csv_pairs(
        ctypes.c_void_p(buf.ctypes.data), buf.size, _u64p(a), _u64p(b), max_out
    )
    if n < 0:
        return None
    return a[:n], b[:n]


def format_csv_pairs(a: np.ndarray, b: np.ndarray):
    """Format two u64 arrays as ``<a>,<b>\\n`` CSV bytes — the export
    fast path (inverse of parse_csv_pairs). Returns bytes, or None
    when the native library is absent (caller formats in Python)."""
    lib = _load()
    if lib is None:
        return None
    a = np.ascontiguousarray(a, dtype=np.uint64)
    b = np.ascontiguousarray(b, dtype=np.uint64)
    if a.size != b.size:
        return None  # mismatched inputs must not read past b
    out = np.empty(a.size * 42, dtype=np.uint8)
    n = lib.pt_format_csv_pairs(
        _u64p(a), _u64p(b), a.size, ctypes.c_void_p(out.ctypes.data), out.size
    )
    if n < 0:
        return None
    return out[:n].tobytes()


def expand_blocks(
    buf_addr: int,
    buf_len: int,
    metas_addr: int,
    offsets: np.ndarray,
    sel: np.ndarray,
    out: np.ndarray,
) -> bool:
    """Expand selected base containers (by index) into dense 1024-word
    blocks, decoding straight from the mmapped file. ``out`` must be a
    caller-zeroed C-contiguous u64[len(sel), 1024]. Returns False when
    the native library is unavailable OR the kernel detects a payload
    running past ``buf_len`` (truncated/corrupt file) — either way the
    caller takes the Python decode path, which raises a proper error."""
    lib = _load()
    if lib is None:
        return False
    sel = np.ascontiguousarray(sel, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.uint32)
    rc = lib.pt_expand_blocks_v2(
        ctypes.c_void_p(buf_addr),
        buf_len,
        ctypes.c_void_p(metas_addr),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        _i64p(sel),
        sel.size,
        _u64p(out),
    )
    if rc != 0:
        out[:] = 0  # discard any partial expansion
        return False
    return True
