"""Generation-stamped query result cache — bounded, byte-accounted LRU.

The serving pipeline's singleflight only coalesces *concurrent*
duplicates; repeated workloads (dashboards, Zipf-skewed TopN traffic)
re-pay full executor cost on every arrival. This cache closes that gap
the way prefix/KV caches do for inference serving: results persist
across requests, and validity is *proved* rather than guessed —

* an entry is keyed by ``(index, canonical subtree hash, shard set,
  exec-option bits)`` — the index name matters: the cache is
  process-wide and generation vectors carry no index identity, so
  same-schema indexes would otherwise collide — and stamped with the
  **fragment-generation vector** observed
  before its build: one ``(field, view, shard, generation)`` entry per
  fragment that could contribute to the result;
* a lookup recomputes the current vector and serves the entry only on
  an exact match. Every write path (set/clear/bulk import/value
  import/block merge/restore) already bumps the fragment generation
  (core/fragment.py, PR 3), so invalidation is free and exact — no TTL
  heuristics, no stale reads;
* the vector is captured BEFORE the build, so a write racing a build
  can only over-invalidate (the entry records a pre-write vector and
  mismatches on the next lookup), never serve post-write data as
  pre-write or vice versa.

Values are stored *encoded* (per-shard row segments for bitmap results,
scalars for Count/Sum/Min/Max, id/count pairs for TopN) and decoded
into fresh objects on every hit, so callers can mutate what they get
back (key translation, cross-shard merges) without corrupting the
cache. Builds are singleflighted per key; ``epoch_reset`` (wired to the
device-health restore path next to ``DeviceStager.reset_after_wedge``)
drops everything and fences out builders that started before the wedge.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

from pilosa_tpu.analysis.locks import OrderedLock
from pilosa_tpu.utils import metrics

DEFAULT_MAX_BYTES = 256 << 20


class _Entry:
    __slots__ = ("value", "nbytes", "genvec")

    def __init__(self, value, nbytes: int, genvec) -> None:
        self.value = value
        self.nbytes = nbytes
        self.genvec = genvec


# -- value codec ------------------------------------------------------------
# Encoded forms are immutable-by-convention tuples; Row segments are
# cloned INTO the cache at insert and OUT of it on every hit, so no
# live object is ever shared between the cache and a caller.


def encode_result(result) -> Optional[tuple[tuple, int]]:
    """(encoded, nbytes) or None when the result type isn't cacheable.
    nbytes is an accounting estimate (LRU budget), not an allocation."""
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.executor.executor import ValCount

    if isinstance(result, Row):
        segs = tuple(
            (shard, seg.clone()) for shard, seg in sorted(result.segments.items())
        )
        nbytes = 128 + sum(64 + 8 * seg.count() for _, seg in segs)
        return ("row", segs), nbytes
    if isinstance(result, bool):
        return None  # write results are never cached
    if isinstance(result, int):
        return ("int", result), 64
    if isinstance(result, ValCount):
        return ("valcount", (result.val, result.count)), 64
    if result is None:
        return ("none", None), 32
    if isinstance(result, list) and all(
        isinstance(p, dict) and set(p) == {"id", "count"} for p in result
    ):
        pairs = tuple((p["id"], p["count"]) for p in result)
        return ("pairs", pairs), 64 + 16 * len(pairs)
    return None


def decode_result(enc: tuple):
    """A FRESH result object from an encoded entry."""
    from pilosa_tpu.core.row import Row
    from pilosa_tpu.executor.executor import ValCount

    tag, payload = enc
    if tag == "row":
        r = Row()
        for shard, seg in payload:
            r.segments[shard] = seg.clone()
        return r
    if tag == "int":
        return payload
    if tag == "valcount":
        return ValCount(payload[0], payload[1])
    if tag == "none":
        return None
    if tag == "pairs":
        return [{"id": i, "count": c} for i, c in payload]
    raise ValueError(f"unknown plan-cache entry tag: {tag!r}")


class PlanCache:
    """Process-wide result cache. One instance per server (the executor
    holds it); bare executors default to none, so tests and benches opt
    in explicitly."""

    def __init__(
        self,
        max_bytes: int = DEFAULT_MAX_BYTES,
        min_cost: float = 0.0,
    ) -> None:
        self.max_bytes = int(max_bytes)
        # builds cheaper than this (seconds) aren't stored: caching a
        # 50 us Count costs more in bookkeeping + eviction pressure
        # than it saves. 0 caches everything (the tested default).
        self.min_cost = float(min_cost)
        self._mu = OrderedLock("plancache.mu")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._building: dict[tuple, threading.Event] = {}
        self.bytes = 0
        self.epoch = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.inserts = 0

    # -- lookups -------------------------------------------------------------

    def _lookup_locked(self, key, genvec) -> Optional[_Entry]:
        """Entry for ``key`` valid at ``genvec``, counting hit or
        invalidation; None on absence (NOT counted — probe-only callers
        must not skew the miss rate). Caller holds _mu."""
        e = self._entries.get(key)
        if e is None:
            return None
        if e.genvec != genvec:
            self._remove_locked(key, e)
            self.invalidations += 1
            metrics.count(metrics.PLANCACHE_INVALIDATIONS)
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        metrics.count(metrics.PLANCACHE_HITS)
        return e

    def contains(self, key) -> bool:
        """Presence probe WITHOUT generation validation — a cheap
        pre-filter so tree walks don't compute a generation vector per
        node. A True answer may still invalidate at lookup time."""
        with self._mu:
            return key in self._entries

    def get(self, key, genvec_fn: Callable[[], tuple]) -> Optional[Any]:
        """Probe-only lookup: decoded value on a valid hit, else None
        (no miss counted, no build). The planner uses this to feed
        already-cached subtree rows into parent ops without forcing a
        build of every unique subtree it walks."""
        if not self.contains(key):
            return None
        genvec = genvec_fn()
        with self._mu:
            e = self._lookup_locked(key, genvec)
            if e is None:
                return None
            value = e.value
        return decode_result(value)

    def get_or_build(
        self, key, genvec_fn: Callable[[], tuple], build: Callable[[], Any]
    ) -> Any:
        """Serve ``key`` from cache or build it exactly once across
        concurrent callers (singleflight). The builder's exceptions
        propagate to the leader; followers retry (and usually become
        the next leader) rather than inheriting a failure that might
        have been the leader's deadline, not theirs."""
        while True:
            genvec = genvec_fn()
            with self._mu:
                e = self._lookup_locked(key, genvec)
                if e is not None:
                    value = e.value
                    return decode_result(value)
                ev = self._building.get(key)
                if ev is None:
                    ev = self._building[key] = threading.Event()
                    leader = True
                else:
                    leader = False
            if not leader:
                ev.wait()
                continue
            try:
                epoch0 = self.epoch
                t0 = time.monotonic()
                result = build()
                cost = time.monotonic() - t0
                self._maybe_insert(key, result, genvec, cost, epoch0)
                return result
            finally:
                # miss accounting lives here, under _mu, so concurrent
                # leaders don't race the increment and a build that
                # raises still counts as a miss (it did the work)
                with self._mu:
                    self.misses += 1
                    self._building.pop(key, None)
                metrics.count(metrics.PLANCACHE_MISSES)
                ev.set()

    # -- inserts / eviction --------------------------------------------------

    def put(self, key, genvec, result, cost: float = 0.0, epoch0=None) -> None:
        """Insert a result computed OUTSIDE the singleflight (the fused
        whole-query path executes many calls in one launch, so there is
        no per-call build closure to route through ``get_or_build``).
        ``genvec`` must be the vector captured BEFORE the fused build —
        preserving the over-invalidation-only race direction documented
        in the module docstring — and ``epoch0`` the epoch observed then
        (defaults to the current epoch), so a device wedge mid-build
        fences the insert exactly as it fences ``get_or_build``'s."""
        self._maybe_insert(
            key, result, genvec, cost, self.epoch if epoch0 is None else epoch0
        )

    def _maybe_insert(self, key, result, genvec, cost: float, epoch0: int) -> None:
        if cost < self.min_cost:
            return
        enc = encode_result(result)
        if enc is None:
            return
        value, nbytes = enc
        if nbytes > self.max_bytes:
            return
        with self._mu:
            if self.epoch != epoch0:
                # an epoch reset (device wedge) happened mid-build: the
                # result may reflect pre-wedge device state — drop it
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = _Entry(value, nbytes, genvec)
            self.bytes += nbytes
            self.inserts += 1
            while self.bytes > self.max_bytes and self._entries:
                k, e = self._entries.popitem(last=False)
                self.bytes -= e.nbytes
                self.evictions += 1
                metrics.count(metrics.PLANCACHE_EVICTIONS)
            metrics.gauge(metrics.PLANCACHE_BYTES, self.bytes)

    def _remove_locked(self, key, e: _Entry) -> None:
        del self._entries[key]
        self.bytes -= e.nbytes
        metrics.gauge(metrics.PLANCACHE_BYTES, self.bytes)

    # -- lifecycle -----------------------------------------------------------

    def epoch_reset(self) -> None:
        """Drop everything and fence out in-flight builders. Wired next
        to ``DeviceStager.reset_after_wedge`` (executor device-health
        restore) — results computed by a wedged accelerator must not
        outlive it — and to the recalculate-caches admin op, whose rank
        reorders can change TopN candidate walks without a generation
        bump."""
        with self._mu:
            self._entries.clear()
            self.bytes = 0
            self.epoch += 1
            metrics.gauge(metrics.PLANCACHE_BYTES, 0)

    def stats(self) -> dict:
        """The /debug/plancache snapshot."""
        with self._mu:
            total = self.hits + self.misses
            return {
                "enabled": True,
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "min_cost": self.min_cost,
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": round(self.hits / total, 4) if total else None,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "inserts": self.inserts,
                "building": len(self._building),
                "epoch": self.epoch,
            }


class DevicePlanCache:
    """HBM-resident companion to PlanCache for bitmap-valued subtrees:
    entries hold the packed u32[S, W] device stack a ``__cached``
    placeholder lowers to, so a plan-cache hit on the device path stops
    round-tripping through host Row decode + re-pack + re-upload
    (``executor._cached_words`` per shard) — the device re-ingesting
    what it just produced.

    Same validity model as PlanCache — generation-vector stamped at
    insert, exact-match validated at lookup, so every write path
    invalidates for free — but byte-accounted against a dedicated HBM
    budget (``plan-cache-device-bytes``) with LRU eviction: device
    memory is the scarcer resource and is shared with the staging
    cache. ``epoch_reset`` is wired to the device-health restore next
    to ``DeviceStager.reset_after_wedge``: arrays produced by a wedged
    runtime must not outlive it. Values are immutable by contract
    (device arrays are never written in place), so hits return the
    resident array without a copy."""

    def __init__(self, max_bytes: int) -> None:
        self.max_bytes = int(max_bytes)
        self._mu = OrderedLock("plancache.device_mu")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.bytes = 0
        self.epoch = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.inserts = 0
        # process-wide HBM governor (executor/hbm.py): when attached,
        # max_bytes becomes this cache's tenant SHARE of the global
        # ledger and the cache is the FIRST relief tier — pure derived
        # state, cheapest thing on the chip to rebuild
        self.governor = None

    def set_governor(self, governor) -> None:
        self.governor = governor
        if governor is None:
            return
        governor.register(
            "device_cache",
            share_bytes=self.max_bytes,
            evict_fn=self._evict_lru,
            tier=0,
        )
        with self._mu:
            current = self.bytes
        if current:
            governor.reserve("device_cache", current)

    @staticmethod
    def _index_of(key) -> str:
        """The tenant index a cache key belongs to — device-cache keys
        are ``(index, subtree_hash, shards)`` (executor.py), so the
        first element is the attribution handle for per-tenant HBM
        quotas. Defensive for non-conforming keys (direct tests)."""
        if isinstance(key, tuple) and key and isinstance(key[0], str):
            return key[0]
        return ""

    def _evict_lru(self, need: int, prefer=None) -> int:
        """Governor relief tier 0: drop LRU entries until ``need``
        bytes are freed. Called WITHOUT the governor lock held.

        ``prefer`` narrows eviction to the listed tenant indexes
        (quota enforcement: an over-quota tenant sheds only its own
        plans); None keeps the classic global LRU sweep."""
        freed = 0
        freed_by: dict = {}
        with self._mu:
            if prefer is not None:
                want = set(prefer)
                victims = [
                    k for k in self._entries if self._index_of(k) in want
                ]
                for k in victims:
                    if freed >= need:
                        break
                    e = self._entries.pop(k)
                    self.bytes -= e.nbytes
                    freed += e.nbytes
                    freed_by[self._index_of(k)] = (
                        freed_by.get(self._index_of(k), 0) + e.nbytes
                    )
                    self.evictions += 1
                    metrics.count(metrics.PLANCACHE_DEVICE_EVICTIONS)
            else:
                while freed < need and self._entries:
                    k, e = self._entries.popitem(last=False)
                    self.bytes -= e.nbytes
                    freed += e.nbytes
                    idx = self._index_of(k)
                    freed_by[idx] = freed_by.get(idx, 0) + e.nbytes
                    self.evictions += 1
                    metrics.count(metrics.PLANCACHE_DEVICE_EVICTIONS)
            if freed:
                metrics.gauge(metrics.PLANCACHE_DEVICE_BYTES, self.bytes)
        if freed and self.governor is not None:
            for idx, n in freed_by.items():
                self.governor.release("device_cache", n, index=idx)
        return freed

    def get(self, key, genvec_fn: Callable[[], tuple]):
        """The resident device array for ``key`` valid at the CURRENT
        generation vector, or None (miss / invalidated). Probe-and-pack
        is the caller's job — uploads are too heavyweight to
        singleflight here, and concurrent misses for one key just
        upload the same immutable content twice."""
        genvec = genvec_fn()
        freed = 0
        try:
            with self._mu:
                e = self._entries.get(key)
                if e is None:
                    self.misses += 1
                    return None
                if e.genvec != genvec:
                    del self._entries[key]
                    self.bytes -= e.nbytes
                    freed = e.nbytes
                    self.invalidations += 1
                    self.misses += 1
                    metrics.count(metrics.PLANCACHE_INVALIDATIONS)
                    metrics.gauge(metrics.PLANCACHE_DEVICE_BYTES, self.bytes)
                    return None
                self._entries.move_to_end(key)
                self.hits += 1
                metrics.count(metrics.PLANCACHE_DEVICE_HITS)
                return e.value
        finally:
            if freed and self.governor is not None:
                self.governor.release(
                    "device_cache", freed, index=self._index_of(key)
                )

    def put(self, key, genvec, value, nbytes: int, epoch0=None) -> None:
        """Insert a device array stamped with the generation vector
        captured BEFORE its content was materialized (same race
        direction as PlanCache: a write racing the pack can only
        over-invalidate). ``epoch0`` fences inserts built before a
        device wedge."""
        nbytes = int(nbytes)
        if nbytes > self.max_bytes:
            return
        # reserve OUTSIDE _mu: the governor's relief sweep may evict
        # cold stager blocks, and those callbacks take the stager lock
        # (lock order: tenant lock → governor lock, never the reverse)
        gov = self.governor
        tenant = self._index_of(key)
        if gov is not None:
            gov.reserve("device_cache", nbytes, index=tenant)
        # per-tenant return ledger: evicted entries credit back to the
        # index that owned them, not the inserting tenant
        gov_return: dict = {}
        returned = 0
        with self._mu:
            if epoch0 is not None and self.epoch != epoch0:
                gov_return[tenant] = nbytes
            else:
                old = self._entries.pop(key, None)
                if old is not None:
                    self.bytes -= old.nbytes
                    gov_return[tenant] = gov_return.get(tenant, 0) + old.nbytes
                    returned += old.nbytes
                self._entries[key] = _Entry(value, nbytes, genvec)
                self.bytes += nbytes
                self.inserts += 1
                while (
                    self.bytes > self.max_bytes
                    or (gov is not None and gov.over_budget() > returned)
                ) and self._entries:
                    k, e = self._entries.popitem(last=False)
                    self.bytes -= e.nbytes
                    idx = self._index_of(k)
                    gov_return[idx] = gov_return.get(idx, 0) + e.nbytes
                    returned += e.nbytes
                    self.evictions += 1
                    metrics.count(metrics.PLANCACHE_DEVICE_EVICTIONS)
                metrics.gauge(metrics.PLANCACHE_DEVICE_BYTES, self.bytes)
        if gov is not None:
            for idx, n in gov_return.items():
                gov.release("device_cache", n, index=idx)

    def epoch_reset(self) -> None:
        """Drop every resident array and fence out packs that started
        before the wedge (their epoch0 no longer matches)."""
        with self._mu:
            self._entries.clear()
            self.bytes = 0
            self.epoch += 1
            metrics.gauge(metrics.PLANCACHE_DEVICE_BYTES, 0)
        # the epoch fence extends to the governor ledger (ISSUE 14)
        if self.governor is not None:
            self.governor.reset("device_cache")

    def stats(self) -> dict:
        """Merged into the /debug/fusion snapshot."""
        with self._mu:
            total = self.hits + self.misses
            return {
                "enabled": True,
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": round(self.hits / total, 4) if total else None,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "inserts": self.inserts,
                "epoch": self.epoch,
            }
