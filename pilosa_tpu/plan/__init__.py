"""Query planning (L3.5) — the layer between parsing and execution.

Three parts (ISSUE 4):

* ``canon``   — PQL AST canonicalization: flatten nested Union/Intersect,
                sort commutative operands, normalize argument order, and
                derive a stable content hash per subtree, so
                ``Intersect(Row(a), Row(b))`` and
                ``Intersect(Row(b), Row(a))`` share one identity.
* ``cache``   — a bounded (byte-accounted, LRU) result cache keyed by
                ``(canonical hash, shard set, fragment-generation
                vector)``: a cached entry is valid iff every
                contributing fragment's generation still matches, so
                every write path invalidates for free through the
                generation bumps PR 3 introduced — no TTLs.
* ``planner`` — cache keys/generation vectors for the executor, plus
                intra-query and intra-gang common-subexpression
                elimination: repeated subtrees across the calls of one
                (possibly pipeline-combined) query execute once, and
                cached subtree rows feed back into parent ops as staged
                inputs.
"""

from pilosa_tpu.plan.cache import PlanCache
from pilosa_tpu.plan.canon import call_hash, canonicalize, query_signature

__all__ = ["PlanCache", "call_hash", "canonicalize", "query_signature"]
