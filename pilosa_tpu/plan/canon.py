"""PQL AST canonicalization — one stable identity per query subtree.

The executor's result cache, the planner's common-subexpression
elimination, and the serving pipeline's singleflight all need the same
primitive: two call trees that must produce identical results should
map to the same key. Raw query text is a bad key — PQL's commutative
operators admit arbitrarily many spellings of one computation
(``Intersect(Row(a), Row(b))`` vs ``Intersect(Row(b), Row(a))``,
``Union(a, Union(b, c))`` vs ``Union(a, b, c)``, permuted option
order). Canonicalization rewrites to a normal form:

* **flatten** nested ``Union``/``Intersect`` into their parent (both
  are associative);
* **sort** the operands of commutative ops (``Union``, ``Intersect``,
  ``Xor``) by their canonical serialization;
* **normalize** argument order (sorted keys) and literal spelling
  (type-tagged encoding, so ``1`` and ``1.0`` and ``"1"`` stay
  distinct).

``Difference`` is NOT commutative and is left untouched beyond child
recursion; operands are never deduplicated (``Xor(a, a)`` is empty, not
``a``). The canonical serialization is hashed (sha256) into a compact
content key; the ``__cached`` placeholder nodes the planner substitutes
hash as the subtree they replaced, so a rewritten tree keeps the
original tree's identity.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from threading import Lock
from typing import Optional

from pilosa_tpu.pql.ast import Call, Condition, Query

# associative ops whose same-name children splice into the parent
FLATTEN = ("Union", "Intersect")
# commutative ops whose operand order is irrelevant to the result
COMMUTATIVE = ("Union", "Intersect", "Xor")

# the planner's substitution placeholder (plan/planner.py): carries the
# canonical hash of the subtree it replaced in args["_h"], so rewritten
# trees serialize — and therefore hash — exactly as the original
CACHED_CALL = "__cached"


def _enc_value(v) -> str:
    """Type-tagged literal encoding: distinct types never collide
    (True vs 1, 1 vs 1.0 vs "1"), and strings are length-prefixed so a
    crafted string can't forge another encoding's shape."""
    if v is None:
        return "n"
    if isinstance(v, bool):
        return "b1" if v else "b0"
    if isinstance(v, int):
        return f"i{v}"
    if isinstance(v, float):
        return f"f{v!r}"
    if isinstance(v, str):
        return f"s{len(v)}:{v}"
    if isinstance(v, list):
        return "l[" + ",".join(_enc_value(x) for x in v) + "]"
    if isinstance(v, Condition):
        return f"c({v.op}){_enc_value(v.value)}"
    return f"o{v!r}"


def _canon_children(c: Call) -> list[Call]:
    """Children with nested same-op Union/Intersect spliced in."""
    if c.name not in FLATTEN:
        return c.children
    out: list[Call] = []
    for ch in c.children:
        if ch.name == c.name and ch.children and not ch.args:
            out.extend(_canon_children(ch))
        else:
            out.append(ch)
    return out


def canonicalize(c: Call) -> Call:
    """A NEW canonical Call tree (input untouched): nested
    Union/Intersect flattened, commutative operands sorted. Useful for
    inspection/debugging; keys should use call_hash, which canonicalizes
    implicitly."""
    kids = [canonicalize(ch) for ch in _canon_children(c)]
    if c.name in COMMUTATIVE:
        kids.sort(key=call_hash)
    return Call(c.name, dict(c.args), kids)


def call_hash(c: Call) -> str:
    """Stable content hash of one call subtree, invariant under
    operand order (commutative ops), Union/Intersect nesting, and
    argument order.

    Hashing is bottom-up — a node hashes over its children's HASHES,
    not their serializations — so a planner-substituted ``__cached``
    placeholder (which carries the replaced subtree's hash) is exactly
    transparent: the rewritten parent keeps the original tree's
    identity."""
    if c.name == CACHED_CALL:
        return str(c.args["_h"])
    kid_hashes = [call_hash(k) for k in _canon_children(c)]
    if c.name in COMMUTATIVE:
        kid_hashes.sort()
    args = ";".join(f"{k}={_enc_value(c.args[k])}" for k in sorted(c.args))
    s = f"{c.name}({args}|{','.join(kid_hashes)})"
    return hashlib.sha256(s.encode()).hexdigest()[:24]


def query_hash(q: Query) -> str:
    """Whole-query hash: per-call hashes joined IN ORDER (results are
    positional, so call order is part of the identity)."""
    return hashlib.sha256(
        "|".join(call_hash(c) for c in q.calls).encode()
    ).hexdigest()[:24]


# -- serving-pipeline signature ---------------------------------------------

# text -> signature memo: dashboards repeat byte-identical query texts,
# so the hot path usually skips the re-parse. Bounded LRU under a lock
# (the handler calls this from many transport threads).
_SIG_MAX = 1024
_sig_lru: "OrderedDict[str, Optional[str]]" = OrderedDict()
_sig_mu = Lock()


def query_signature(text: str) -> Optional[str]:
    """Canonical signature for a query TEXT, or None when it doesn't
    parse (the caller falls back to the raw text so a syntax error
    still reaches the executor and 400s there). Used by the serving
    pipeline's singleflight so argument-order-permuted duplicates
    coalesce (ISSUE 4 satellite 1)."""
    with _sig_mu:
        if text in _sig_lru:
            _sig_lru.move_to_end(text)
            return _sig_lru[text]
    from pilosa_tpu.pql import parse

    try:
        sig: Optional[str] = "pqh:" + query_hash(parse(text))
    except Exception:
        sig = None
    with _sig_mu:
        _sig_lru[text] = sig
        _sig_lru.move_to_end(text)
        while len(_sig_lru) > _SIG_MAX:
            _sig_lru.popitem(last=False)
    return sig
