"""Planner — cache keys, generation vectors, and common-subexpression
elimination for the executor.

Two jobs sit here, both keyed by canonical subtree hashes (plan/canon):

* **Whole-call cache keys.** ``call_cache_key`` decides whether a call's
  result may be cached at all (it must depend only on fragment state —
  attr-store reads have no generation counter, so anything touching
  them is uncacheable) and, when it may, derives the cache key plus a
  generation-vector thunk covering every fragment that could contribute.
  The vector enumerates EVERY view of each referenced field over the
  query's shard set: coarser than strictly necessary (a write to a
  field's BSI view invalidates a standard-view entry on the same
  field), but exact in the direction that matters — no write that can
  change the result is ever missed, including time-quantum fan-out and
  view creation.

* **CSE rewrite.** ``rewrite_for_cse`` walks the calls of one query
  (which, via the pipeline's cross-request combiner, may be a whole
  gang of coalesced HTTP requests): bitmap subtrees that are already
  cached — or that repeat within the query — are replaced by
  ``__cached`` placeholder nodes carrying the materialized per-shard
  rows. The executor evaluates a placeholder by reading those rows
  (CPU path) or packing them into device words (device path), so
  ``Count(Intersect(hot, cold))`` recomputes only the cold leg.
  Placeholders hash as the subtree they replaced (canon.CACHED_CALL),
  so a rewritten call keeps its original cache identity.

Local-only: substituted trees are never serialized, so the executor
gates all of this behind single-node / remote-leg execution — on a
cluster the coordinator's calls travel to shard owners as text and each
owner runs its own planner against its own fragments.
"""

from __future__ import annotations

from typing import Callable, Optional

from pilosa_tpu.pql.ast import Call
from pilosa_tpu.plan.canon import CACHED_CALL, call_hash

# bitmap-valued calls the CSE rewrite may substitute
BITMAP_CALLS = ("Row", "Union", "Intersect", "Difference", "Xor", "Range")
# compound calls whose cacheability is their children's
_COMPOUND = ("Union", "Intersect", "Difference", "Xor", "Count")


def subtree_fields(c: Call) -> Optional[frozenset]:
    """Field names this subtree reads, or None when the result can
    depend on state the generation vector cannot see (attr stores,
    write calls, unknown call names, malformed args — let the executor
    produce the error uncached)."""
    name = c.name
    if name == CACHED_CALL:
        return c.args.get("_fields")
    try:
        if name in _COMPOUND:
            fields: set = set()
            for ch in c.children:
                f = subtree_fields(ch)
                if f is None:
                    return None
                fields |= f
            return frozenset(fields)
        if name in ("Row", "Range"):
            if c.children:
                return None
            return frozenset([c.field_arg()])
        if name == "TopN":
            if c.args.get("attrName") or c.args.get("attrValues"):
                return None  # attr filters read stores with no generation
            field, ok = c.string_arg("_field")
            if not ok:
                return None
            fields = {field}
            for ch in c.children:
                f = subtree_fields(ch)
                if f is None:
                    return None
                fields |= f
            return frozenset(fields)
        if name in ("Sum", "Min", "Max"):
            field, ok = c.string_arg("field")
            if not ok:
                return None
            fields = {field}
            for ch in c.children:
                f = subtree_fields(ch)
                if f is None:
                    return None
                fields |= f
            return frozenset(fields)
        if name == "Rows":
            field, ok = c.string_arg("_field")
            if not ok:
                return None
            return frozenset([field])
        if name == "GroupBy":
            # dims (Rows), aggregate (bare Sum) and filter are all
            # children — their union covers every fragment read
            if not c.children:
                return None
            fields = set()
            for ch in c.children:
                f = subtree_fields(ch)
                if f is None:
                    return None
                fields |= f
            return frozenset(fields)
        if name in ("Distinct", "Percentile"):
            field, ok = c.string_arg("field")
            if not ok:
                return None
            fields = {field}
            for ch in c.children:
                f = subtree_fields(ch)
                if f is None:
                    return None
                fields |= f
            return frozenset(fields)
    except (ValueError, TypeError):
        return None
    return None  # writes / unknown calls


def extract_row_operands(calls) -> list[tuple[str, int]]:
    """(field, row_id) for every plain Row leaf under ``calls`` — the
    plan-driven prefetcher's staging list (executor/tiering.py). Only
    leaves the stager can promote as a standard-view row block qualify;
    malformed or range-style Rows are skipped, never raised."""
    out: list[tuple[str, int]] = []

    def walk(c: Call) -> None:
        if c.name == "Row" and not c.children:
            try:
                field = c.field_arg()
                row_id, ok = c.uint_arg(field)
            except (ValueError, TypeError):
                return
            if ok:
                out.append((field, int(row_id)))
            return
        if c.name == "Rows":
            # GroupBy dimension with explicit ids — each id is a
            # standard-view row block the stager can promote ahead of
            # the segmented-reduction launch. Discovered dims (no ids=)
            # are unknowable before execution; skip them.
            try:
                field, ok = c.string_arg("_field")
                ids, has_ids = c.uint_slice_arg("ids")
            except (ValueError, TypeError):
                return
            if ok and has_ids:
                out.extend((field, int(r)) for r in ids)
            return
        for ch in c.children:
            walk(ch)

    for c in calls:
        walk(c)
    return out


def generation_vector(holder, index: str, fields, shards) -> tuple:
    """((field, view, shard, generation), ...) for every EXISTING
    fragment of the referenced fields over the shard set. A write bumps
    its fragment's generation; a restore bumps it; a new fragment or
    view changes the vector's shape — all read as a mismatch by the
    cache. Sorted, so the vector is a pure function of state."""
    try:
        idx = holder.index(index)
        if idx is None:
            return ("noindex",)
        vec = []
        for fname in sorted(fields):
            fld = idx.field(fname)
            if fld is None:
                vec.append((fname, None))
                continue
            for vname in sorted(fld.views):
                view = fld.views.get(vname)
                if view is None:
                    continue  # deleted between the sort and the read
                frags = view.fragments
                for s in shards:
                    frag = frags.get(s)
                    if frag is not None:
                        vec.append((fname, vname, s, frag.generation))
        return tuple(vec)
    except (RuntimeError, KeyError):
        # a concurrent schema mutation raced the dict walk: answer with
        # a vector that can never match, so this lookup misses instead
        # of guessing
        return ("racing", id(object()))


def _opt_bits(opt, attrless: bool) -> tuple:
    """The ExecOptions bits that can change a call's raw result."""
    return (bool(opt.remote), attrless or bool(opt.exclude_row_attrs))


def call_cache_key(
    executor, index: str, c: Call, shards, opt
) -> Optional[tuple[tuple, Callable[[], tuple]]]:
    """(cache key, generation-vector thunk) for a whole top-level call,
    or None when the call is uncacheable."""
    fields = subtree_fields(c)
    if fields is None:
        return None
    if c.name == "Row" and not opt.exclude_row_attrs:
        # top-level Row() calls get row attrs attached
        # (executor._execute_bitmap_call); attr stores have no
        # generation counter, so such results must not be cached
        fld = executor.holder.field(index, next(iter(fields)))
        if fld is not None and fld.row_attr_store is not None:
            return None
    key = (index, call_hash(c), tuple(shards), _opt_bits(opt, attrless=False))
    holder = executor.holder
    return key, lambda: generation_vector(holder, index, fields, shards)


def subtree_cache_key(index: str, h: str, shards_t: tuple, opt) -> tuple:
    """Key for a SUBTREE row entry: always attr-less (nested bitmap
    nodes never attach attrs), so top-level bitmap calls that exclude
    attrs and nested occurrences of the same subtree share one entry.
    The index name is part of the key (as in call_cache_key): the
    PlanCache is process-wide and generation vectors carry no index
    identity, so same-schema indexes with matching generation counts
    would otherwise serve each other's results."""
    return (index, h, shards_t, _opt_bits(opt, attrless=True))


def rewrite_for_cse(executor, index: str, calls: list, shards, opt) -> list:
    """Substitute cached / repeated bitmap subtrees with ``__cached``
    placeholder nodes (intra-query + intra-gang CSE). Input calls are
    never mutated; untouched calls pass through identically."""
    pc = executor.plan_cache
    shards_t = tuple(shards)
    holder = executor.holder

    # (hash, fields) per node, memoized by object identity — the scan
    # and substitution passes each visit every node once
    memo: dict[int, Optional[tuple]] = {}

    def info(node: Call) -> Optional[tuple]:
        k = id(node)
        if k not in memo:
            fields = subtree_fields(node)
            memo[k] = None if fields is None else (call_hash(node), fields)
        return memo[k]

    # pass 1: occurrence counts of cacheable bitmap subtrees (all
    # depths; a subtree repeated inside two distinct parents still
    # shares). Top-level calls are the whole-call cache's job.
    counts: dict[str, int] = {}

    def scan(node: Call, top: bool) -> None:
        if not top and node.name in BITMAP_CALLS:
            i = info(node)
            if i is not None:
                counts[i[0]] = counts.get(i[0], 0) + 1
        for ch in node.children:
            scan(ch, False)

    for c in calls:
        scan(c, True)

    from pilosa_tpu.core.row import Row
    from pilosa_tpu.executor.executor import ExecOptions

    sub_opt = ExecOptions(
        remote=opt.remote,
        exclude_row_attrs=True,
        exclude_columns=opt.exclude_columns,
    )
    resolved: dict[str, tuple] = {}  # h -> (Row, frozen genvec)

    def resolve(node: Call, h: str, fields) -> Optional[tuple]:
        hit = resolved.get(h)
        if hit is not None:
            return hit
        key = subtree_cache_key(index, h, shards_t, opt)
        # Freeze the vector BEFORE resolving: the device plan cache
        # stamps the packed u32 stack of this Row with g0, and a stamp
        # taken after a concurrent write could certify stale content as
        # fresh. Frozen, a racing write can only over-invalidate.
        g0 = generation_vector(holder, index, fields, shards)
        gv = lambda: g0
        if counts.get(h, 0) >= 2:
            # repeated within this query/gang: build once, share
            row = pc.get_or_build(
                key,
                gv,
                lambda: executor._execute_bitmap_call(index, node, shards, sub_opt),
            )
        else:
            row = pc.get(key, gv)  # probe-only: feed hot legs back in
        if isinstance(row, Row):
            hit = (row, g0)
            resolved[h] = hit
            return hit
        return None

    def substitute(node: Call, top: bool) -> Call:
        if not top and node.name in BITMAP_CALLS:
            i = info(node)
            if i is not None:
                h, fields = i
                hit = resolve(node, h, fields)
                if hit is not None:
                    row, g0 = hit
                    return Call(
                        CACHED_CALL,
                        args={
                            "_h": h,
                            "_row": row,
                            "_fields": fields,
                            # for the device-resident plan cache:
                            # the frozen stamp and a fresh-vector thunk
                            # (canon.call_hash ignores extra args here)
                            "_genvec": g0,
                            "_gv": lambda: generation_vector(
                                holder, index, fields, shards
                            ),
                        },
                    )
        if node.children:
            new = [substitute(ch, False) for ch in node.children]
            if any(a is not b for a, b in zip(new, node.children)):
                return Call(node.name, node.args, new)
        return node

    out = []
    for c in calls:
        i = info(c)
        if i is not None and pc.contains(
            (index, i[0], shards_t, _opt_bits(opt, attrless=False))
        ):
            # the whole call is (probably) cached — the _execute_call
            # hook will serve it; descending here would waste probes
            out.append(c)
            continue
        out.append(substitute(c, True))
    return out


def resolve_keys(executor, index: str, idx, calls) -> None:
    """Keyed-surface entry point: resolve string keys to integer ids
    in-place across every call tree BEFORE canonicalization, so the
    CSE hashes and plan-cache keys above only ever see resolved ids —
    two spellings of the same keyed subtree share one cache entry, and
    re-keying an id can never serve a stale cached row. Delegates to
    the translate subsystem (translate/resolve.py)."""
    from pilosa_tpu.translate import resolve

    ts = executor.translate_store
    if ts is None:
        return
    for c in calls:
        resolve.resolve_call(ts, index, idx, c)
