"""Continuous-batching async device dispatch — the executor↔device
boundary as a persistent feed loop (ISSUE 8).

The round-7 profile was blunt: the chip answers thousands of TopN qps
batched while serving delivered ~123 at c32, because every query still
parked a thread on a blocking dispatch and only *identical* queries
ever shared a launch (pipeline gangs) or *homogeneous* TopN scoring
coalesced (BatchedScorer). TPU/GPU inference servers close exactly
this gap with continuous batching (Orca/vLLM iteration-level
scheduling): one persistent dispatch loop owns the device and admits
whatever is queued into the next wave, so the device never idles
between launches. This module is that loop for bitmap queries:

* **Submit, don't block.** ``Executor.execute`` hands eligible local
  reads to ``submit()`` and gets a future back; the calling thread
  waits on the future instead of occupying the executor. Ineligible
  work (writes, gang/multihost, cluster fan-out, remote legs, traced
  queries, ``serial``) keeps the old inline path — the PR 5/6 gang
  determinism contract holds because gang execution is ``serial`` and
  never reaches the engine.
* **Heterogeneous waves.** The loop drains up to ``max_wave`` queued
  items per wave. Within a wave, items group by execution context
  (index, shard set, exec-opt bits) and dedup by canonical plan
  signature (plan/canon.py) — wave-level singleflight, so duplicate
  plans (including argument-order permutations) execute once and share
  results. Each group then becomes ONE combined multi-call query
  through ``executor._execute``: *mixed* TopN/Count/Sum/chain plans
  ride one wave, fan through the executor's read pool together, and
  the BatchedScorer / stacked scorers coalesce their kernel work into
  batched launches — generalizing both the pipeline's identical-query
  gangs and the scorer's homogeneous micro-batches.
* **Overlap.** ``max_inflight`` waves execute concurrently (double /
  triple buffering at the serving layer): while wave N computes, the
  loop is already building wave N+1 and firing advisory stage-ahead
  warms (``stager.stage_ahead``) so operand uploads overlap kernel
  execution, and wave N−1's waiters consume results as each runner
  finishes.
* **Deadlines.** Items whose deadline expired while queued are
  cancelled at wave build — before any parse/translate/kernel work —
  and their wave-mates are unaffected; a combined execution that fails
  (one bad member, a deadline, anything) falls back to per-item
  execution so each member gets ITS OWN outcome, mirroring the
  pipeline's gang fallback.
* **Shutdown by construction.** ``close()`` flips ``_closing`` under
  the queue lock; from then on ``submit()`` returns ``None`` and the
  caller executes inline — there is no submit/close race to lose. The
  loop drains what was already queued within the ``drain`` budget and
  fails the rest.

Observability: ``dispatch.wave_size``, ``dispatch.inflight_depth``,
``dispatch.device_idle_fraction`` (1 − fraction of wall time with at
least one wave executing, since first submit), and
``dispatch.queue_wait_seconds``; snapshot at ``/debug/dispatch``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Optional

from pilosa_tpu.analysis.locks import OrderedLock
from pilosa_tpu.pql import Query
from pilosa_tpu.utils import heat, metrics, trace

# Request-deadline seam (server/deadline.py), imported lazily for the
# same L4→L6 layering reason as executor.py.
_deadline_mod = None


def _deadline():
    global _deadline_mod
    if _deadline_mod is None:
        from pilosa_tpu.server import deadline as _m

        _deadline_mod = _m
    return _deadline_mod


class _Item:
    """One submitted query: the future its caller blocks on."""

    __slots__ = (
        "index",
        "query",
        "shards",
        "opt",
        "deadline",
        "signature",
        "n_calls",
        "event",
        "value",
        "error",
        "t_enq",
        "wait_s",
        "trace_ctx",
        "attrib",
        "wave_no",
    )

    def __init__(
        self, index, query, shards, opt, deadline, signature, trace_ctx=None
    ) -> None:
        self.index = index
        self.query = query
        self.shards = shards
        self.opt = opt
        self.deadline = deadline
        self.signature = signature
        self.n_calls = len(query.calls)
        self.event = threading.Event()
        self.value: Any = None
        self.error: Optional[BaseException] = None
        self.t_enq = 0.0
        self.wait_s = 0.0
        # distributed trace context (utils/trace.py tuple): a deduped
        # item span-links the executed item it shared results with
        self.trace_ctx = trace_ctx
        # waterfall legs measured inside the wave, apportioned to this
        # item; result() merges them into the waiter's attribution ctx
        self.attrib: Optional[dict] = None
        self.wave_no = 0

    def finish(self, result=None, error=None) -> None:
        self.value = result
        self.error = error
        self.event.set()

    def result(self) -> Any:
        """Block until the wave resolves this item. A waiter whose own
        deadline passes first raises (the runner's dequeue-time check
        skips its queued work; a launched item completes harmlessly on
        the abandoned future)."""
        dl = self.deadline
        if dl is None:
            self.event.wait()
        else:
            while not self.event.is_set():
                rem = dl.remaining()
                if rem <= 0:
                    dl.check("dispatch")  # raises (and counts)
                self.event.wait(timeout=min(rem, 0.5))
        d = trace.attrib_current()
        if d is not None:
            # the waiter's waterfall: queue wait + this item's share of
            # the wave's measured legs (+ the wave id for log joins)
            if self.wait_s > 0.0:
                d[trace.WF_DISPATCH_QUEUE] = (
                    d.get(trace.WF_DISPATCH_QUEUE, 0.0) + self.wait_s
                )
            if self.attrib:
                for k, v in self.attrib.items():
                    d[k] = d.get(k, 0.0) + v
            if self.wave_no:
                d["_wave"] = self.wave_no
        if self.error is not None:
            raise self.error
        return self.value


class DispatchEngine:
    """The persistent per-device dispatch loop. One per Executor; the
    loop thread starts lazily on first submit, so idle executors (and
    every bare test executor that never routes through it) pay
    nothing."""

    def __init__(
        self,
        executor,
        max_wave: int = 16,
        max_inflight: int = 2,
        stage_ahead: int = 1,
    ) -> None:
        self.executor = executor
        self.max_wave = max(1, int(max_wave))
        self.max_inflight = max(1, int(max_inflight))
        self.stage_ahead_depth = max(0, int(stage_ahead))
        self._mu = OrderedLock("dispatch.mu")
        self._cond = threading.Condition(self._mu)
        self._q: deque[_Item] = deque()
        self._closing = False
        self._loop_thread: Optional[threading.Thread] = None
        # wave runner slots: the loop blocks here BEFORE dequeuing, so
        # while all slots compute the queue keeps accumulating and the
        # next wave comes out wider — backlog IS the batching window,
        # exactly like the pipeline's gang dequeue
        self._slots = threading.Semaphore(self.max_inflight)
        self._inflight = 0
        self._in_wave = threading.local()
        # busy/idle accounting: busy = wall time with >=1 wave
        # executing, measured from first submit. The exported
        # dispatch.device_idle_fraction is 1 - busy/wall — the number
        # continuous batching exists to drive down.
        self._t_start: Optional[float] = None
        self._busy_total = 0.0
        self._busy_since: Optional[float] = None
        # counters (ints under _mu; snapshot is consistent)
        self.waves = 0
        self.items = 0
        self.dedup_hits = 0
        self.combined_items = 0
        self.fallbacks = 0
        self.expired = 0
        # per-tenant rollup (index = tenant, server/tenancy.py): who is
        # filling the waves, who is expiring in queue — the dispatch
        # leg of the per-tenant attribution story
        self.by_tenant: dict[str, dict[str, int]] = {}

    def _tenant_row_locked(self, index: str) -> dict:
        row = self.by_tenant.get(index)
        if row is None:
            row = self.by_tenant[index] = {
                "items": 0,
                "dedup_hits": 0,
                "expired": 0,
            }
        return row

    # -- admission -----------------------------------------------------------

    def submit(
        self,
        index: str,
        query: Query,
        shards,
        opt,
        deadline=None,
        text: Optional[str] = None,
        trace_ctx=None,
    ) -> Optional[_Item]:
        """Enqueue a read-only query for the next wave and return its
        future — or ``None`` when the engine is closing, in which case
        the caller executes inline (shutdown can never strand a
        submit)."""
        sig = None
        if text is not None:
            from pilosa_tpu.plan import canon

            sig = canon.query_signature(text)
        item = _Item(index, query, shards, opt, deadline, sig, trace_ctx=trace_ctx)
        with self._mu:
            if self._closing:
                return None
            if self._loop_thread is None:
                self._t_start = time.monotonic()
                t = threading.Thread(
                    target=self._loop, name="dispatch-loop", daemon=True
                )
                self._loop_thread = t
                t.start()
            item.t_enq = time.monotonic()
            self._q.append(item)
            self.items += 1
            self._tenant_row_locked(index)["items"] += 1
            self._cond.notify_all()
        return item

    def in_wave(self) -> bool:
        """True on a thread currently executing a wave (re-entry
        guard: anything inside a wave that reaches execute() again must
        run inline, not deadlock against its own runner slot)."""
        return getattr(self._in_wave, "active", False)

    # -- the dispatch loop ---------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._mu:
                while not self._q and not self._closing:
                    self._cond.wait()
                if not self._q:
                    return  # closing and drained
            # acquire a runner slot BEFORE dequeuing: with every slot
            # busy the backlog keeps growing and the next wave is wider
            self._slots.acquire()
            with self._mu:
                n = min(self.max_wave, len(self._q))
                wave = [self._q.popleft() for _ in range(n)]
                if not wave:
                    self._slots.release()
                    continue
                self.waves += 1
                wave_no = self.waves
                self._inflight += 1
                if self._inflight == 1:
                    self._busy_since = time.monotonic()
                metrics.gauge(metrics.DISPATCH_INFLIGHT_DEPTH, self._inflight)
            # overlap: operand staging for what is STILL queued runs on
            # the stager's side thread while this wave computes
            self._stage_ahead_peek()
            threading.Thread(
                target=self._run_wave_slot,
                args=(wave, wave_no),
                name="dispatch-wave",
                daemon=True,
            ).start()

    def _run_wave_slot(self, wave: list[_Item], wave_no: int = 0) -> None:
        try:
            self._run_wave(wave, wave_no)
        finally:
            with self._mu:
                self._inflight -= 1
                if self._inflight == 0 and self._busy_since is not None:
                    self._busy_total += time.monotonic() - self._busy_since
                    self._busy_since = None
                metrics.gauge(metrics.DISPATCH_INFLIGHT_DEPTH, self._inflight)
                metrics.gauge(
                    metrics.DISPATCH_DEVICE_IDLE_FRACTION,
                    self._idle_fraction_locked(),
                )
                self._cond.notify_all()  # close() waits on inflight==0
            self._slots.release()

    def _idle_fraction_locked(self) -> float:
        if self._t_start is None:
            return 1.0
        now = time.monotonic()
        wall = now - self._t_start
        if wall <= 0:
            return 0.0
        busy = self._busy_total
        if self._busy_since is not None:
            busy += now - self._busy_since
        return max(0.0, min(1.0, 1.0 - busy / wall))

    # -- wave execution ------------------------------------------------------

    def _run_wave(self, wave: list[_Item], wave_no: int = 0) -> None:
        self._in_wave.active = True
        # wave id rides the contextvar so the logger's correlation
        # suffix (wave=N) joins this wave's log lines to its items'
        # waterfalls
        wtok = trace.set_wave(wave_no)
        try:
            now = time.monotonic()
            metrics.observe(metrics.DISPATCH_WAVE_SIZE, len(wave))
            live: list[_Item] = []
            for it in wave:
                it.wait_s = now - it.t_enq
                it.wave_no = wave_no
                metrics.observe(metrics.DISPATCH_QUEUE_WAIT_SECONDS, it.wait_s)
                if it.deadline is not None and it.deadline.expired():
                    # expired while queued: cancelled before any
                    # parse/translate/kernel work; wave-mates unaffected
                    with self._mu:
                        self.expired += 1
                        self._tenant_row_locked(it.index)["expired"] += 1
                    metrics.count(
                        metrics.PIPELINE_DEADLINE_EXPIRED, stage="dispatch"
                    )
                    it.finish(error=_deadline().DeadlineExceeded("dispatch"))
                    continue
                live.append(it)
            if heat.LEDGER.enabled:
                # wave-membership heat: one count per (index, shard)
                # admitted into this wave (fused launches ride the same
                # membership — a deduped item still occupied a slot)
                for it in live:
                    for s in it.shards or ():
                        heat.record_wave(it.index, "", s)
            groups: dict[tuple, list[_Item]] = {}
            for it in live:
                o = it.opt
                key = (
                    it.index,
                    tuple(it.shards) if it.shards is not None else None,
                    o.remote,
                    o.exclude_row_attrs,
                    o.exclude_columns,
                    o.cache,
                )
                groups.setdefault(key, []).append(it)
            for members in groups.values():
                self._run_group(members, wave_no)
        finally:
            trace.reset_wave(wtok)
            self._in_wave.active = False

    def _run_group(self, members: list[_Item], wave_no: int = 0) -> None:
        """Dedup by canonical signature, then execute the distinct
        plans as one combined multi-call query."""
        leaders: list[_Item] = []
        by_sig: dict[str, _Item] = {}
        dups: dict[int, list[_Item]] = {}
        for it in members:
            lead = by_sig.get(it.signature) if it.signature is not None else None
            if lead is not None and lead.n_calls == it.n_calls:
                dups.setdefault(id(lead), []).append(it)
                with self._mu:
                    self.dedup_hits += 1
                    self._tenant_row_locked(it.index)["dedup_hits"] += 1
                if it.trace_ctx is not None and it.trace_ctx[2]:
                    # wave-level singleflight: the deduped item's trace
                    # span-links the executed item and names the wave
                    lctx = lead.trace_ctx
                    trace.record_link(
                        metrics.STAGE_DISPATCH_DEDUP,
                        it.trace_ctx,
                        lctx if lctx is not None else ("", ""),
                        wave=wave_no,
                        signature=it.signature,
                    )
                continue
            if it.signature is not None:
                by_sig[it.signature] = it
            leaders.append(it)
        if len(leaders) > 1:
            if not self._try_combined(leaders):
                for it in leaders:
                    self._run_single(it)
        elif leaders:
            self._run_single(leaders[0])
        for lead in leaders:
            for d in dups.get(id(lead), ()):
                d.attrib = lead.attrib  # served by the leader's work
                d.finish(result=lead.value, error=lead.error)

    def _try_combined(self, leaders: list[_Item]) -> bool:
        """One combined execution for the whole group: the calls fan
        through the executor's read pool together, so the batched
        scorers coalesce heterogeneous members' kernel work. Runs under
        the group-minimum deadline; any failure reports False and the
        caller re-runs members individually (a bad member can never
        fail its wave-mates)."""
        head = leaders[0]
        combined = Query(calls=[c for it in leaders for c in it.query.calls])
        dls = [it.deadline for it in leaders if it.deadline is not None]
        gang_dl = min(dls, key=lambda d: d.at) if dls else None
        dm = _deadline()
        # fresh attribution scope for the combined execution: the legs
        # measured inside (fenced device compute, transfer, stager, ...)
        # are apportioned to the members by call count — one wave, one
        # measurement, each waiter sees its share
        measured: dict = {}
        try:
            with dm.activate(gang_dl), trace.attrib_activate(measured):
                results = self.executor._execute(
                    head.index, combined, head.shards, head.opt
                )
        except BaseException:
            with self._mu:
                self.fallbacks += 1
            return False
        with self._mu:
            self.combined_items += len(leaders)
        total_calls = sum(it.n_calls for it in leaders) or 1
        off = 0
        for it in leaders:
            if measured:
                w = it.n_calls / total_calls
                it.attrib = {k: v * w for k, v in measured.items()}
            it.finish(result=results[off : off + it.n_calls])
            off += it.n_calls
        return True

    def _run_single(self, it: _Item) -> None:
        if it.event.is_set():
            return
        dm = _deadline()
        if it.deadline is not None and it.deadline.expired():
            # the deadline lapsed during a FAILED combined attempt: the
            # waiter already raised 504 on its own clock — re-executing
            # here would burn a full solo run on an abandoned future
            # (the fault's blast radius leaking into device time). Give
            # the item its honest outcome instead.
            with self._mu:
                self.expired += 1
            metrics.count(metrics.PIPELINE_DEADLINE_EXPIRED, stage="dispatch")
            it.finish(error=dm.DeadlineExceeded("dispatch"))
            return
        measured: dict = {}
        try:
            with dm.activate(it.deadline), trace.attrib_activate(measured):
                result = self.executor._execute(
                    it.index, it.query, it.shards, it.opt
                )
            it.attrib = measured or None
            it.finish(result=result)
        except BaseException as err:
            it.finish(error=err)

    # -- stage-ahead overlap -------------------------------------------------

    def _stage_ahead_peek(self) -> None:
        """Advisory operand prefetch for queued-but-unlaunched items:
        while the launched wave computes, the stager's side thread
        uploads the NEXT waves' Row operands (staging overlapped with
        compute). Bounded, best-effort, and idempotent — the real
        execution re-stages whatever this missed.

        With a plan-driven prefetcher wired (executor/tiering.py), the
        queued items' plans go to the scheduler instead: it extracts
        Row operands itself, promotes their blocks T1/T2 → T0, and
        marks them for accuracy attribution — replacing the opaque
        warm-thunk path."""
        ex = self.executor
        pf = getattr(ex, "prefetcher", None)
        if pf is not None and pf.enabled:
            with self._mu:
                peek = list(self._q)[: pf.depth * self.max_wave]
            if peek:
                pf.schedule(peek)
            return
        if self.stage_ahead_depth <= 0:
            return
        stage = getattr(ex.stager, "stage_ahead", None)
        if stage is None:
            return
        with self._mu:
            peek = list(self._q)[: self.stage_ahead_depth * self.max_wave]
        seen: set = set()
        for it in peek:
            key = (it.index, it.signature)
            if it.signature is not None and key in seen:
                continue
            seen.add(key)
            stage(lambda it=it: ex._warm_query(it.index, it.query, it.shards))

    # -- lifecycle -----------------------------------------------------------

    def close(self, drain: float = 5.0) -> bool:
        """Stop admission (``submit`` returns None → callers run
        inline), drain queued + in-flight waves within ``drain``
        seconds, fail whatever remains. Returns True when everything
        drained in time."""
        t0 = time.monotonic()
        with self._mu:
            self._closing = True
            self._cond.notify_all()
            loop = self._loop_thread
        if loop is not None:
            loop.join(timeout=max(0.0, drain - (time.monotonic() - t0)))
        leftovers: list[_Item] = []
        with self._mu:
            deadline = t0 + drain
            while self._inflight > 0 and time.monotonic() < deadline:
                self._cond.wait(timeout=0.05)
            clean = self._inflight == 0 and not self._q
            while self._q:
                leftovers.append(self._q.popleft())
        for it in leftovers:
            it.finish(error=RuntimeError("dispatch engine shut down"))
        return clean

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """The /debug/dispatch snapshot."""
        with self._mu:
            return {
                "enabled": True,
                "closing": self._closing,
                "max_wave": self.max_wave,
                "max_inflight": self.max_inflight,
                "stage_ahead": self.stage_ahead_depth,
                "queued": len(self._q),
                "inflight_waves": self._inflight,
                "waves": self.waves,
                "items": self.items,
                "dedup_hits": self.dedup_hits,
                "combined_items": self.combined_items,
                "fallbacks": self.fallbacks,
                "deadline_expired": self.expired,
                "tenants": {idx: dict(row) for idx, row in self.by_tenant.items()},
                "device_idle_fraction": self._idle_fraction_locked(),
                "fusion": (
                    self.executor.fuser.stats()
                    if getattr(self.executor, "fuser", None) is not None
                    else {"enabled": False}
                ),
                "prefetch": (
                    self.executor.prefetcher.stats()
                    if getattr(self.executor, "prefetcher", None) is not None
                    else {"enabled": False}
                ),
            }
