"""HBM-pressure governance and OOM recovery (ISSUE 14).

PR 12 made HBM load-bearing: staged fragment blocks, device-resident
plan-cache entries, batcher pad scratch, and fused whole-query launches
all compete for the same accelerator memory — previously under three
*independent* byte budgets that could jointly overcommit the chip, and
with no handling at all for an allocation failure (``RESOURCE_EXHAUSTED``
surfaced as an unhandled 500). Two pieces fix that:

* ``HbmGovernor`` — one process-wide byte ledger every HBM tenant
  reserves against. The old per-subsystem knobs survive as per-tenant
  *shares* of the global budget; the global budget is the sum of shares
  unless pinned smaller by ``hbm-budget-bytes``. When the ledger runs
  over (or live ``DeviceTelemetry`` gauges show real HBM pressure), the
  governor relieves in tiers: the device plan cache first (pure derived
  state, cheapest to rebuild), then cold stager blocks. Fused launches
  consult ``admit()`` with their estimated transient peak BEFORE
  launching, so a wave that cannot fit is split or routed to the classic
  per-call path instead of launched into an OOM.

* ``OomRecovery`` — the policy applied at the device-call boundaries
  (``_timed_kernel``, the fused launch, the batched scorers): classify
  the failure (allocation vs. wedge), journal ``device.oom``, then for
  an allocation failure evict through the governor tiers and retry the
  call ONCE; if the retry also fails (or the error is a wedge-class
  runtime fault) the call degrades to the CPU roaring leg by raising
  ``DeviceOom`` — a ``DeviceDown`` subclass, so the executor's existing
  fallback path serves the query from host bitmaps. ``DeviceHealth``
  trips only on REPEAT unrecovered failures inside a short window —
  never a wedged process, never a wrong answer, and a single transient
  OOM never gates a healthy device off.

Lock discipline: the governor's ledger lock is never held across a
tenant eviction callback (those take the stager/plan-cache locks), and
tenants never call back into the governor while holding their own locks
in a way that re-enters ``relieve`` on themselves — ``reserve`` excludes
the requesting tenant from the relief sweep; the tenant's own LRU loop
handles its share.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from pilosa_tpu.analysis.locks import OrderedLock
from pilosa_tpu.executor.devicehealth import DeviceDown
from pilosa_tpu.utils import events, metrics


class DeviceOom(DeviceDown):
    """An unrecovered device allocation failure. Subclasses DeviceDown
    so the executor's existing guarded-call fallback serves the query
    from the CPU roaring path; the health gate is NOT tripped (that is
    OomRecovery's call, and only on repeat failure)."""


# -- error classification -----------------------------------------------------

# substrings that mark an allocation failure (XLA RESOURCE_EXHAUSTED,
# PJRT "out of memory", injected faults from utils/chaos.py)
_ALLOC_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM")
# exception type names raised by jax/XLA runtime failures; anything
# else textual that marks a device-side runtime fault
_RUNTIME_TYPES = ("XlaRuntimeError", "JaxRuntimeError")
_WEDGE_MARKERS = ("INTERNAL:", "DATA_LOSS", "FAILED_PRECONDITION", "ABORTED")


def classify_device_error(exc: BaseException) -> Optional[str]:
    """``"alloc"`` for an allocation failure (eviction + retry can
    help), ``"wedge"`` for a non-allocation device runtime fault
    (retry is pointless; degrade and let repeat failures trip the
    health gate), ``None`` for anything that is not a device error —
    those propagate untouched (a shape bug must stay a loud bug)."""
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _ALLOC_MARKERS):
        return "alloc"
    if type(exc).__name__ in _RUNTIME_TYPES:
        return "wedge"
    if any(m in text for m in _WEDGE_MARKERS):
        return "wedge"
    return None


# -- the byte ledger ----------------------------------------------------------


class _Tenant:
    __slots__ = (
        "name", "share", "evict_fn", "tier", "domain", "used",
        "by_index", "prefer_ok",
    )

    def __init__(
        self, name: str, share: int, evict_fn, tier: int, domain: str = "hbm"
    ) -> None:
        self.name = name
        self.share = share
        self.evict_fn = evict_fn
        self.tier = tier
        # "hbm" tenants hold device memory and count against the global
        # budget; "host" tenants (the T1 container tier) ride the same
        # ledger for visibility and stats but never trigger — or are
        # swept by — device pressure relief (ISSUE 17)
        self.domain = domain
        self.used = 0
        # sub-tenant accounting (ISSUE 19): bytes by owning INDEX —
        # "tenant" in the multi-tenant sense, vs this class which is a
        # registered SUBSYSTEM account. Only charges that name an index
        # land here; used - sum(by_index) is unattributed scratch.
        self.by_index: dict[str, int] = {}
        # whether evict_fn accepts the quota-relief ``prefer=`` kwarg
        self.prefer_ok = False


class HbmGovernor:
    """One process-wide HBM byte ledger with tiered pressure relief.

    Tenants register with a *share* (their old standalone budget — the
    per-tenant cap) and optionally an ``evict_fn(need_bytes) -> freed``
    callback plus a *tier* (lower tiers evict first). ``reserve`` /
    ``release`` keep the ledger; a reserve that pushes the TOTAL over
    the global budget triggers a relief sweep over the OTHER tenants'
    tiers (the requester's own LRU loop handles its share), and reports
    whether the ledger is back under budget. ``admit`` answers the
    fused-launch admission question: does an estimated transient peak
    fit in current headroom (relieving first if not)?
    """

    # fraction of the live telemetry limit above which a reserve/admit
    # opportunistically relieves pressure even when the ledger itself
    # is under budget (mirrors the profiler's hbm-watermark default)
    TELEMETRY_WATERMARK = 0.9

    def __init__(self, budget_bytes: int = 0) -> None:
        # 0 = derive from the sum of registered shares (the compatible
        # default: each tenant capped at its old knob, total capped at
        # their sum); > 0 pins the global budget below that sum — the
        # double-budget overcommit fix
        self.budget_bytes = int(budget_bytes)
        self._mu = OrderedLock("hbm.governor_mu")
        self._tenants: dict[str, _Tenant] = {}
        # per-INDEX byte quotas (ISSUE 19, tenant-hbm-quota): caps one
        # tenant's total footprint across all hbm-domain subsystems;
        # 0 / absent = unlimited
        self._index_quotas: dict[str, int] = {}
        self._default_index_quota = 0

    # -- registration ---------------------------------------------------------

    def register(
        self,
        name: str,
        share_bytes: int = 0,
        evict_fn: Optional[Callable[[int], int]] = None,
        tier: int = 99,
        domain: str = "hbm",
    ) -> None:
        prefer_ok = False
        if evict_fn is not None:
            try:
                import inspect

                prefer_ok = "prefer" in inspect.signature(evict_fn).parameters
            except (TypeError, ValueError):
                prefer_ok = False
        with self._mu:
            t = self._tenants.get(name)
            if t is None:
                t = _Tenant(name, int(share_bytes), evict_fn, tier, domain)
                self._tenants[name] = t
            else:
                t.share = int(share_bytes)
                t.evict_fn = evict_fn
                t.tier = tier
                t.domain = domain
            t.prefer_ok = prefer_ok

    def set_index_quotas(
        self, quotas: dict[str, int], default: int = 0
    ) -> None:
        """Install per-index byte quotas (server wiring, from
        ``tenant-hbm-quota``). A reserve that pushes an index past its
        quota triggers a targeted sweep of THAT index's blocks only."""
        with self._mu:
            self._index_quotas = {k: int(v) for k, v in quotas.items()}
            self._default_index_quota = int(default)

    # -- accounting -----------------------------------------------------------

    def budget(self) -> int:
        with self._mu:
            return self._budget_locked()

    def _budget_locked(self) -> int:
        if self.budget_bytes > 0:
            return self.budget_bytes
        return sum(
            t.share for t in self._tenants.values() if t.domain == "hbm"
        ) or (8 << 30)

    def used(self, name: Optional[str] = None) -> int:
        with self._mu:
            if name is not None:
                t = self._tenants.get(name)
                return t.used if t is not None else 0
            return sum(t.used for t in self._tenants.values())

    def headroom(self) -> int:
        with self._mu:
            return self._budget_locked() - sum(
                t.used for t in self._tenants.values() if t.domain == "hbm"
            )

    def over_budget(self) -> int:
        """Bytes the ledger currently exceeds the global budget by
        (0 when under). Tenants consult this in their own LRU-evict
        loops so evicting their entries converges the global ledger,
        not just their share."""
        return max(0, -self.headroom())

    def reserve(self, name: str, nbytes: int, index: str = "") -> bool:
        """Record ``nbytes`` against ``name``'s account (and, when the
        charge names its owning ``index``, that tenant's sub-account).
        Always records (the bytes are already being uploaded — the
        ledger must reflect reality); returns False when the ledger
        remains over budget after relieving the OTHER tenants, in which
        case the caller evicts its own LRU entries (its loop also
        checks ``over_budget``)."""
        nbytes = int(nbytes)
        quota_excess = 0
        with self._mu:
            t = self._tenants.get(name)
            if t is None:
                t = _Tenant(name, 0, None, 99)
                self._tenants[name] = t
            t.used += nbytes
            used = t.used
            if index:
                t.by_index[index] = t.by_index.get(index, 0) + nbytes
                idx_used = self._index_used_locked(index)
                quota = self._index_quota_locked(index)
                if quota > 0 and idx_used > quota:
                    quota_excess = idx_used - quota
        metrics.gauge(metrics.HBM_GOVERNOR_BYTES, used, tenant=name)
        if index:
            metrics.gauge(
                metrics.TENANT_HBM_BYTES, self.index_used(index), tenant=index
            )
        if quota_excess > 0:
            # over ITS quota, not the global budget: sweep only this
            # index's blocks — a tenant at quota degrades only its own
            # queries (ISSUE 19)
            self.relieve_index(index, quota_excess)
        if self.over_budget() > 0:
            self.relieve(exclude=name)
        self._telemetry_relief(exclude=name)
        return self.over_budget() <= 0

    def release(self, name: str, nbytes: int, index: str = "") -> None:
        with self._mu:
            t = self._tenants.get(name)
            if t is None:
                return
            t.used = max(0, t.used - int(nbytes))
            used = t.used
            if index and index in t.by_index:
                left = t.by_index[index] - int(nbytes)
                if left > 0:
                    t.by_index[index] = left
                else:
                    del t.by_index[index]
        metrics.gauge(metrics.HBM_GOVERNOR_BYTES, used, tenant=name)
        if index:
            metrics.gauge(
                metrics.TENANT_HBM_BYTES, self.index_used(index), tenant=index
            )

    def reset(self, name: Optional[str] = None) -> None:
        """Zero an account (or every account): the wedge-recovery /
        epoch-fence path — the arrays the ledger tracked died with the
        device context, so the ledger must not remember them."""
        with self._mu:
            tenants = (
                [self._tenants[name]] if name in self._tenants else []
            ) if name is not None else list(self._tenants.values())
            for t in tenants:
                t.used = 0
                t.by_index.clear()
        for t in tenants:
            metrics.gauge(metrics.HBM_GOVERNOR_BYTES, 0, tenant=t.name)

    # -- per-index (multi-tenant) accounting ----------------------------------

    def _index_quota_locked(self, index: str) -> int:
        return self._index_quotas.get(index, self._default_index_quota)

    def _index_used_locked(self, index: str, domain: str = "hbm") -> int:
        return sum(
            t.by_index.get(index, 0)
            for t in self._tenants.values()
            if t.domain == domain
        )

    def index_used(self, index: str) -> int:
        """One tenant's total HBM-domain bytes across subsystems."""
        with self._mu:
            return self._index_used_locked(index)

    def index_over_quota(self, index: str) -> int:
        """Bytes ``index`` currently exceeds its quota by (0 when under
        or unlimited)."""
        with self._mu:
            quota = self._index_quota_locked(index)
            if quota <= 0:
                return 0
            return max(0, self._index_used_locked(index) - quota)

    def over_quota_indexes(self) -> list[str]:
        """Indexes above their quota, worst offender first — the
        relief sweep's preference list."""
        with self._mu:
            excess = {}
            for t in self._tenants.values():
                if t.domain != "hbm":
                    continue
                for idx, used in t.by_index.items():
                    excess[idx] = excess.get(idx, 0) + used
            out = []
            for idx, used in excess.items():
                quota = self._index_quota_locked(idx)
                if quota > 0 and used > quota:
                    out.append((used - quota, idx))
        return [idx for _, idx in sorted(out, reverse=True)]

    def relieve_index(self, index: str, need: int) -> int:
        """Targeted quota sweep: free ``need`` bytes belonging to ONE
        index, walking the tiers with ``prefer=[index]`` so only that
        tenant's blocks are touched. Callbacks run without the
        governor lock."""
        with self._mu:
            tiers = sorted(
                (
                    t
                    for t in self._tenants.values()
                    if t.evict_fn is not None
                    and t.domain == "hbm"
                    and t.prefer_ok
                ),
                key=lambda t: t.tier,
            )
        freed_total = 0
        for t in tiers:
            deficit = int(need) - freed_total
            if deficit <= 0:
                break
            try:
                freed = int(t.evict_fn(deficit, prefer=[index]) or 0)
            except Exception:
                freed = 0
            if freed > 0:
                freed_total += freed
                metrics.count(metrics.HBM_GOVERNOR_EVICTIONS, tier=t.name)
                metrics.count(
                    metrics.TENANT_HBM_EVICTIONS, tenant=index, tier=t.name
                )
        return freed_total

    # -- admission + relief ---------------------------------------------------

    def admit(self, nbytes: int) -> bool:
        """Fused-launch admission: does an estimated transient peak of
        ``nbytes`` fit in current headroom? Relieves the tiers first
        when it would not — admission prefers evicting rebuildable
        cache state over refusing a launch."""
        nbytes = int(nbytes)
        if nbytes <= self.headroom():
            return True
        self.relieve(need=nbytes)
        return nbytes <= self.headroom()

    def relieve(self, need: int = 0, exclude: Optional[str] = None) -> int:
        """Evict through the tiers (device plan cache first, then cold
        stager blocks) until the ledger has ``need`` bytes of headroom
        (or, with ``need=0``, is back under budget). When some index is
        over its byte quota the sweep walks the tiers TWICE: first
        constrained to the over-quota tenants' blocks (prefer pass),
        then classic LRU for whatever deficit remains — an under-quota
        tenant loses a block only after every over-quota tenant's
        excess is gone. Callbacks run WITHOUT the governor lock — they
        take their owners' locks and call ``release`` re-entrantly.
        Returns bytes freed."""
        with self._mu:
            tiers = sorted(
                (
                    t
                    for t in self._tenants.values()
                    if t.evict_fn is not None and t.domain == "hbm"
                ),
                key=lambda t: t.tier,
            )
            have_quotas = bool(self._index_quotas or self._default_index_quota)
        freed_total = 0
        passes = [None]
        if have_quotas:
            prefer = self.over_quota_indexes()
            if prefer:
                passes = [prefer, None]
        for prefer in passes:
            for t in tiers:
                deficit = (
                    max(0, int(need) - self.headroom())
                    if need
                    else self.over_budget()
                )
                if deficit <= 0:
                    return freed_total
                if t.name == exclude:
                    continue
                try:
                    if prefer is not None:
                        if not t.prefer_ok:
                            continue
                        freed = int(t.evict_fn(deficit, prefer=prefer) or 0)
                    else:
                        freed = int(t.evict_fn(deficit) or 0)
                except Exception:
                    freed = 0
                if freed > 0:
                    freed_total += freed
                    metrics.count(metrics.HBM_GOVERNOR_EVICTIONS, tier=t.name)
        return freed_total

    def relieve_for_oom(self) -> int:
        """The aggressive post-OOM sweep: a real RESOURCE_EXHAUSTED
        means the chip is out of memory regardless of what the ledger
        believed (XLA scratch and fusion intermediates are invisible to
        it), so skip the deficit arithmetic and ask every tier to free
        everything it can before the single retry."""
        with self._mu:
            tiers = sorted(
                (
                    t
                    for t in self._tenants.values()
                    if t.evict_fn is not None and t.domain == "hbm"
                ),
                key=lambda t: t.tier,
            )
            budget = self._budget_locked()
        freed_total = 0
        for t in tiers:
            try:
                freed = int(t.evict_fn(budget) or 0)
            except Exception:
                freed = 0
            if freed > 0:
                freed_total += freed
                metrics.count(metrics.HBM_GOVERNOR_EVICTIONS, tier=t.name)
        return freed_total

    def _telemetry_relief(self, exclude: Optional[str] = None) -> None:
        """Pressure relief driven by live DeviceTelemetry HBM gauges:
        when the poller has a real ``memory_stats()`` sample showing
        the device above the watermark, evict through the tiers even
        though the ledger itself is under budget (the ledger only sees
        OUR tenants; XLA scratch and fusion intermediates are real)."""
        try:
            from pilosa_tpu.utils import profiler

            last = profiler.TELEMETRY.last or {}
            devices = last.get("devices") or {}
        except Exception:
            return
        for dev in devices.values():
            in_use = dev.get("bytes_in_use") or 0
            limit = dev.get("bytes_limit") or 0
            if limit and in_use > limit * self.TELEMETRY_WATERMARK:
                self.relieve(
                    need=int(in_use - limit * self.TELEMETRY_WATERMARK),
                    exclude=exclude,
                )
                return

    def stats(self) -> dict:
        with self._mu:
            by_index: dict[str, int] = {}
            for t in self._tenants.values():
                if t.domain != "hbm":
                    continue
                for idx, used in t.by_index.items():
                    by_index[idx] = by_index.get(idx, 0) + used
            out = {
                "budget_bytes": self._budget_locked(),
                "used_bytes": sum(
                    t.used for t in self._tenants.values() if t.domain == "hbm"
                ),
                # "domain" only on off-device tenants (e.g. the tier1
                # host cache) — device tenants keep the classic shape
                "tenants": {
                    t.name: {
                        "used": t.used,
                        "share": t.share,
                        "tier": t.tier,
                        **({"domain": t.domain} if t.domain != "hbm" else {}),
                        **(
                            {"by_index": dict(t.by_index)}
                            if t.by_index
                            else {}
                        ),
                    }
                    for t in self._tenants.values()
                },
            }
            if self._index_quotas or self._default_index_quota:
                out["index_quotas"] = {
                    "default": self._default_index_quota,
                    **self._index_quotas,
                }
            if by_index:
                out["index_used"] = by_index
        return out


# -- OOM recovery at the device-call boundaries -------------------------------


class OomRecovery:
    """Evict → retry once → degrade-to-CPU, with health tripped only on
    repeat failure. One instance per executor, shared by ``_timed_kernel``
    closures, the fused launcher, and the batched scorers."""

    def __init__(
        self,
        governor: Optional[HbmGovernor] = None,
        health=None,
        on_degrade: Optional[Callable[[], None]] = None,
        trip_after: int = 2,
        window_s: float = 30.0,
    ) -> None:
        self.governor = governor
        self.health = health
        self.on_degrade = on_degrade
        self.trip_after = trip_after
        self.window_s = window_s
        self._mu = threading.Lock()
        self._failures: list[float] = []  # monotonic stamps of degrades
        # telemetry (read by stats/tests)
        self.ooms = 0
        self.recovered = 0
        self.degraded = 0

    def run(self, fn: Callable, kind: str = "kernel"):
        """Run a device call under the recovery policy. Raises
        ``DeviceOom`` when the call must degrade to the CPU leg;
        re-raises non-device errors untouched."""
        try:
            return fn()
        except Exception as e:
            cls = classify_device_error(e)
            if cls is None:
                raise
            with self._mu:
                self.ooms += 1
            metrics.count(metrics.DEVICE_OOM, kind=kind, cls=cls)
            events.record(
                events.DEVICE_OOM, boundary=kind, cls=cls, error=str(e)[:200]
            )
            if cls == "alloc":
                if self.governor is not None:
                    self.governor.relieve_for_oom()
                try:
                    out = fn()
                except Exception as e2:
                    if classify_device_error(e2) is None:
                        raise
                else:
                    with self._mu:
                        self.recovered += 1
                        self._failures.clear()
                    metrics.count(metrics.DEVICE_OOM_RECOVERED, path="retry")
                    events.record(
                        events.DEVICE_OOM_RECOVERED, boundary=kind, path="retry"
                    )
                    return out
            # allocation retry failed too, or a wedge-class fault:
            # degrade this call to the CPU leg and remember the failure
            self._degrade(kind, e)

    def _degrade(self, kind: str, cause: BaseException) -> None:
        now = time.monotonic()
        with self._mu:
            self.degraded += 1
            self._failures = [
                t for t in self._failures if now - t < self.window_s
            ]
            self._failures.append(now)
            repeat = len(self._failures) >= self.trip_after
        metrics.count(metrics.DEVICE_OOM_CPU_DEGRADES)
        metrics.count(metrics.DEVICE_OOM_RECOVERED, path="cpu")
        events.record(events.DEVICE_OOM_RECOVERED, boundary=kind, path="cpu")
        cb = self.on_degrade
        if cb is not None:
            try:
                cb()
            except Exception:
                pass
        if repeat and self.health is not None:
            # repeat unrecovered failures inside the window: this is no
            # longer a transient — gate the device and let the probe
            # loop + restore callback rebuild the device-side machinery
            try:
                self.health.trip("repeated unrecovered device OOM")
            except Exception:
                pass
        raise DeviceOom(f"device {kind} failed after OOM recovery") from cause

    def stats(self) -> dict:
        with self._mu:
            return {
                "ooms": self.ooms,
                "recovered": self.recovered,
                "degraded": self.degraded,
                "recent_failures": len(self._failures),
            }
