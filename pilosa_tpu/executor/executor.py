"""Query executor (L4) — lowers PQL call trees onto shard kernels.

Mirrors the reference's executor (reference executor.go): top-level
dispatch by call name, per-shard leaf functions, cross-shard map/reduce.
Two execution paths per shard:

  * CPU   — roaring Row algebra (the correctness oracle, always available)
  * device — packed-word XLA kernels over HBM-staged fragment state:
             bitmap subtrees fold elementwise, Count/Sum/Min/Max reduce
             via popcount kernels, TopN batches every candidate's
             intersection count into one matrix pass
             (replacing the reference's per-candidate heap loop).

Both paths are bit-identical; `device_policy` picks ("never" | "auto" |
"always"). Cross-node distribution plugs in through the `cluster`
seam (reference mapReduce, executor.go:1464) — single-node runs use a
local loop over shards.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from datetime import datetime
from typing import Any, Optional

import numpy as np

from pilosa_tpu.utils import chaos, heat, metrics, profiler, trace

from pilosa_tpu import SHARD_WIDTH, ops
from pilosa_tpu.core import Row, TopOptions, VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD
from pilosa_tpu.core.cache import sort_pairs
from pilosa_tpu.core.cache import pairs_arrays as cache_pairs_arrays
from pilosa_tpu.core.fragment import DEFAULT_MIN_THRESHOLD, FragmentQuarantinedError
from pilosa_tpu.executor import analytics
from pilosa_tpu.core.timequantum import TIME_FORMAT, views_by_time_range
from pilosa_tpu.executor.batcher import BatchedScorer
from pilosa_tpu.executor.devicehealth import DeviceDown
from pilosa_tpu.executor.hbm import (
    DeviceOom,
    HbmGovernor,
    OomRecovery,
    classify_device_error,
)
from pilosa_tpu.executor.stager import DeviceStager
from pilosa_tpu.pql import BETWEEN, Call, Condition, NEQ, Query, parse
from pilosa_tpu.roaring import Bitmap

_W32 = SHARD_WIDTH // 32

# Minimum packed words across a query's fragments before "auto" picks the
# device path (tiny fragments are faster in roaring on host).
AUTO_DEVICE_MIN_CONTAINERS = 64


# re-export: one canonical not-found type framework-wide (the HTTP
# layer maps it to 404 by type; any plain KeyError stays a 500)
from pilosa_tpu.utils.errors import NotFoundError  # noqa: E402

# Request-deadline seam (server/deadline.py). Imported LAZILY: a
# top-level import would pull the server package (L6) into this module
# (L4) at import time and trip the server→executor circular import;
# resolving once at first use costs one global check per call after.
_deadline_mod = None


def _deadline():
    global _deadline_mod
    if _deadline_mod is None:
        from pilosa_tpu.server import deadline as _m

        _deadline_mod = _m
    return _deadline_mod


@dataclass
class ValCount:
    """reference executor.go:1762."""

    val: int = 0
    count: int = 0

    def add(self, other: "ValCount") -> "ValCount":
        return ValCount(self.val + other.val, self.count + other.count)

    def smaller(self, other: "ValCount") -> "ValCount":
        if self.count == 0 or (other.val < self.val and other.count > 0):
            return other
        return ValCount(self.val, self.count)

    def larger(self, other: "ValCount") -> "ValCount":
        if self.count == 0 or (other.val > self.val and other.count > 0):
            return other
        return ValCount(self.val, self.count)


def pairs_add(a: list[tuple[int, int]], b: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Merge id/count pair lists, summing counts (reference Pairs.Add)."""
    m = dict(a)
    for id_, cnt in b:
        m[id_] = m.get(id_, 0) + cnt
    return list(m.items())


@dataclass
class ExecOptions:
    """reference execOptions (executor.go:1714)."""

    remote: bool = False
    exclude_row_attrs: bool = False
    exclude_columns: bool = False
    # plan result cache participation (plan/cache.py): False bypasses
    # both lookup and insert — the `cache=false` query option, and the
    # profile=true path (a profiled query must show real execution)
    cache: bool = True
    # run a multi-call query's calls serially instead of through the
    # read pool. Gang-dispatched multihost execution requires it: every
    # rank must issue collectives in the identical order, and a thread
    # pool's interleaving is not deterministic across processes
    serial: bool = False


class _NotDeviceable(Exception):
    """Raised when a call subtree can't run on the device path."""


class _ScoreCarry:
    """Cross-pass TopN score carry: pass 1's chunk scores, appended as
    whole arrays and resolved vectorized at pass-2 seed time.

    Pass 2 only needs the union winners' counts (~n ids per shard), but
    the previous dict form fanned EVERY pass-1 score into a (shard, id)
    tuple key eagerly — ~8k tuple builds + dict inserts per query at 64
    shards, measured ~3 ms of the ~6 ms serialized host work that
    bounds serving throughput on a 1-core host. Append is O(1) per
    chunk; seed() builds one small per-shard zip-dict on demand (see
    its docstring for why not np.isin)."""

    __slots__ = ("_by_shard", "_n")

    def __init__(self) -> None:
        # shard -> [(ids, scores), ...]: seed() is called once PER
        # SHARD at pass-2 provider init (64 calls/query on the tall
        # config), so a flat chunk list would be rescanned 64x — the
        # first cut of this class did exactly that and profiled at
        # ~3.6 ms/query, as expensive as the dict fanout it replaced
        self._by_shard: dict[int, list] = {}
        self._n = 0

    def __len__(self) -> int:  # `if carry:` seeds only when non-empty
        return self._n

    def add(self, shard: int, ids, scores) -> None:
        # scores may be pow2- or chunk-size-padded past len(ids) (the
        # old dict zip truncated implicitly) — slice, never trust widths
        if len(ids):
            self._by_shard.setdefault(shard, []).append((ids, scores[: len(ids)]))
            self._n += 1

    def add_stacked(self, shards, ids_by_shard, mat) -> None:
        for i, ids in enumerate(ids_by_shard):
            if ids:
                self._by_shard.setdefault(shards[i], []).append(
                    (ids, mat[i][: len(ids)])
                )
                self._n += 1

    def seed(self, shard: int, rids) -> dict[int, int]:
        """{rid: score} for the requested ids present in this carry.
        Chunks are disjoint id ranges per shard (prefix walks), so no
        overwrite ambiguity. Plain zip-dict, deliberately NOT np.isin:
        at the serving sizes (a 128-entry head chunk vs ~n winner ids,
        64 shards/query) isin's fixed per-call overhead profiled at
        ~2 ms/query while the zip build is ~5 us/shard; at deep-walk
        sizes (16k ids) the two are comparable."""
        chunks = self._by_shard.get(shard)
        if not chunks or not rids:
            return {}
        lut: dict[int, object] = {}
        for ids, scores in chunks:
            sc = scores.tolist() if hasattr(scores, "tolist") else scores
            lut.update(zip(ids, sc))
        return {rid: int(lut[rid]) for rid in rids if rid in lut}


def _eval_tree(t, leaves):
    """Evaluate a lowered boolean call tree over leaf word arrays.
    Traced inside jit: the whole chain becomes one XLA fusion. Works
    unbatched (leaves u32[S, W]) and batched (u32[Q, S, W]) — the
    boolean ops are elementwise (reference executor.go:704-1000)."""
    tag = t[0]
    if tag == "leaf":
        return leaves[t[1]]
    acc = _eval_tree(t[1][0], leaves)
    for sub in t[1][1:]:
        v = _eval_tree(sub, leaves)
        if tag == "Intersect":
            acc = ops.and_(acc, v)
        elif tag == "Union":
            acc = ops.or_(acc, v)
        elif tag == "Xor":
            acc = ops.xor_(acc, v)
        else:
            acc = ops.andnot(acc, v)
    return acc


def _make_chain_scorer(ex: "Executor") -> BatchedScorer:
    """Coalescing scorer for fused Count(chain) dispatches: concurrent
    same-shape chains (identical boolean tree + leaf shapes — the key)
    stack their leaves into ONE batched kernel, i32[Q] counts back.
    OFF by default (PILOSA_CHAIN_BATCH=1 enables): on the tunneled
    chip, per-query dispatch pipelines ~50 independent RPCs and
    measured 671 qps at c64 vs 235-297 coalesced — the chain kernel is
    too cheap for batching to amortize, unlike TopN's matrix scan, so
    the leader's serialized fetch rounds only cost depth. Kept for
    deployments where per-dispatch overhead (not round-trip
    pipelining) is the scarce resource. Pads with a repeat of a real
    source (a leaves tuple has no zeros_like); pad lanes' counts are
    never read."""
    return BatchedScorer(
        max_batch=int(os.environ.get("PILOSA_CHAIN_MAX_BATCH", 32)),
        single_fn=ex._chain_count_single,
        batch_fn=ex._chain_count_batch,
        pad_fn=lambda proto: proto,
    )


def _make_stacked_scorer() -> BatchedScorer:
    """Coalescing scorer for the cross-shard stacked-sparse TopN path.
    max_batch bounds the lax.map sweep (default 32: on a tunneled chip
    the scores fetch is ~1 RTT regardless of width, so wide coalesced
    launches are the serving throughput lever; PILOSA_STACKED_MAX_BATCH
    tunes it); num_rows rides in the staged tuple. A factory because
    the device health gate rebuilds it on restore (its queue may be
    held by abandoned workers)."""
    return BatchedScorer(
        max_batch=int(os.environ.get("PILOSA_STACKED_MAX_BATCH", 32)),
        single_fn=lambda src, st: ops.sparse_intersection_counts_stacked(src, *st),
        batch_fn=lambda srcs, st: ops.sparse_intersection_counts_stacked_batch_list(
            srcs, *st
        ),
    )


def _timed_kernel(kind: str, fn, signature=None, recovery=None):
    """Wrap a cached jitted kernel with the compile-vs-execute timing
    split: the FIRST invocation traces + compiles inside XLA (observed
    as spmd.compile_seconds), warm invocations are dispatch only
    (spmd.execute_seconds). When the caller is traced, each invocation
    also lands as a spmd.kernel span.

    This is also the device-leg fence (ISSUE 12): ``block_until_ready``
    on the outputs pins the measurement to real device completion
    instead of async-dispatch return, so the timing feeds the waterfall
    as device.compute and the first call feeds the compile tracker
    under ``signature`` (the canonical plan key of the cached jit).

    And it is the OOM-recovery boundary (ISSUE 14): with ``recovery``
    (an executor's OomRecovery) an allocation failure at dispatch or at
    the fence evicts through the HBM governor and retries ONCE before
    degrading the call to the CPU leg. The chaos hook fires INSIDE the
    attempt, so a retry re-consults the injection counter and passes."""

    state = {"first": True}

    def attempt(*args, **kw):
        cf = chaos.FAULTS
        if cf is not None:
            cf.on_kernel(kind)
        out = fn(*args, **kw)
        try:
            import jax  # lazy, matching this module's other jax uses

            jax.block_until_ready(out)
        except Exception as e:
            # a device fault surfacing at the fence IS the kernel
            # failing — the recovery policy must see it; anything else
            # is a non-jax output with nothing to fence
            if classify_device_error(e) is not None:
                raise
        return out

    def run(*args, **kw):
        t0 = time.monotonic()
        if recovery is not None:
            out = recovery.run(lambda: attempt(*args, **kw), kind=kind)
        else:
            out = attempt(*args, **kw)
        dt = time.monotonic() - t0
        first = state["first"]
        if first:
            state["first"] = False
            metrics.observe(metrics.SPMD_COMPILE_SECONDS, dt, kind=kind)
            profiler.COMPILES.note(kind, signature, dt)
        else:
            metrics.observe(metrics.SPMD_EXECUTE_SECONDS, dt, kind=kind)
        trace.attrib_add(trace.WF_DEVICE_COMPUTE, dt)
        sp = trace.current()
        if sp is not None:
            sp.record(metrics.STAGE_SPMD_KERNEL, t0, dt, kind=kind, first=first)
        return out

    return run


# post-OOM-degrade cooldown: after a device call degrades to CPU, the
# device predicates stay CPU-forced this long so the immediate re-run
# (and the next waves) don't launch straight back into the same OOM
OOM_CPU_COOLDOWN_S = 30.0


def _fetch(arr) -> np.ndarray:
    """Materialize a device result on host, crediting the D2H
    transfer+decode waterfall leg when attribution is active."""
    if trace.attrib_current() is None:
        return np.asarray(arr)
    t0 = time.monotonic()
    out = np.asarray(arr)
    trace.attrib_add(trace.WF_TRANSFER_DECODE, time.monotonic() - t0)
    return out


class Executor:
    def __init__(
        self,
        holder,
        cluster=None,
        node=None,
        stager: Optional[DeviceStager] = None,
        device_policy: str = "auto",
        translate_store=None,
        max_writes_per_request: int = 5000,
        mesh=None,
        health=None,
        auto_min_containers: Optional[int] = None,
        plan_cache=None,
        dispatch_enabled: Optional[bool] = None,
        dispatch_max_wave: int = 16,
        dispatch_max_inflight: int = 2,
        dispatch_stage_ahead: int = 1,
        prefetch_enabled: Optional[bool] = None,
        prefetch_depth: int = 2,
        fusion_enabled: Optional[bool] = None,
        fusion_max_calls: int = 64,
        plan_cache_device_bytes: Optional[int] = None,
        governor: Optional[HbmGovernor] = None,
        analytics_max_groups: Optional[int] = None,
    ) -> None:
        self.holder = holder
        self.cluster = cluster  # None = single-node
        self.node = node
        # A mesh turns the shard-batched device path SPMD: stacks stage
        # split over the mesh's shard axis and Count/Sum/TopN terminals
        # lower to shard_map kernels whose cross-shard reduces are ICI
        # collectives (parallel/spmd.py) — the reference's per-node
        # HTTP scatter-gather (executor.go:1444-1593) inside one program.
        self.mesh = mesh
        self.stager = stager or DeviceStager(mesh=mesh)
        if mesh is not None and self.stager.mesh is not mesh:
            # a shared stager staging on a different (or no) mesh would
            # hand the SPMD kernels wrongly-placed arrays — fail loud
            raise ValueError("executor mesh differs from the stager's mesh")
        self.device_policy = device_policy
        self.translate_store = translate_store
        self.max_writes_per_request = max_writes_per_request
        # GroupBy cross-product bound: a panel larger than this fails
        # loudly before K row stacks are staged into HBM
        self.analytics_max_groups = (
            int(analytics_max_groups)
            if analytics_max_groups is not None
            else analytics.DEFAULT_MAX_GROUPS
        )
        # coalesces concurrent TopN scoring against the same staged
        # matrix into one batched kernel launch (see batcher.py)
        self.scorer = BatchedScorer()
        # concurrent cross-shard TopN queries sharing a staged candidate
        # chunk (the common case: every TopN's pass-1 head is the same
        # cache-rankings prefix) coalesce into one stacked kernel launch
        # — one device round-trip serves the whole batch.
        self.stacked_scorer = _make_stacked_scorer()
        # concurrent same-shape Count(chain) queries CAN coalesce into
        # one batched tree-count launch (see _make_chain_scorer); off by
        # default — measured slower than per-query RPC pipelining on the
        # tunneled chip (rationale at the _execute_count call site)
        self._chain_batch = os.environ.get("PILOSA_CHAIN_BATCH", "0") == "1"
        self.chain_scorer = _make_chain_scorer(self)
        # optional device health gate (executor/devicehealth.py):
        # serving deployments pass one so a wedged accelerator degrades
        # reads to the CPU roaring path instead of hanging them; bare
        # executors (tests, benches) skip the per-call guard hop
        self.health = health
        if health is not None:
            health.on_restore = self._on_device_restore
        # multihost gang runtime (parallel/multihost.py). When set (the
        # server wires it on the leader rank of a jax.distributed
        # deployment), non-remote queries entering execute() are routed
        # through the gang: the descriptor broadcasts to every rank and
        # all processes enter the identical execution in lockstep —
        # required because this executor's mesh spans processes, so any
        # SPMD kernel IS a multi-process collective program.
        self.gang = None
        # generation-stamped query result cache (plan/cache.py). None =
        # disabled (the default for bare executors, so tests and benches
        # opt in explicitly); the server wires one per process. Only
        # consulted for locally-executed reads — on a cluster each
        # shard owner caches its own remote legs, because only IT can
        # see its fragments' generations.
        self.plan_cache = plan_cache
        # fused count-of-tree programs keyed by query structure
        self._tree_jits: dict[str, Any] = {}
        # batched variants keyed by (structure, pow2 width)
        self._tree_batch_jits: dict[tuple, Any] = {}
        # auto-policy crossover, in estimated touched containers (see
        # _touched_containers + AUTOTUNE.json). The default assumes a
        # co-located chip (~1-2 ms dispatch ⇒ crossover ~10^2); deploys
        # behind a high-RTT tunnel should raise it (the measured tunnel
        # crossover on this rig is ~3,700). Precedence: explicit
        # constructor value (the server plumbs its config knob here) >
        # PILOSA_AUTO_DEVICE_MIN_CONTAINERS env > AUTOTUNE default.
        if auto_min_containers is not None:
            self.auto_min_containers = int(auto_min_containers)
        else:
            self.auto_min_containers = int(
                os.environ.get(
                    "PILOSA_AUTO_DEVICE_MIN_CONTAINERS", AUTO_DEVICE_MIN_CONTAINERS
                )
            )
        self._read_pool = None  # lazy; see execute()
        self._read_pool_mu = threading.Lock()
        # checkout refcount + closing flag: close() drains active
        # pool.map users instead of nulling the attr under them, and a
        # checkout during shutdown gets None (the caller runs the calls
        # serially inline) — see _read_pool_acquire
        self._read_pool_cv = threading.Condition(self._read_pool_mu)
        self._read_pool_users = 0
        self._read_pool_closing = False
        # continuous-batching async dispatch engine (dispatch.py):
        # eligible local reads entering execute() submit a future and
        # wait instead of blocking through the call tree, so concurrent
        # heterogeneous plans coalesce into device waves. The loop
        # thread starts lazily on first submit. PILOSA_DISPATCH=0 turns
        # it off for bare executors (benches A/B it); the server passes
        # its dispatch-* knobs explicitly.
        if dispatch_enabled is None:
            dispatch_enabled = os.environ.get("PILOSA_DISPATCH", "1") != "0"
        if dispatch_enabled:
            from pilosa_tpu.executor.dispatch import DispatchEngine

            self.dispatch_engine = DispatchEngine(
                self,
                max_wave=dispatch_max_wave,
                max_inflight=dispatch_max_inflight,
                stage_ahead=dispatch_stage_ahead,
            )
        else:
            self.dispatch_engine = None
        # plan-driven prefetch scheduler (executor/tiering.py): the
        # dispatch engine's wave builder hands it queued plans so the
        # NEXT waves' Row blocks promote T1/T2 → T0 ahead of compute,
        # with accuracy attribution. Replaces the thunk-based advisory
        # warm when enabled; PILOSA_PREFETCH=0 reverts for A/B.
        if prefetch_enabled is None:
            prefetch_enabled = os.environ.get("PILOSA_PREFETCH", "1") != "0"
        if prefetch_enabled and self.dispatch_engine is not None:
            from pilosa_tpu.executor.tiering import PrefetchScheduler

            self.prefetcher = PrefetchScheduler(self, depth=prefetch_depth)
        else:
            self.prefetcher = None
        # whole-query device fusion (fusion.py): multi-call read queries
        # — and the multi-call Queries the dispatch engine combines a
        # wave into — lower to ONE jitted program, intermediates stay in
        # HBM, only final scalars/score heads transfer. PILOSA_FUSION=0
        # turns it off for bare executors (benches A/B it); the server
        # passes its fusion-* knobs explicitly.
        if fusion_enabled is None:
            fusion_enabled = os.environ.get("PILOSA_FUSION", "1") != "0"
        if fusion_enabled:
            from pilosa_tpu.executor.fusion import QueryFuser

            self.fuser = QueryFuser(self, max_calls=fusion_max_calls)
        else:
            self.fuser = None
        # device-resident plan cache (plan/cache.py DevicePlanCache):
        # __cached subtree stacks stay in HBM instead of round-tripping
        # through host Row decode + re-pack + re-upload. 0 disables;
        # single-device only (mesh placement differs — gated at the
        # probe site in _device_bitmap_stack).
        if plan_cache_device_bytes is None:
            plan_cache_device_bytes = int(
                os.environ.get("PILOSA_PLAN_CACHE_DEVICE_BYTES", 256 << 20)
            )
        if plan_cache_device_bytes > 0 and self.plan_cache is not None:
            from pilosa_tpu.plan.cache import DevicePlanCache

            self.device_cache = DevicePlanCache(plan_cache_device_bytes)
        else:
            self.device_cache = None
        # compiled shard_map kernels keyed by (kind, static args) — the
        # closures in spmd.py are rebuilt per call, so cache here to keep
        # XLA's jit cache effective across queries
        self._spmd_kernels: dict[tuple, Any] = {}
        self._spmd_mu = threading.Lock()
        # one HBM byte ledger for every device-resident tenant
        # (executor/hbm.py): the stager, the device plan cache, and the
        # batcher pad scratch stop overcommitting the chip through
        # disjoint budgets — their old knobs become per-tenant shares
        self.governor = governor if governor is not None else HbmGovernor()
        self.stager.set_governor(self.governor)
        if self.device_cache is not None:
            self.device_cache.set_governor(self.governor)
        for sc in (self.scorer, self.stacked_scorer, self.chain_scorer):
            sc.set_governor(self.governor)
        # OOM recovery policy shared by every device-call boundary:
        # evict → retry once → degrade this call to the CPU leg; the
        # health gate trips only on repeat unrecovered failures
        self._oom_cpu_until = 0.0
        self.oom_cpu_cooldown_s = float(
            os.environ.get("PILOSA_OOM_CPU_COOLDOWN_S", OOM_CPU_COOLDOWN_S)
        )
        self._oom = OomRecovery(
            governor=self.governor,
            health=self.health,
            on_degrade=self._on_oom_degrade,
        )

    def _spmd_kernel(self, kind: str, *statics):
        key = (kind,) + statics
        with self._spmd_mu:
            fn = self._spmd_kernels.get(key)
            if fn is None:
                from pilosa_tpu.parallel import spmd

                if kind == "count":
                    fn = spmd.count_stack_spmd(self.mesh)
                elif kind == "plane_counts":
                    fn = spmd.bsi_sum_spmd(self.mesh, *statics)
                elif kind == "topn_scores_sparse":
                    fn = spmd.topn_scores_sparse_spmd(self.mesh, *statics)
                else:
                    raise ValueError(kind)
                fn = _timed_kernel(kind, fn, signature=key, recovery=self._oom)
                self._spmd_kernels[key] = fn
            return fn

    def _shard_plan(self, shards: list[int]) -> list[int]:
        """Pad the shard list to a mesh-size multiple (padding shards
        have no fragments and stage as zero words — identity for every
        reduce). No-op without a mesh."""
        if self.mesh is None:
            return shards
        from pilosa_tpu.parallel.spmd import ShardBatchPlan

        return ShardBatchPlan(self.mesh, shards).padded

    # -- entry point (reference Execute, executor.go:83) ---------------------

    def execute(
        self,
        index_name: str,
        query,
        shards: Optional[list[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> list[Any]:
        gang = self.gang
        if gang is not None and gang.should_dispatch_query(
            bool(opt is not None and opt.remote),
            query if isinstance(query, str) else str(query),
        ):
            # multihost leader: broadcast the descriptor so every rank
            # enters this execution in lockstep (the mesh spans
            # processes — executing here alone would deadlock the first
            # collective). The gang thread re-enters execute() with the
            # in-gang flag set and falls through to the normal path.
            from pilosa_tpu.parallel import multihost

            desc = multihost.query_descriptor(
                index_name,
                query if isinstance(query, str) else str(query),
                shards,
                opt or ExecOptions(),
                trace_ctx=trace.current_ctx(),
            )
            dl = _deadline().current()
            sp = trace.current()
            if sp is None:
                return gang.dispatch(desc, deadline=dl)
            with sp.child(metrics.STAGE_GANG, plan=desc.payload.get("plan")):
                return gang.dispatch(desc, deadline=dl)
        engine = self.dispatch_engine
        if engine is not None and self._engine_eligible(opt):
            parsed = parse(query) if isinstance(query, str) else query
            if parsed.write_call_n() == 0:
                fut = engine.submit(
                    index_name,
                    parsed,
                    shards,
                    opt or ExecOptions(),
                    deadline=_deadline().current(),
                    text=query if isinstance(query, str) else None,
                    trace_ctx=trace.current_ctx(),
                )
                if fut is not None:  # None: engine closing -> inline
                    return fut.result()
            query = parsed  # already parsed; don't redo it below
        sp = trace.current()
        if sp is None:  # untraced: no span objects anywhere below
            return self._execute(index_name, query, shards, opt)
        with sp.child(metrics.STAGE_EXECUTOR, index=index_name):
            return self._execute(index_name, query, shards, opt)

    def _engine_eligible(self, opt) -> bool:
        """Route this execute() through the async dispatch engine?
        Only plain local reads: the PR 5/6 gang determinism contract
        keeps multihost/federation execution ``serial`` and
        engine-free; cluster fan-out and remote legs have their own
        scheduling; traced queries must show real execution in their
        span tree; and a thread already inside a wave re-enters inline
        rather than deadlocking against its own runner slot."""
        if self.gang is not None or self.cluster is not None:
            return False
        if opt is not None and (opt.remote or opt.serial):
            return False
        if trace.current() is not None:
            return False
        return not self.dispatch_engine.in_wave()

    def _execute(
        self,
        index_name: str,
        query,
        shards: Optional[list[int]] = None,
        opt: Optional[ExecOptions] = None,
    ) -> list[Any]:
        if isinstance(query, str):
            query = parse(query)
        opt = opt or ExecOptions()
        # deadline boundary: a request whose deadline passed while it
        # crossed the API layer is cancelled before any shard work
        dl = _deadline().current()
        if dl is not None:
            dl.check(metrics.STAGE_EXECUTOR)
        idx = self.holder.index(index_name)
        if idx is None:
            raise NotFoundError(f"index not found: {index_name}")
        if (
            self.max_writes_per_request
            and query.write_call_n() > self.max_writes_per_request
        ):
            raise ValueError(
                f"too many writes: {query.write_call_n()} > {self.max_writes_per_request}"
            )
        if shards is None and self._needs_shards(query.calls):
            shards = list(range(idx.max_shard() + 1))
        if self.translate_store is not None and not opt.remote:
            # keys→ids BEFORE canonicalization (plan/planner.py): the
            # CSE hashes, plan-cache keys, and dispatch signatures all
            # see resolved integer ids only
            from pilosa_tpu.plan import planner as _planner

            _planner.resolve_keys(self, index_name, idx, query.calls)
        calls = query.calls
        if (
            self.plan_cache is not None
            and opt.cache
            and self._local_batchable(opt)
            and shards
            and query.write_call_n() == 0
        ):
            # CSE against the result cache (plan/planner.py): repeated
            # bitmap subtrees across this query's calls — which, via the
            # pipeline's cross-request combiner, may span a whole gang
            # of coalesced HTTP requests — execute once, and subtrees
            # already cached feed back in as materialized rows. Local
            # execution only: __cached placeholders never serialize.
            from pilosa_tpu.plan import planner

            t0_cse = time.monotonic()
            with trace.child(metrics.STAGE_PLAN_CANON):
                calls = planner.rewrite_for_cse(
                    self, index_name, query.calls, shards, opt
                )
            trace.attrib_add(trace.WF_PLAN_CANON, time.monotonic() - t0_cse)
        # whole-query fusion (fusion.py): lower the fusable calls of a
        # multi-call read into ONE jitted launch; residual calls fall
        # through to the per-call paths below and results merge
        # positionally. Gang/serial/remote/cluster legs bypass inside
        # try_execute, mirroring the dispatch-engine contract.
        fused: dict[int, Any] = {}
        if (
            self.fuser is not None
            # a single analytic call is itself a K-way panel — worth a
            # fused launch even without a second call to share it with
            and (
                len(calls) > 1
                or any(c.name in analytics.ANALYTIC_CALLS for c in calls)
            )
            and query.write_call_n() == 0
            and not opt.serial
            and shards
        ):
            fused = self.fuser.try_execute(index_name, calls, shards, opt) or {}
        run_calls = (
            [c for i, c in enumerate(calls) if i not in fused] if fused else calls
        )
        if len(run_calls) > 1 and query.write_call_n() == 0 and not opt.serial:
            # An all-read request has no cross-call ordering constraints
            # (the reference runs calls serially, executor.go:126-145,
            # but read results are order-independent); running them
            # concurrently lets the BatchedScorer coalesce their TopN
            # scoring into batched kernel launches — the intra-request
            # form of continuous micro-batching.
            pool = self._read_pool_acquire()
            parent = trace.current()  # contextvars don't follow pool workers
            pdl = dl  # nor does the request deadline
            attrib = trace.attrib_current()  # nor the waterfall accumulator

            def run_call(call):
                with trace.activate(parent), _deadline().activate(pdl), trace.attrib_activate(attrib):
                    return self._execute_call(index_name, call, shards, opt)

            if pool is None:
                # close() in progress: run serially inline instead of
                # racing a shutting-down pool
                results = [run_call(c) for c in run_calls]
            else:
                try:
                    results = list(pool.map(run_call, run_calls))
                finally:
                    self._read_pool_release()
        else:
            results = []
            for call in run_calls:
                results.append(self._execute_call(index_name, call, shards, opt))
        if fused:
            it = iter(results)
            results = [
                fused[i] if i in fused else next(it) for i in range(len(calls))
            ]
        if self.translate_store is not None and not opt.remote:
            results = [
                self._translate_result(index_name, idx, call, r)
                for call, r in zip(calls, results)
            ]
        return results

    # -- key translation (reference translateCall/translateResult,
    #    executor.go:1595-1696) --------------------------------------------

    def _translate_call(self, index, idx, c: Call) -> None:
        # delegated to the translate subsystem (translate/resolve.py);
        # kept as a method so direct callers and tests keep working
        from pilosa_tpu.translate import resolve

        resolve.resolve_call(self.translate_store, index, idx, c)

    def _translate_result(self, index, idx, call: Call, result):
        from pilosa_tpu.translate import resolve

        return resolve.translate_result(
            self.translate_store, index, idx, call, result
        )

    @staticmethod
    def _needs_shards(calls: list[Call]) -> bool:
        for c in calls:
            if c.name not in ("Clear", "Set", "SetRowAttrs", "SetColumnAttrs", "SetValue"):
                return True
        return False

    # -- dispatch (reference executeCall, executor.go:165) -------------------

    def _cpu_forced(self) -> bool:
        """True while the device gate is tripped OR the post-OOM-degrade
        cooldown is running. Checked by the device predicates, so it
        applies on EVERY thread — including cluster map-reduce pool
        workers — without per-thread state."""
        if self.health is not None and not self.health.healthy:
            return True
        return time.monotonic() < self._oom_cpu_until

    def _on_oom_degrade(self) -> None:
        """A device call degraded to CPU after failed OOM recovery:
        force the CPU predicates for a cooldown so the immediate re-run
        (and the next waves) don't launch straight back into the OOM."""
        self._oom_cpu_until = time.monotonic() + self.oom_cpu_cooldown_s

    def _on_device_restore(self) -> None:
        """Replace machinery whose locks abandoned guard workers may
        hold forever (a dispatcher hung inside a dead kernel launch
        keeps its per-fragment dispatch lock; a hung staging upload
        keeps the stager's). Fresh instances start clean; zombies keep
        mutating their orphaned predecessors harmlessly."""
        self.scorer = BatchedScorer()
        self.stacked_scorer = _make_stacked_scorer()
        self.chain_scorer = _make_chain_scorer(self)
        # the ledger must forget the dead runtime's pad scratch with
        # the scorers; fresh instances re-register at zero
        self.governor.reset("batcher")
        for sc in (self.scorer, self.stacked_scorer, self.chain_scorer):
            sc.set_governor(self.governor)
        self._oom_cpu_until = 0.0
        self.stager.reset_after_wedge()
        if self.plan_cache is not None:
            # results computed by the wedged device must not outlive it
            self.plan_cache.epoch_reset()
        if self.device_cache is not None:
            # ditto for HBM-resident arrays: handles created by the dead
            # runtime may be invalid
            self.device_cache.epoch_reset()

    def _execute_call(self, index, c: Call, shards, opt) -> Any:
        metrics.count(metrics.EXECUTOR_CALLS, call=c.name)
        sp = trace.current()
        if sp is None:
            return self._execute_call_cached(index, c, shards, opt)
        with sp.child(metrics.STAGE_CALL, call=c.name):
            return self._execute_call_cached(index, c, shards, opt)

    def _execute_call_cached(self, index, c: Call, shards, opt) -> Any:
        """Whole-call result cache around dispatch (plan/cache.py): a
        generation-valid entry answers without touching the executor;
        a miss executes under singleflight and stamps the entry with
        the pre-build generation vector. Uncacheable calls (writes,
        attr-dependent reads, malformed args) and non-local execution
        dispatch straight through."""
        from pilosa_tpu.pql.ast import WRITE_CALLS

        pc = self.plan_cache
        if (
            pc is None
            or not opt.cache
            or not self._local_batchable(opt)
            or shards is None
            or c.name in WRITE_CALLS
        ):
            return self._execute_call_guarded(index, c, shards, opt)
        from pilosa_tpu.plan import planner

        keyinfo = planner.call_cache_key(self, index, c, shards, opt)
        if keyinfo is None:
            return self._execute_call_guarded(index, c, shards, opt)
        key, genvec_fn = keyinfo
        return pc.get_or_build(
            key,
            genvec_fn,
            lambda: self._execute_call_guarded(index, c, shards, opt),
        )

    def _execute_call_guarded(self, index, c: Call, shards, opt) -> Any:
        """Read calls run under the device health gate when one is
        configured: a wedged accelerator trips the gate and the same
        call re-runs on the CPU roaring path (reads are pure — safe to
        re-run; the gate state itself forces the CPU predicates, so the
        re-run is device-free on every thread). Writes never touch the
        device and skip the guard."""
        from pilosa_tpu.pql.ast import WRITE_CALLS

        if c.name in WRITE_CALLS:
            # writes never touch the device: no guard, no OOM fallback
            return self._execute_call_inner(index, c, shards, opt)
        guarded = (
            self.health is not None
            and self.device_policy != "never"
            and not self._cpu_forced()
        )
        try:
            if guarded:
                # the guard pool is another thread: hand the span over
                parent = trace.current()
                return self.health.guard(
                    lambda: self._execute_call_inner_on(parent, index, c, shards, opt)
                )
            return self._execute_call_inner(index, c, shards, opt)
        except DeviceDown:
            # gate closed, or an unrecovered OOM degraded this call
            # (DeviceOom): the CPU predicates are already forced (gate
            # state / OOM cooldown), so the re-run is device-free
            metrics.count(metrics.EXECUTOR_DEVICE_DOWN_FALLBACK)
        except Exception as e:
            # a raw device fault that escaped the kernel boundaries
            # (e.g. surfaced at a batcher fetch): apply the same
            # recovery policy here — classify, journal, evict, set the
            # CPU cooldown — then serve from the CPU leg
            if classify_device_error(e) is None:
                raise

            def _reraise():
                raise e

            try:
                self._oom.run(_reraise, kind="call")
            except DeviceOom:
                pass
            metrics.count(metrics.EXECUTOR_DEVICE_DOWN_FALLBACK)
        return self._execute_call_inner(index, c, shards, opt)

    def _execute_call_inner_on(self, parent, index, c, shards, opt) -> Any:
        with trace.activate(parent):
            return self._execute_call_inner(index, c, shards, opt)

    def _execute_call_inner(self, index, c: Call, shards, opt) -> Any:
        name = c.name
        if name == "Sum":
            return self._execute_sum(index, c, shards, opt)
        if name == "Min":
            return self._execute_min(index, c, shards, opt)
        if name == "Max":
            return self._execute_max(index, c, shards, opt)
        if name == "Clear":
            return self._execute_clear_bit(index, c, opt)
        if name == "Count":
            return self._execute_count(index, c, shards, opt)
        if name == "Set":
            return self._execute_set_bit(index, c, opt)
        if name == "SetValue":
            self._execute_set_value(index, c, opt)
            return None
        if name == "SetRowAttrs":
            self._execute_set_row_attrs(index, c, opt)
            return None
        if name == "SetColumnAttrs":
            self._execute_set_column_attrs(index, c, opt)
            return None
        if name == "TopN":
            return self._execute_topn(index, c, shards, opt)
        if name == "GroupBy":
            return self._execute_groupby(index, c, shards, opt)
        if name == "Distinct":
            return self._execute_distinct(index, c, shards, opt)
        if name == "Percentile":
            return self._execute_percentile(index, c, shards, opt)
        if name == "Rows":
            raise ValueError("Rows() can only be used inside GroupBy()")
        return self._execute_bitmap_call(index, c, shards, opt)

    # -- map/reduce seam -----------------------------------------------------

    def _map_reduce(self, index, shards, c, opt, map_fn, reduce_fn, zero_factory=None):
        """Single-node: loop shards in order (deterministic reduce order —
        the reference's goroutine fan-in is arrival-ordered). The cluster
        layer overrides this via self.cluster.map_reduce.

        zero_factory builds a FRESH accumulator: reduce_fn may mutate its
        first argument (Row.merge), and mapped values can be cached
        fragment rows that must never be mutated."""
        if self.cluster is not None and not opt.remote:
            return self.cluster.map_reduce(
                index, shards, c, opt, map_fn, reduce_fn, zero_factory
            )
        result = zero_factory() if zero_factory else None
        # captured ONCE: the untraced loop body pays a single branch per
        # shard, no span objects (ISSUE 1 overhead bound); same for the
        # deadline — one contextvar read, then a monotonic compare per
        # shard, so expired work stops at the next shard boundary
        # instead of finishing a result nobody will read
        parent = trace.current()
        dl = _deadline().current()
        attrib = trace.attrib_current()  # same single-capture discipline
        # heat ledger read hook, captured once per query like the tracer:
        # the per-shard body pays one is-not-None branch when disabled
        if heat.LEDGER.enabled:
            _heat_read = heat.LEDGER.record_read
            try:
                _heat_field = c.field_arg()
            except (ValueError, AttributeError):
                _heat_field = ""
        else:
            _heat_read = None
            _heat_field = ""
        for shard in shards:
            if dl is not None:
                dl.check(metrics.STAGE_MAP_SHARD)
            if _heat_read is not None:
                _heat_read(index, _heat_field, shard)
            if parent is not None:
                with parent.child(metrics.STAGE_MAP_SHARD, shard=shard):
                    v = map_fn(shard)
            else:
                v = map_fn(shard)
            if result is None:
                result = v
            elif attrib is None:
                result = reduce_fn(result, v)
            else:
                t0r = time.monotonic()
                result = reduce_fn(result, v)
                attrib[trace.WF_REDUCE] = attrib.get(trace.WF_REDUCE, 0.0) + (
                    time.monotonic() - t0r
                )
        return result

    def _heat_read_legs(self, index, c, shards) -> None:
        """Shard-batched device launches (Count/Sum/TopN stacks, fused
        whole-query reads) bypass ``_map_reduce``'s per-shard loop, so
        their read legs land here — one per shard in the stack, same
        accounting as the serial path."""
        if not heat.LEDGER.enabled or not shards:
            return
        try:
            field = c.field_arg()
        except (ValueError, AttributeError):
            field = ""
        rec = heat.LEDGER.record_read
        for s in shards:
            rec(index, field, s)

    def _analytics_heat_legs(self, index, fields, shards) -> None:
        """Analytic segmented-reduction launches bypass ``_map_reduce``'s
        per-shard loop AND touch several fields per launch (dimension
        rows + aggregate planes), so their legs record here: one read
        per (field, shard), same accounting as the serial path."""
        if not heat.LEDGER.enabled or not shards:
            return
        rec = heat.LEDGER.record_read
        for f in fields:
            for s in shards:
                rec(index, f, s)

    # -- bitmap calls ---------------------------------------------------------

    def _execute_bitmap_call(self, index, c: Call, shards, opt) -> Row:
        def map_fn(shard):
            return self._bitmap_call_shard(index, c, shard)

        def reduce_fn(prev: Row, v: Row) -> Row:
            prev.merge(v)
            return prev

        other = self._map_reduce(index, shards, c, opt, map_fn, reduce_fn, zero_factory=Row)

        # Attach attributes for top-level Row() calls
        # (reference executeBitmapCall, executor.go:338-385).
        if c.name == "Row" and not opt.exclude_row_attrs:
            field_name = c.field_arg()
            fld = self.holder.field(index, field_name)
            if fld is not None and fld.row_attr_store is not None:
                row_id, ok = c.uint_arg(field_name)
                if ok:
                    attrs = fld.row_attr_store.attrs(row_id)
                    other.attrs = attrs or {}
        return other

    def _bitmap_call_shard(self, index, c: Call, shard: int) -> Row:
        """reference executeBitmapCallShard (executor.go:388-405)."""
        if self._use_device(index, c, shard):
            try:
                words = self._device_bitmap(index, c, shard)
                return _row_from_device(words, shard)
            except _NotDeviceable:
                pass
        return self._bitmap_call_shard_cpu(index, c, shard)

    def _bitmap_call_shard_cpu(self, index, c: Call, shard: int) -> Row:
        name = c.name
        if name == "__cached":
            # planner-substituted subtree (plan/planner.py): the
            # materialized per-shard rows ARE the result
            seg = c.args["_row"].shard_segment(shard)
            if seg is None:
                return Row()
            return Row.from_segment(shard, seg)
        if name == "Row":
            return self._row_shard(index, c, shard)
        if name == "Difference":
            return self._nary_shard(index, c, shard, "difference", require=True)
        if name == "Intersect":
            return self._nary_shard(index, c, shard, "intersect", require=True)
        if name == "Range":
            return self._range_shard(index, c, shard)
        if name == "Union":
            return self._nary_shard(index, c, shard, "union", require=False)
        if name == "Xor":
            return self._nary_shard(index, c, shard, "xor", require=False)
        raise ValueError(f"unknown call: {name}")

    def _row_shard(self, index, c: Call, shard: int) -> Row:
        field_name = c.field_arg()
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise ValueError(f"Row() must specify {field_name}")
        frag = self.holder.fragment(index, field_name, VIEW_STANDARD, shard)
        if frag is None:
            return Row()
        return frag.row(row_id)

    def _nary_shard(self, index, c: Call, shard: int, op: str, require: bool) -> Row:
        if require and not c.children:
            raise ValueError(f"empty {c.name} query is currently not supported")
        other = Row()
        for i, child in enumerate(c.children):
            row = self._bitmap_call_shard(index, child, shard)
            other = row if i == 0 else getattr(other, op)(row)
        other.invalidate_count()
        return other

    def _range_shard(self, index, c: Call, shard: int) -> Row:
        """reference executeRangeShard / executeBSIGroupRangeShard."""
        if c.has_condition_arg():
            return self._bsi_range_shard(index, c, shard)
        # time range over quantum views
        field_name = c.field_arg()
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise ValueError("Range() must specify row")
        start_str, ok = c.string_arg("_start")
        if not ok:
            raise ValueError("Range() start time required")
        end_str, ok = c.string_arg("_end")
        if not ok:
            raise ValueError("Range() end time required")
        start = datetime.strptime(start_str, TIME_FORMAT)
        end = datetime.strptime(end_str, TIME_FORMAT)
        q = f.time_quantum()
        if not q:
            return Row()
        row = Row()
        for view in views_by_time_range(VIEW_STANDARD, start, end, q):
            frag = self.holder.fragment(index, field_name, view, shard)
            if frag is None:
                continue
            row = row.union(frag.row(row_id))
        return row

    def _bsi_range_shard(self, index, c: Call, shard: int) -> Row:
        if len(c.args) == 0:
            raise ValueError("Range(): condition required")
        if len(c.args) > 1:
            raise ValueError("Range(): too many arguments")
        ((field_name, cond),) = c.args.items()
        if not isinstance(cond, Condition):
            raise ValueError(f"Range(): expected condition argument, got {cond!r}")
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        bsig = f.bsi_group(field_name)
        if bsig is None:
            raise NotFoundError(f"bsiGroup not found: {field_name}")
        frag = self.holder.fragment(
            index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, shard
        )

        # != null
        if cond.op == NEQ and cond.value is None:
            if frag is None:
                return Row()
            return frag.not_null(bsig.bit_depth())

        if cond.op == BETWEEN:
            predicates = cond.int_slice_value()
            if len(predicates) != 2:
                raise ValueError(
                    "Range(): BETWEEN condition requires exactly two integer values"
                )
            base_min, base_max, out_of_range = bsig.base_value_between(*predicates)
            if out_of_range:
                return Row()
            if frag is None:
                return Row()
            if predicates[0] <= bsig.min and predicates[1] >= bsig.max:
                return frag.not_null(bsig.bit_depth())
            return frag.range_between(bsig.bit_depth(), base_min, base_max)

        value = cond.value
        if not isinstance(value, int) or isinstance(value, bool):
            raise ValueError("Range(): conditions only support integer values")
        base_value, out_of_range = bsig.base_value(cond.op, value)
        if out_of_range and cond.op != NEQ:
            return Row()
        if frag is None:
            return Row()
        # fully-encompassing ranges return all not-null
        if (
            (cond.op == "<" and value > bsig.max)
            or (cond.op == "<=" and value >= bsig.max)
            or (cond.op == ">" and value < bsig.min)
            or (cond.op == ">=" and value <= bsig.min)
        ):
            return frag.not_null(bsig.bit_depth())
        if out_of_range and cond.op == NEQ:
            return frag.not_null(bsig.bit_depth())
        return frag.range_op(cond.op, bsig.bit_depth(), base_value)

    # -- device path ---------------------------------------------------------

    def _use_device(self, index, c: Call, shard: int) -> bool:
        use = self._use_device_decide(index, c, shard)
        metrics.count(
            metrics.EXECUTOR_ROUTE_DEVICE if use else metrics.EXECUTOR_ROUTE_CPU,
            call=c.name,
        )
        sp = trace.current()
        if sp is not None:
            sp.event(
                metrics.STAGE_ROUTE,
                call=c.name,
                shard=shard,
                path="device" if use else "cpu",
            )
        return use

    def _use_device_decide(self, index, c: Call, shard: int) -> bool:
        if self.device_policy == "never" or self._cpu_forced():
            return False
        if self.device_policy == "always":
            return True
        return self._touched_containers(index, c, shard) >= self.auto_min_containers

    def _touched_containers(self, index, c: Call, shard: int) -> int:
        """Estimated container blocks this call subtree READS in this
        shard — the CPU path's cost driver. Counting the fragment's
        total containers (the old heuristic) mischooses the device for
        a 2-row query on a tall fragment. Measured on the real chip
        (AUTOTUNE.json): CPU ≈ 0.02 ms per touched container; the
        device dispatch is flat, so the crossover is a touched-container
        threshold."""
        total = 0
        if c.name == "Row":
            try:
                fname = c.field_arg()
            except ValueError:
                fname = None
            if fname:
                frag = self.holder.fragment(index, fname, VIEW_STANDARD, shard)
                if frag is not None:
                    row_id, _ = c.uint_arg(fname)
                    total += frag.sparse_block_count([row_id])
        elif c.name == "Range" and c.has_condition_arg():
            for fname in c.args:
                f = self.holder.field(index, fname)
                bsig = f.bsi_group(fname) if f is not None else None
                frag = self.holder.fragment(
                    index, fname, VIEW_BSI_GROUP_PREFIX + fname, shard
                )
                if frag is not None and bsig is not None:
                    total += frag.sparse_block_count(
                        list(range(bsig.bit_depth() + 1))
                    )
        elif c.name == "Range":
            # time-range form: the row is read once per quantum view in
            # the span, so the cost estimate sums containers across
            # views. Without this branch the estimate was 0 and the
            # auto policy NEVER routed time ranges to the (existing)
            # shard-stacked device lowering — the CPU roaring union was
            # the only path that ever ran (VERDICT §6).
            total += self._time_range_containers(index, c, shard)
        elif c.name in ("GroupBy", "Distinct", "Percentile", "Rows"):
            total += self._analytics_containers(index, c, shard)
        for child in c.children:
            total += self._touched_containers(index, child, shard)
        return total

    def _time_range_containers(self, index, c: Call, shard: int) -> int:
        """Touched-container estimate for a time-range Range() — the
        queried row's container count summed over every quantum view in
        [start, end]. Malformed args estimate 0 (the execution path
        raises the real error)."""
        try:
            field_name = c.field_arg()
            row_id, ok = c.uint_arg(field_name)
            start_str, ok1 = c.string_arg("_start")
            end_str, ok2 = c.string_arg("_end")
            if not (ok and ok1 and ok2):
                return 0
            f = self.holder.field(index, field_name)
            if f is None:
                return 0
            q = f.time_quantum()
            if not q:
                return 0
            start = datetime.strptime(start_str, TIME_FORMAT)
            end = datetime.strptime(end_str, TIME_FORMAT)
        except ValueError:
            return 0
        total = 0
        for view in views_by_time_range(VIEW_STANDARD, start, end, q):
            frag = self.holder.fragment(index, field_name, view, shard)
            if frag is not None:
                total += frag.sparse_block_count([row_id])
        return total

    def _analytics_containers(self, index, c: Call, shard: int) -> int:
        """Touched-container estimate for the analytic calls. A Rows()
        dimension reads every listed (or discovered) row; Distinct /
        Percentile / a GroupBy Sum aggregate read the field's full BSI
        plane set. Filter subtrees and nested Rows() dimensions are
        counted by the caller's child recursion."""
        total = 0
        if c.name == "Rows":
            fname, ok = c.string_arg("_field")
            if ok and fname:
                frag = self.holder.fragment(index, fname, VIEW_STANDARD, shard)
                if frag is not None:
                    ids, has_ids = c.uint_slice_arg("ids")
                    total += frag.sparse_block_count(
                        list(ids) if has_ids else frag.row_ids()
                    )
            return total
        fname = ""
        if c.name in ("Distinct", "Percentile"):
            fname, _ = c.string_arg("field")
        elif c.name == "GroupBy":
            for child in c.children:
                if child.name == "Sum" and not child.children:
                    fname, _ = child.string_arg("field")
                    break
        if fname:
            f = self.holder.field(index, fname)
            bsig = f.bsi_group(fname) if f is not None else None
            frag = self.holder.fragment(
                index, fname, VIEW_BSI_GROUP_PREFIX + fname, shard
            )
            if frag is not None and bsig is not None:
                total += frag.sparse_block_count(
                    list(range(bsig.bit_depth() + 1))
                )
        return total

    def _cached_words(self, c: Call, shard: int):
        """u32[W] packed words for one shard of a ``__cached`` node's
        row, memoized on the node (a node is query-local, so the memo
        dies with the query; repeated shards within one query — device
        single-shard walks — pack once)."""
        memo = c.args.setdefault("_words", {})
        w = memo.get(shard)
        if w is None:
            w64 = np.zeros(SHARD_WIDTH // 64, dtype=np.uint64)
            seg = c.args["_row"].shard_segment(shard)
            if seg is not None:
                cols = np.asarray(seg.slice_all(), dtype=np.uint64) - np.uint64(
                    shard * SHARD_WIDTH
                )
                np.bitwise_or.at(
                    w64,
                    (cols >> np.uint64(6)).astype(np.int64),
                    np.uint64(1) << (cols & np.uint64(63)),
                )
            w = np.ascontiguousarray(w64).view("<u4")
            memo[shard] = w
        return w

    def _device_bitmap(self, index, c: Call, shard: int):
        """Lower a bitmap call subtree to a device u32[W] word vector."""
        name = c.name
        if name == "__cached":
            return self._cached_words(c, shard)
        if name == "Row":
            field_name = c.field_arg()
            f = self.holder.field(index, field_name)
            if f is None:
                raise NotFoundError(f"field not found: {field_name}")
            row_id, ok = c.uint_arg(field_name)
            if not ok:
                raise ValueError(f"Row() must specify {field_name}")
            frag = self.holder.fragment(index, field_name, VIEW_STANDARD, shard)
            if frag is None:
                return np.zeros(_W32, dtype=np.uint32)
            return self.stager.row(frag, row_id)
        if name in ("Intersect", "Union", "Xor", "Difference"):
            if not c.children:
                if name in ("Intersect", "Difference"):
                    raise ValueError(f"empty {name} query is currently not supported")
                return np.zeros(_W32, dtype=np.uint32)
            acc = self._device_bitmap(index, c.children[0], shard)
            for child in c.children[1:]:
                w = self._device_bitmap(index, child, shard)
                if name == "Intersect":
                    acc = ops.and_(acc, w)
                elif name == "Union":
                    acc = ops.or_(acc, w)
                elif name == "Xor":
                    acc = ops.xor_(acc, w)
                else:
                    acc = ops.andnot(acc, w)
            return acc
        if name == "Range":
            return self._device_range(index, c, shard)
        raise _NotDeviceable(name)

    def _device_range(self, index, c: Call, shard: int):
        if not c.has_condition_arg():
            # time range: union staged rows across quantum views
            field_name = c.field_arg()
            f = self.holder.field(index, field_name)
            if f is None:
                raise NotFoundError(f"field not found: {field_name}")
            row_id, ok = c.uint_arg(field_name)
            start_str, ok1 = c.string_arg("_start")
            end_str, ok2 = c.string_arg("_end")
            if not (ok and ok1 and ok2):
                raise _NotDeviceable("Range")
            q = f.time_quantum()
            if not q:
                return np.zeros(_W32, dtype=np.uint32)
            start = datetime.strptime(start_str, TIME_FORMAT)
            end = datetime.strptime(end_str, TIME_FORMAT)
            acc = None
            for view in views_by_time_range(VIEW_STANDARD, start, end, q):
                frag = self.holder.fragment(index, field_name, view, shard)
                if frag is None:
                    continue
                w = self.stager.row(frag, row_id)
                acc = w if acc is None else ops.or_(acc, w)
            return acc if acc is not None else np.zeros(_W32, dtype=np.uint32)

        ((field_name, cond),) = c.args.items()
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        bsig = f.bsi_group(field_name)
        if bsig is None:
            raise NotFoundError(f"bsiGroup not found: {field_name}")
        frag = self.holder.fragment(
            index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, shard
        )
        depth = bsig.bit_depth()
        zeros = np.zeros(_W32, dtype=np.uint32)

        if cond.op == NEQ and cond.value is None:
            if frag is None:
                return zeros
            return self.stager.row(frag, depth)
        if cond.op == BETWEEN:
            predicates = cond.int_slice_value()
            base_min, base_max, out_of_range = bsig.base_value_between(*predicates)
            if out_of_range or frag is None:
                return zeros
            planes = self.stager.planes(frag, depth)
            if predicates[0] <= bsig.min and predicates[1] >= bsig.max:
                return planes[-1]
            return ops.bsi_range_between(
                planes, np.uint32(base_min), np.uint32(base_max), bit_depth=depth
            )
        value = cond.value
        if not isinstance(value, int) or isinstance(value, bool):
            raise ValueError("Range(): conditions only support integer values")
        base_value, out_of_range = bsig.base_value(cond.op, value)
        if out_of_range and cond.op != NEQ:
            return zeros
        if frag is None:
            return zeros
        planes = self.stager.planes(frag, depth)
        if (
            (cond.op == "<" and value > bsig.max)
            or (cond.op == "<=" and value >= bsig.max)
            or (cond.op == ">" and value < bsig.min)
            or (cond.op == ">=" and value <= bsig.min)
        ):
            return planes[-1]
        if out_of_range and cond.op == NEQ:
            return planes[-1]
        pred = np.uint32(base_value)
        if cond.op == "==":
            return ops.bsi_range_eq(planes, pred, bit_depth=depth)
        if cond.op == "!=":
            return ops.bsi_range_neq(planes, pred, bit_depth=depth)
        if cond.op in ("<", "<="):
            return ops.bsi_range_lt(
                planes, pred, bit_depth=depth, allow_equality=cond.op == "<="
            )
        if cond.op in (">", ">="):
            return ops.bsi_range_gt(
                planes, pred, bit_depth=depth, allow_equality=cond.op == ">="
            )
        raise ValueError(f"invalid range operation: {cond.op}")

    # -- shard-batched device path -------------------------------------------
    # When this node executes many shards locally (single-node, or the
    # remote leg of a distributed query), the whole shard set runs as ONE
    # kernel dispatch over u32[S, W] stacks instead of S dispatches —
    # the reference's per-shard goroutine fan-out vectorised away
    # (SURVEY.md §2.2 'intra-node shard parallelism').

    def _local_batchable(self, opt) -> bool:
        return self.cluster is None or opt.remote

    def _use_device_batched(self, index, c: Call, shards) -> bool:
        use = self._use_device_batched_decide(index, c, shards)
        metrics.count(
            metrics.EXECUTOR_ROUTE_DEVICE if use else metrics.EXECUTOR_ROUTE_CPU,
            call=c.name,
        )
        sp = trace.current()
        if sp is not None:
            sp.event(
                metrics.STAGE_ROUTE,
                call=c.name,
                shards=len(shards),
                path="device" if use else "cpu",
            )
        return use

    def _use_device_batched_decide(self, index, c: Call, shards) -> bool:
        if self.device_policy == "never" or len(shards) < 2 or self._cpu_forced():
            return False
        if self.device_policy == "always":
            return True
        total = sum(self._touched_containers(index, c, s) for s in shards)
        return total >= self.auto_min_containers

    def _tree_leaves(self, index, c: Call, batch):
        """Lower a bitmap call tree to (leaf device arrays, structure):
        boolean nodes become structure tuples, anything else (Row /
        Range / time-range) stages or evaluates to a leaf array."""
        leaves: list = []

        def build(call: Call):
            if call.name in ("Intersect", "Union", "Xor", "Difference") and call.children:
                return (call.name, tuple(build(ch) for ch in call.children))
            arr = self._device_bitmap_stack(index, call, batch)
            leaves.append(arr)
            return ("leaf", len(leaves) - 1)

        return leaves, build(c)

    def _tree_count_jit(self, tree):
        """Jitted popcount-of-tree, cached per tree structure (bounded
        by distinct query shapes, like the reference's parsed-query
        cache would be). Returns i32[1] so the batcher's single path
        and the caller's unwrap are shape-uniform with the batch path."""
        import jax

        key = repr(tree)
        fn = self._tree_jits.get(key)
        if fn is None:
            fn = _timed_kernel(
                "tree_count",
                jax.jit(lambda *ls: ops.count_bits(_eval_tree(tree, ls))[None]),
                signature=key,
                recovery=self._oom,
            )
            self._tree_jits[key] = fn
        return fn

    def _tree_count_batch_jit(self, tree, q: int, nleaves: int):
        """Jitted popcount-of-tree over Q coalesced same-shape queries:
        takes the Q queries' leaf arrays flattened (query-major), stacks
        each leaf position to u32[Q, S, W], evaluates the boolean tree
        once batched, and returns i32[Q] counts. One kernel dispatch
        serves Q concurrent chain queries — the lever that takes chains
        past the tunnel's request-pipelining depth the same way the
        stacked scorer does for TopN. Cache key includes Q (pow2-padded
        by the batcher, so compile count stays bounded)."""
        import jax
        import jax.numpy as jnp

        key = (repr(tree), q)
        fn = self._tree_batch_jits.get(key)
        if fn is None:

            def run(*flat):
                stacked = tuple(
                    jnp.stack([flat[k * nleaves + l] for k in range(q)])
                    for l in range(nleaves)
                )
                acc = _eval_tree(tree, stacked)  # u32[Q, S, W]
                pc = jax.lax.population_count(acc).astype(jnp.int32)
                return jnp.sum(pc, axis=tuple(range(1, pc.ndim)))

            fn = _timed_kernel(
                "tree_count_batch", jax.jit(run), signature=key, recovery=self._oom
            )
            self._tree_batch_jits[key] = fn
        return fn

    def _chain_count_single(self, leaves, tree):
        return self._tree_count_jit(tree)(*leaves)

    def _chain_count_batch(self, srcs, tree):
        nleaves = len(srcs[0])
        flat = [arr for leaves in srcs for arr in leaves]
        return self._tree_count_batch_jit(tree, len(srcs), nleaves)(*flat)

    def _device_bitmap_stack(self, index, c: Call, shards):
        """Lower a bitmap call subtree to u32[S, W] across shards."""
        name = c.name
        if name == "__cached":
            # device-resident plan cache: serve the packed stack from
            # HBM instead of re-packing + re-uploading the Row the
            # device just produced. Keyed by the subtree's canonical
            # hash; validated against the CURRENT generation vector
            # (the planner froze the insert stamp BEFORE resolving the
            # row, so a racing write can only over-invalidate). Mesh
            # runs skip it — stacks there are mesh-sharded and a plain
            # device_put array would be wrongly placed.
            dc = self.device_cache
            g0 = c.args.get("_genvec")
            gvfn = c.args.get("_gv")
            if dc is not None and g0 is not None and gvfn is not None and self.mesh is None:
                dkey = (index, c.args["_h"], tuple(shards))
                hit = dc.get(dkey, gvfn)
                if hit is not None:
                    return hit
                stack = np.stack([self._cached_words(c, s) for s in shards])
                epoch0 = dc.epoch
                try:
                    dev = self.stager.upload(stack)
                except Exception:
                    return stack  # upload failed: host stack still works
                dc.put(dkey, g0, dev, int(stack.nbytes), epoch0=epoch0)
                return dev
            return np.stack([self._cached_words(c, s) for s in shards])
        if name == "Row":
            field_name = c.field_arg()
            f = self.holder.field(index, field_name)
            if f is None:
                raise NotFoundError(f"field not found: {field_name}")
            row_id, ok = c.uint_arg(field_name)
            if not ok:
                raise ValueError(f"Row() must specify {field_name}")
            frags = tuple(
                self.holder.fragment(index, field_name, VIEW_STANDARD, s)
                for s in shards
            )
            return self.stager.row_stack(frags, row_id)
        if name in ("Intersect", "Union", "Xor", "Difference"):
            if not c.children:
                if name in ("Intersect", "Difference"):
                    raise ValueError(f"empty {name} query is currently not supported")
                return np.zeros((len(shards), _W32), dtype=np.uint32)
            acc = self._device_bitmap_stack(index, c.children[0], shards)
            for child in c.children[1:]:
                w = self._device_bitmap_stack(index, child, shards)
                if name == "Intersect":
                    acc = ops.and_(acc, w)
                elif name == "Union":
                    acc = ops.or_(acc, w)
                elif name == "Xor":
                    acc = ops.xor_(acc, w)
                else:
                    acc = ops.andnot(acc, w)
            return acc
        if name == "Range":
            return self._device_range_stack(index, c, shards)
        raise _NotDeviceable(name)

    def _device_range_stack(self, index, c: Call, shards):
        import jax

        zeros = np.zeros((len(shards), _W32), dtype=np.uint32)
        if not c.has_condition_arg():
            field_name = c.field_arg()
            f = self.holder.field(index, field_name)
            if f is None:
                raise NotFoundError(f"field not found: {field_name}")
            row_id, ok = c.uint_arg(field_name)
            start_str, ok1 = c.string_arg("_start")
            end_str, ok2 = c.string_arg("_end")
            if not (ok and ok1 and ok2):
                raise _NotDeviceable("Range")
            q = f.time_quantum()
            if not q:
                return zeros
            start = datetime.strptime(start_str, TIME_FORMAT)
            end = datetime.strptime(end_str, TIME_FORMAT)
            acc = None
            for view in views_by_time_range(VIEW_STANDARD, start, end, q):
                frags = tuple(
                    self.holder.fragment(index, field_name, view, s) for s in shards
                )
                if not any(frags):
                    continue
                w = self.stager.row_stack(frags, row_id)
                acc = w if acc is None else ops.or_(acc, w)
            return acc if acc is not None else zeros

        ((field_name, cond),) = c.args.items()
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        bsig = f.bsi_group(field_name)
        if bsig is None:
            raise NotFoundError(f"bsiGroup not found: {field_name}")
        depth = bsig.bit_depth()
        frags = tuple(
            self.holder.fragment(
                index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, s
            )
            for s in shards
        )
        if not any(frags):
            return zeros
        planes = self.stager.planes_stack(frags, depth)

        if cond.op == NEQ and cond.value is None:
            return planes[:, -1, :]
        if cond.op == BETWEEN:
            predicates = cond.int_slice_value()
            base_min, base_max, out_of_range = bsig.base_value_between(*predicates)
            if out_of_range:
                return zeros
            if predicates[0] <= bsig.min and predicates[1] >= bsig.max:
                return planes[:, -1, :]
            return jax.vmap(
                lambda p: ops.bsi_range_between(
                    p, np.uint32(base_min), np.uint32(base_max), bit_depth=depth
                )
            )(planes)
        value = cond.value
        if not isinstance(value, int) or isinstance(value, bool):
            raise ValueError("Range(): conditions only support integer values")
        base_value, out_of_range = bsig.base_value(cond.op, value)
        if out_of_range and cond.op != NEQ:
            return zeros
        if (
            (cond.op == "<" and value > bsig.max)
            or (cond.op == "<=" and value >= bsig.max)
            or (cond.op == ">" and value < bsig.min)
            or (cond.op == ">=" and value <= bsig.min)
        ):
            return planes[:, -1, :]
        if out_of_range and cond.op == NEQ:
            return planes[:, -1, :]
        pred = np.uint32(base_value)
        if cond.op == "==":
            kern = lambda p: ops.bsi_range_eq(p, pred, bit_depth=depth)
        elif cond.op == "!=":
            kern = lambda p: ops.bsi_range_neq(p, pred, bit_depth=depth)
        elif cond.op in ("<", "<="):
            kern = lambda p: ops.bsi_range_lt(
                p, pred, bit_depth=depth, allow_equality=cond.op == "<="
            )
        else:
            kern = lambda p: ops.bsi_range_gt(
                p, pred, bit_depth=depth, allow_equality=cond.op == ">="
            )
        return jax.vmap(kern)(planes)

    # -- Count ---------------------------------------------------------------

    def _execute_count(self, index, c: Call, shards, opt) -> int:
        if len(c.children) == 0:
            raise ValueError("Count() requires an input bitmap")
        if len(c.children) > 1:
            raise ValueError("Count() only accepts a single bitmap input")
        child = c.children[0]

        if (
            self._local_batchable(opt)
            and shards
            and self._use_device_batched(index, child, shards)
        ):
            try:
                with trace.child(metrics.STAGE_DEVICE_BATCH, call="Count"):
                    n = self._count_device_batched(index, child, shards)
                self._heat_read_legs(index, child, shards)
                return n
            except _NotDeviceable:
                pass

        def map_fn(shard):
            if self._use_device(index, child, shard):
                try:
                    words = self._device_bitmap(index, child, shard)
                    return int(ops.count_bits(words))
                except _NotDeviceable:
                    pass
            return self._bitmap_call_shard_cpu(index, child, shard).count()

        result = self._map_reduce(
            index, shards, c, opt, map_fn, lambda a, b: a + b, zero_factory=lambda: 0
        )
        return int(result or 0)

    def _count_device_batched(self, index, child, shards) -> int:
        batch = self._shard_plan(shards)
        if self.mesh is not None:
            words = self._device_bitmap_stack(index, child, batch)
            return int(self._spmd_kernel("count")(words))
        # One fused program per query-tree structure: boolean
        # internal nodes trace into a single jit so the whole
        # chain is one XLA fusion + one dispatch, instead of an
        # eager op (= a host round-trip on tunneled chips) per
        # tree node (SURVEY.md §7 step 4).
        #
        # Default: per-query dispatch. Measured A/B on the
        # tunneled chip (c64 closed-loop, warm): direct 671 qps
        # vs coalesced 235-297 — the tunnel pipelines ~50
        # independent RPCs while the scorer's drain rounds
        # serialize on one fetch chain, and the chain kernel is
        # too cheap (~0.1 ms) for batching to amortize anything
        # (unlike TopN's matrix scan). PILOSA_CHAIN_BATCH=1
        # opts into coalescing for deployments where dispatch
        # COST (not round-trip pipelining) dominates; each slot
        # carries its own staged leaf snapshot, so coalescing
        # never changes which data a query counts.
        leaves, tree = self._tree_leaves(index, child, batch)
        if self._chain_batch:
            key = (
                "chain",
                repr(tree),
                tuple(getattr(a, "shape", None) for a in leaves),
            )
            res = self.chain_scorer.score(key, tree, tuple(leaves))
        else:
            res = self._tree_count_jit(tree)(*leaves)
        return int(_fetch(res).reshape(-1)[0])

    # -- Sum / Min / Max -----------------------------------------------------

    def _bsi_shard_parts(self, index, c: Call, shard: int):
        """(fragment, bsig, filter) for a Sum/Min/Max shard; None if missing."""
        field_name, _ = c.string_arg("field")
        f = self.holder.field(index, field_name)
        if f is None:
            return None
        bsig = f.bsi_group(field_name)
        if bsig is None:
            return None
        frag = self.holder.fragment(
            index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, shard
        )
        if frag is None:
            return None
        return frag, bsig

    def _bsi_filter(self, index, c: Call, shard: int) -> Optional[Row]:
        if len(c.children) == 1:
            return self._bitmap_call_shard(index, c.children[0], shard)
        return None

    def _device_filter(self, index, c: Call, shard: int):
        """(filter_words, has_filter) on the device path."""
        if len(c.children) == 1:
            return self._device_bitmap(index, c.children[0], shard), True
        return np.zeros(_W32, dtype=np.uint32), False

    def _execute_sum(self, index, c: Call, shards, opt) -> ValCount:
        if not c.args.get("field"):
            raise ValueError("Sum(): field required")
        if len(c.children) > 1:
            raise ValueError("Sum() only accepts a single bitmap input")

        # shard-batched fast path: one dispatch for all local shards
        if self._local_batchable(opt) and shards and self._use_device_batched(index, c, shards):
            field_name, _ = c.string_arg("field")
            f = self.holder.field(index, field_name)
            bsig = f.bsi_group(field_name) if f else None
            if bsig is not None:
                batch = self._shard_plan(shards)
                frags = tuple(
                    self.holder.fragment(
                        index, field_name, VIEW_BSI_GROUP_PREFIX + field_name, s
                    )
                    for s in batch
                )
                if any(frags):
                    try:
                        with trace.child(metrics.STAGE_DEVICE_BATCH, call="Sum"):
                            vc = self._sum_device_batched(
                                index, c, batch, bsig, frags
                            )
                        self._heat_read_legs(index, c, shards)
                        return vc
                    except _NotDeviceable:
                        pass

        def map_fn(shard):
            parts = self._bsi_shard_parts(index, c, shard)
            if parts is None:
                return ValCount()
            frag, bsig = parts
            depth = bsig.bit_depth()
            if self._use_device(index, c, shard) or (
                self.device_policy != "never"
                and frag.sparse_block_count(list(range(depth + 1)))
                >= self.auto_min_containers
            ):
                try:
                    filt, has_filter = self._device_filter(index, c, shard)
                    planes = self.stager.planes(frag, depth)
                    counts = _fetch(
                        ops.bsi_plane_counts(
                            planes, filt, bit_depth=depth, has_filter=has_filter
                        )
                    )
                    vsum = sum(int(counts[i]) << i for i in range(depth))
                    vcount = int(counts[depth])
                    return ValCount(vsum + vcount * bsig.min, vcount)
                except _NotDeviceable:
                    pass
            filt = self._bsi_filter(index, c, shard)
            vsum, vcount = frag.sum(filt, depth)
            return ValCount(vsum + vcount * bsig.min, vcount)

        result = self._map_reduce(
            index, shards, c, opt, map_fn, lambda a, b: a.add(b), zero_factory=ValCount
        )
        if result is None or result.count == 0:
            return ValCount()
        return result

    def _sum_device_batched(self, index, c: Call, batch, bsig, frags) -> ValCount:
        depth = bsig.bit_depth()
        if len(c.children) == 1:
            filt = self._device_bitmap_stack(index, c.children[0], batch)
            has_filter = True
        else:
            filt = np.zeros((len(batch), _W32), dtype=np.uint32)
            has_filter = False
        planes = self.stager.planes_stack(frags, depth)
        if self.mesh is not None:
            counts = _fetch(
                self._spmd_kernel("plane_counts", depth, has_filter)(planes, filt)
            )
        else:
            counts = _fetch(
                ops.bsi_plane_counts_batched(
                    planes, filt, bit_depth=depth, has_filter=has_filter
                )
            )
        vsum = sum(int(counts[i]) << i for i in range(depth))
        vcount = int(counts[depth])
        if vcount == 0:
            return ValCount()
        return ValCount(vsum + vcount * bsig.min, vcount)

    def _execute_min(self, index, c: Call, shards, opt) -> ValCount:
        return self._execute_minmax(index, c, shards, opt, is_min=True)

    def _execute_max(self, index, c: Call, shards, opt) -> ValCount:
        return self._execute_minmax(index, c, shards, opt, is_min=False)

    def _execute_minmax(self, index, c: Call, shards, opt, is_min: bool) -> ValCount:
        if not c.args.get("field"):
            raise ValueError(f"{'Min' if is_min else 'Max'}(): field required")
        if len(c.children) > 1:
            raise ValueError(
                f"{'Min' if is_min else 'Max'}() only accepts a single bitmap input"
            )

        def map_fn(shard):
            parts = self._bsi_shard_parts(index, c, shard)
            if parts is None:
                return ValCount()
            frag, bsig = parts
            depth = bsig.bit_depth()
            if self._use_device(index, c, shard) or (
                self.device_policy != "never"
                and frag.sparse_block_count(list(range(depth + 1)))
                >= self.auto_min_containers
            ):
                try:
                    filt, has_filter = self._device_filter(index, c, shard)
                    planes = self.stager.planes(frag, depth)
                    kernel = ops.bsi_min if is_min else ops.bsi_max
                    bits, count = kernel(
                        planes, filt, bit_depth=depth, has_filter=has_filter
                    )
                    count = int(count)
                    if count == 0:
                        return ValCount()
                    val = sum(1 << i for i, b in enumerate(_fetch(bits)) if b)
                    return ValCount(val + bsig.min, count)
                except _NotDeviceable:
                    pass
            filt = self._bsi_filter(index, c, shard)
            val, count = (frag.min if is_min else frag.max)(filt, depth)
            return ValCount(val + bsig.min, count)

        reduce_fn = (
            (lambda a, b: a.smaller(b)) if is_min else (lambda a, b: a.larger(b))
        )
        result = self._map_reduce(
            index, shards, c, opt, map_fn, reduce_fn, zero_factory=ValCount
        )
        if result is None or result.count == 0:
            return ValCount()
        return result

    # -- device-resident analytics (ISSUE 18) --------------------------------
    #
    # GroupBy / Distinct / Percentile execute shard-batched as segmented
    # device reductions (one jitted launch per panel, intermediates
    # never leaving HBM) with the same degrade ladder as Count/Sum/TopN:
    # batched device -> per-shard CPU oracle via _map_reduce (which the
    # cluster layer federates). A FragmentQuarantinedError raised while
    # STAGING a batch degrades that launch to the classic path, where
    # the quarantined shard's leg surfaces the clean 503 instead of
    # poisoning the whole fused launch.

    def _execute_groupby(self, index, c: Call, shards, opt) -> list[dict]:
        plan = analytics.parse_groupby(c)
        metrics.count(metrics.ANALYTICS_QUERIES, call="GroupBy")
        dims = analytics.resolve_dims(
            self.holder, index, plan, shards, self.analytics_max_groups
        )
        merged = None
        if (
            self._local_batchable(opt)
            and shards
            and self.mesh is None  # group stacks flatten the shard axis
            and all(ids for _, ids in dims)
            and self._use_device_batched(index, c, shards)
        ):
            try:
                with trace.child(metrics.STAGE_DEVICE_BATCH, call="GroupBy"):
                    merged = self._groupby_device_batched(
                        index, plan, dims, shards
                    )
                fields = [f for f, _ in dims] + (
                    [plan.agg_field] if plan.agg_field else []
                )
                self._analytics_heat_legs(index, fields, shards)
            except _NotDeviceable:
                merged = None
            except FragmentQuarantinedError:
                metrics.count(metrics.ANALYTICS_DEGRADED_LEGS, call="GroupBy")
                merged = None
        if merged is None:

            def map_fn(shard):
                return analytics.groupby_shard(self, index, plan, dims, shard)

            merged = self._map_reduce(
                index,
                shards,
                c,
                opt,
                map_fn,
                analytics.merge_group_lists,
                zero_factory=list,
            )
        if opt.remote:
            # un-finalized wire list: the coordinator merges remote legs
            # first, then orders + applies limit exactly once
            return merged or []
        return analytics.finalize_groups(plan, merged or [])

    def _groupby_device_batched(self, index, plan, dims, shards) -> list[dict]:
        """One segmented-reduction launch for the whole panel: stack each
        dimension's rows, cross-product AND on device, popcount the K
        group bitmaps (and their BSI plane intersections for Sum)."""
        import jax.numpy as jnp

        wf = len(shards) * _W32
        dim_stacks = []
        for field, ids in dims:
            frags = tuple(
                self.holder.fragment(index, field, VIEW_STANDARD, s)
                for s in shards
            )
            rows = [self.stager.row_stack(frags, rid) for rid in ids]
            dim_stacks.append(jnp.stack(rows).reshape(len(ids), wf))
        if plan.filter is not None:
            filt = jnp.asarray(
                self._device_bitmap_stack(index, plan.filter, shards)
            ).reshape(wf)
        else:
            filt = None
        k = 1
        for _, ids in dims:
            k *= len(ids)
        metrics.count(metrics.FUSION_GROUPBY_LAUNCHES)
        metrics.observe(metrics.FUSION_GROUPBY_GROUPS, k)
        if plan.agg_field is None:
            counts = _fetch(ops.groupby_counts(tuple(dim_stacks), filt))
            return analytics.emit_device_groups(dims, counts)
        f = self.holder.field(index, plan.agg_field)
        bsig = f.bsi_group(plan.agg_field) if f is not None else None
        if bsig is None:
            raise NotFoundError(f"bsiGroup not found: {plan.agg_field}")
        depth = bsig.bit_depth()
        afrags = tuple(
            self.holder.fragment(
                index, plan.agg_field, VIEW_BSI_GROUP_PREFIX + plan.agg_field, s
            )
            for s in shards
        )
        if not any(afrags):
            counts = _fetch(ops.groupby_counts(tuple(dim_stacks), filt))
            return analytics.emit_device_groups(
                dims, counts, sums=[0] * int(counts.shape[0])
            )
        planes = jnp.transpose(
            self.stager.planes_stack(afrags, depth), (1, 0, 2)
        ).reshape(depth + 1, wf)
        counts, plane_counts = ops.groupby_sum_reduce(
            tuple(dim_stacks), filt, planes
        )
        sums = analytics.assemble_sums(_fetch(plane_counts), depth, bsig.min)
        return analytics.emit_device_groups(dims, _fetch(counts), sums=sums)

    def _execute_distinct(self, index, c: Call, shards, opt) -> list[int]:
        field, ok = c.string_arg("field")
        if not ok or not field:
            raise ValueError("Distinct(): field required")
        if len(c.children) > 1:
            raise ValueError("Distinct() only accepts a single bitmap input")
        metrics.count(metrics.ANALYTICS_QUERIES, call="Distinct")
        f = self.holder.field(index, field)
        bsig = f.bsi_group(field) if f is not None else None
        if bsig is None:
            raise NotFoundError(f"bsiGroup not found: {field}")
        if (
            self._local_batchable(opt)
            and shards
            and self.mesh is None
            and bsig.bit_depth() <= analytics.DISTINCT_DEVICE_MAX_DEPTH
            and self._use_device_batched(index, c, shards)
        ):
            try:
                with trace.child(metrics.STAGE_DEVICE_BATCH, call="Distinct"):
                    vals = self._distinct_device_batched(index, c, shards, bsig)
                self._analytics_heat_legs(index, [field], shards)
                return vals
            except _NotDeviceable:
                pass
            except FragmentQuarantinedError:
                metrics.count(metrics.ANALYTICS_DEGRADED_LEGS, call="Distinct")

        def map_fn(shard):
            return analytics.distinct_shard(self, index, c, field, shard)

        result = self._map_reduce(
            index,
            shards,
            c,
            opt,
            map_fn,
            analytics.merge_distinct_lists,
            zero_factory=list,
        )
        return result or []

    def _distinct_device_batched(self, index, c: Call, shards, bsig) -> list[int]:
        """OR-reduce the per-shard value presence into one 2^depth
        bitmap on device; the host decodes set positions to values."""
        field, _ = c.string_arg("field")
        depth = bsig.bit_depth()
        frags = tuple(
            self.holder.fragment(index, field, VIEW_BSI_GROUP_PREFIX + field, s)
            for s in shards
        )
        if not any(frags):
            return []
        if len(c.children) == 1:
            filt = self._device_bitmap_stack(index, c.children[0], shards)
            has_filter = True
        else:
            filt = np.zeros((len(shards), _W32), dtype=np.uint32)
            has_filter = False
        planes = self.stager.planes_stack(frags, depth)
        words = _fetch(
            ops.bsi_distinct_presence(
                planes, filt, bit_depth=depth, has_filter=has_filter
            )
        )
        return analytics.decode_presence_words(words, bsig.min)

    def _execute_percentile(self, index, c: Call, shards, opt) -> ValCount:
        field, nth_bp = analytics.parse_percentile(c)
        metrics.count(metrics.ANALYTICS_QUERIES, call="Percentile")
        f = self.holder.field(index, field)
        bsig = f.bsi_group(field) if f is not None else None
        if bsig is None:
            raise NotFoundError(f"bsiGroup not found: {field}")
        if (
            self._local_batchable(opt)
            and shards
            and self.mesh is None
            and self._use_device_batched(index, c, shards)
        ):
            try:
                with trace.child(metrics.STAGE_DEVICE_BATCH, call="Percentile"):
                    vc = self._percentile_device_batched(
                        index, c, shards, bsig, nth_bp
                    )
                self._analytics_heat_legs(index, [field], shards)
                return vc
            except _NotDeviceable:
                pass
            except FragmentQuarantinedError:
                metrics.count(
                    metrics.ANALYTICS_DEGRADED_LEGS, call="Percentile"
                )
        return self._percentile_by_counting(
            index, c, shards, opt, field, bsig, nth_bp
        )

    def _percentile_device_batched(
        self, index, c: Call, shards, bsig, nth_bp: int
    ) -> ValCount:
        """Bit-sliced binary search over the BSI planes, entirely on
        device: one launch, one fetch of (depth bits, count)."""
        field, _ = c.string_arg("field")
        depth = bsig.bit_depth()
        frags = tuple(
            self.holder.fragment(index, field, VIEW_BSI_GROUP_PREFIX + field, s)
            for s in shards
        )
        if not any(frags):
            return ValCount()
        if len(c.children) == 1:
            filt = self._device_bitmap_stack(index, c.children[0], shards)
            has_filter = True
        else:
            filt = np.zeros((len(shards), _W32), dtype=np.uint32)
            has_filter = False
        planes = self.stager.planes_stack(frags, depth)
        bits, count = ops.bsi_percentile_batched(
            planes, filt, np.int32(nth_bp), bit_depth=depth, has_filter=has_filter
        )
        count = int(count)
        if count == 0:
            return ValCount()
        val = sum(1 << i for i, b in enumerate(_fetch(bits)) if b)
        return ValCount(val + bsig.min, count)

    def _percentile_by_counting(
        self, index, c: Call, shards, opt, field, bsig, nth_bp: int
    ) -> ValCount:
        """Classic leg: O(depth) counting binary search over the value
        domain built from synthesized Count(Range(...)) calls — each
        Count federates (and device-routes) through its own path, so
        this leg is cluster-correct without a new merge type, and it is
        the CPU oracle the device descent must match bit-for-bit."""

        def count_where(cond: Condition) -> int:
            child: Call = Call("Range", {field: cond})
            if len(c.children) == 1:
                child = Call(
                    "Intersect", children=[c.children[0].clone(), child]
                )
            return self._execute_count(
                index, Call("Count", children=[child]), shards, opt
            )

        n = count_where(Condition(NEQ, None))
        if n == 0:
            return ValCount()
        k = analytics.nearest_rank(nth_bp, n)
        lo, hi = bsig.min, bsig.max
        while lo < hi:
            mid = (lo + hi) // 2
            if count_where(Condition("<=", mid)) >= k:
                hi = mid
            else:
                lo = mid + 1
        return ValCount(lo, n)

    # -- TopN (reference executeTopN two-pass, executor.go:521-585) ----------

    def _execute_topn(
        self, index, c: Call, shards, opt, prescored=None
    ) -> list[dict]:
        ids_arg, _ = c.uint_slice_arg("ids")
        n, _ = c.uint_arg("n")
        # (shard, row_id) -> exact intersection count, filled by pass 1's
        # scoring dispatches and consulted by pass 2: on skewed data the
        # winning ids sit in every shard's cache head, so pass 2 usually
        # needs no device round-trip at all — on a tunneled chip that is
        # half the query's wall clock
        carry = _ScoreCarry()
        pairs = self._execute_topn_shards(
            index, c, shards, opt, carry, prescored=prescored
        )
        if not pairs or ids_arg or opt.remote:
            return _pairs_result(pairs)
        # Pass 2: re-query the union of candidate ids for exact counts.
        other = c.clone()
        other.args["ids"] = sorted(p[0] for p in pairs)
        trimmed = self._execute_topn_shards(index, other, shards, opt, carry)
        if n and n < len(trimmed):
            trimmed = trimmed[:n]
        return _pairs_result(trimmed)

    def _execute_topn_shards(
        self, index, c: Call, shards, opt, carry=None, prescored=None
    ) -> list[tuple[int, int]]:
        if (
            self._local_batchable(opt)
            and shards
            and len(c.children) == 1
            # a fused launch already scored the head chunk on device —
            # honor it regardless of the (re-evaluated) auto crossover,
            # so the prescore is never discarded by a borderline flip
            and (
                prescored is not None
                or self._use_device_batched(index, c, shards)
            )
        ):
            try:
                with trace.child(metrics.STAGE_DEVICE_BATCH, call="TopN"):
                    if self.mesh is not None:
                        pairs = self._topn_shards_spmd(index, c, shards, carry)
                    else:
                        pairs = self._topn_shards_batched(
                            index, c, shards, carry, prescored=prescored
                        )
                self._heat_read_legs(index, c, shards)
                return sort_pairs(pairs)
            except _NotDeviceable:
                pass

        def map_fn(shard):
            return self._execute_topn_shard(index, c, shard, carry)

        result = self._map_reduce(index, shards, c, opt, map_fn, pairs_add, zero_factory=list)
        return sort_pairs(result or [])

    def _topn_shards_batched(
        self, index, c: Call, shards, carry=None, prescored=None
    ) -> list[tuple[int, int]]:
        """Single-device cross-shard TopN: every shard's candidate
        scoring lands in ONE chunked kernel dispatch over the merged
        block-sparse staging (sparse_intersection_counts_stacked) —
        per-shard sequential launches cost a host round-trip each,
        which at 64 shards dominates latency on tunneled chips. The
        per-shard ranked walk replays on the host for bit-identical
        pruning."""
        field, _ = c.string_arg("_field")
        n, _ = c.uint_arg("n")
        attr_name, _ = c.string_arg("attrName")
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, _ = c.uint_arg("threshold")
        attr_values = c.args.get("attrValues") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if tanimoto > 100:
            raise ValueError("Tanimoto Threshold is from 1 to 100 only")
        if tanimoto > 0:
            # tanimoto pruning needs each shard's CPU source count
            raise _NotDeviceable("TopN+tanimoto")
        if min_threshold <= 0:
            min_threshold = DEFAULT_MIN_THRESHOLD

        if prescored is not None:
            # fused whole-query launch already staged + scored the head
            # chunk: reuse ITS fragment/pairs snapshot (the injected
            # matrix and the walk must agree on candidate order) and
            # its resolved source stack
            frags, pairs_by_shard, ids0, mat0, srcs0 = prescored
        else:
            frags = tuple(
                self.holder.fragment(index, field, VIEW_STANDARD, s)
                for s in shards
            )
            pairs_by_shard = [
                f._top_bitmap_pairs(row_ids) if f is not None else []
                for f in frags
            ]
        if not any(pairs_by_shard):
            return []
        # lazy: a pass 2 fully covered by the carry never resolves the
        # source stack (no device re-fold of compound sources)
        provider = _StackedLazyScores(
            self,
            frags,
            pairs_by_shard,
            (
                srcs0
                if prescored is not None
                else lambda: self._device_bitmap_stack(
                    index, c.children[0], shards
                )
            ),
            shards=shards,
            carry=carry,
        )
        if prescored is not None:
            # inject the fused head as chunk 0; the walk continues from
            # _chunk_size(FIRST_CHUNK) exactly as the unfused schedule
            # would, so chunk boundaries (and staging keys) match
            provider._mats.append(mat0)
            provider._chunk_meta.append((0, mat0.shape[1], ids0))
            provider._pos = mat0.shape[1]
            provider._publish(ids0, mat0)
        opt_ = TopOptions(
            n=int(n),
            src=None,
            row_ids=row_ids,
            min_threshold=min_threshold,
            filter_name=attr_name,
            filter_values=attr_values,
            tanimoto_threshold=0,
        )
        fast = _vectorized_topn_walk(pairs_by_shard, provider, opt_)
        if fast is not None:
            return fast
        out: list[tuple[int, int]] = []
        for i, (frag, pairs) in enumerate(zip(frags, pairs_by_shard)):
            if frag is None or not pairs:
                continue
            out = pairs_add(out, _ranked_walk(frag, opt_, pairs, provider.view(i)))
        return out

    def _topn_shards_spmd(
        self, index, c: Call, shards, carry=None
    ) -> list[tuple[int, int]]:
        """Cross-shard TopN on the mesh with LAZY chunked staging: the
        ranked walk (replayed per shard for bit-identical pruning)
        pulls pow2 chunks of block-sparse candidates on demand; each
        chunk is one shard_map program whose all_gather replaces the
        reference's HTTP Pairs exchange (executor.go:563-585). Eagerly
        staging every ranked-cache candidate densely cost k × S ×
        128 KB — tens of GB at the reference's 50k-candidate cache
        (cache.go:136-233) — where the lazy walk usually prunes within
        the head chunk (fragment.go:870-1002 threshold break)."""
        field, _ = c.string_arg("_field")
        n, _ = c.uint_arg("n")
        attr_name, _ = c.string_arg("attrName")
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, _ = c.uint_arg("threshold")
        attr_values = c.args.get("attrValues") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if tanimoto > 100:
            raise ValueError("Tanimoto Threshold is from 1 to 100 only")
        if tanimoto > 0:
            # tanimoto pruning needs each shard's CPU source count;
            # the per-shard path already has those rows in hand
            raise _NotDeviceable("TopN+tanimoto")
        if min_threshold <= 0:
            min_threshold = DEFAULT_MIN_THRESHOLD

        batch = self._shard_plan(shards)
        frags = tuple(
            self.holder.fragment(index, field, VIEW_STANDARD, s) for s in batch
        )
        pairs_by_shard = [
            f._top_bitmap_pairs(row_ids) if f is not None else [] for f in frags
        ]
        if not any(pairs_by_shard):
            return []
        # carry-seeded provider: pass 2's id subset was scored by pass 1
        # (same source, same fragment snapshot), so a fully-covered
        # second pass dispatches nothing — not even the source stack
        # (srcs is a thunk resolved on first chunk dispatch)
        provider = _SpmdLazyScores(
            self,
            frags,
            pairs_by_shard,
            lambda: self._device_bitmap_stack(index, c.children[0], batch),
            shards=batch,
            carry=carry,
        )
        opt_ = TopOptions(
            n=int(n),
            src=None,
            row_ids=row_ids,
            min_threshold=min_threshold,
            filter_name=attr_name,
            filter_values=attr_values,
            tanimoto_threshold=0,
        )
        fast = _vectorized_topn_walk(pairs_by_shard, provider, opt_)
        if fast is not None:
            return fast
        out: list[tuple[int, int]] = []
        for i, (frag, pairs) in enumerate(zip(frags, pairs_by_shard)):
            if frag is None or not pairs:
                continue
            out = pairs_add(out, _ranked_walk(frag, opt_, pairs, provider.view(i)))
        return out

    def _execute_topn_shard(
        self, index, c: Call, shard: int, carry=None
    ) -> list[tuple[int, int]]:
        field, _ = c.string_arg("_field")
        n, _ = c.uint_arg("n")
        attr_name, _ = c.string_arg("attrName")
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, has_threshold = c.uint_arg("threshold")
        attr_values = c.args.get("attrValues") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")

        src = None
        if len(c.children) == 1:
            src = self._bitmap_call_shard(index, c.children[0], shard)
        elif len(c.children) > 1:
            raise ValueError("TopN() can only have one input bitmap")

        frag = self.holder.fragment(index, field, VIEW_STANDARD, shard)
        if frag is None:
            return []
        if min_threshold <= 0:
            min_threshold = DEFAULT_MIN_THRESHOLD
        if tanimoto > 100:
            raise ValueError("Tanimoto Threshold is from 1 to 100 only")
        opt_ = TopOptions(
            n=int(n),
            src=src,
            row_ids=row_ids,
            min_threshold=min_threshold,
            filter_name=attr_name,
            filter_values=attr_values,
            tanimoto_threshold=tanimoto,
        )
        if src is not None and self._use_device(index, c, shard):
            return self._top_device(frag, opt_, index, c, shard, carry)
        return frag.top(opt_)

    def _top_device(self, frag, opt_: TopOptions, index, c: Call, shard: int, carry=None):
        """Device-accelerated TopN: batch all candidate intersection counts
        into one matrix kernel pass, then replay the reference's ranked
        walk on the precomputed scores (bit-identical outputs)."""
        pairs = frag._top_bitmap_pairs(opt_.row_ids)
        if not pairs:
            return []
        try:
            src_words = self._device_bitmap(index, c.children[0], shard)
        except _NotDeviceable:
            return frag.top(opt_)
        scores = _LazyScores(self, frag, pairs, src_words, shard=shard, carry=carry)
        return _ranked_walk(frag, opt_, pairs, scores)

    # -- writes (reference executor.go:998-1258) -----------------------------

    def _shard_nodes_local(self, index, shard) -> bool:
        """True when this node owns the shard (single-node: always)."""
        return True

    def _execute_set_bit(self, index, c: Call, opt) -> bool:
        field_name = c.field_arg()
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise ValueError("Set() row argument required")
        col_id, ok = c.uint_arg("_col")
        if not ok:
            raise ValueError("Set() col argument required")
        timestamp = None
        ts_str, ok = c.string_arg("_timestamp")
        if ok:
            timestamp = datetime.strptime(ts_str, TIME_FORMAT)
        if self.cluster is not None and not opt.remote:
            return self.cluster.set_bit(index, c, f, row_id, col_id, timestamp, opt)
        # local apply leg: every rank that lands the bit (direct,
        # remote-leg, or gang replay) records the write exactly once
        heat.record_write(index, field_name, col_id // SHARD_WIDTH, 1)
        return f.set_bit(row_id, col_id, timestamp)

    def _execute_clear_bit(self, index, c: Call, opt) -> bool:
        field_name = c.field_arg()
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        row_id, ok = c.uint_arg(field_name)
        if not ok:
            raise ValueError("Clear() row argument required")
        col_id, ok = c.uint_arg("_col")
        if not ok:
            raise ValueError("Clear() col argument required")
        if self.cluster is not None and not opt.remote:
            return self.cluster.clear_bit(index, c, f, row_id, col_id, opt)
        heat.record_write(index, field_name, col_id // SHARD_WIDTH, 1)
        return f.clear_bit(row_id, col_id)

    def _gang_forward_write(self, index, c: Call, opt) -> bool:
        """Federated leader receiving a forward-style write (SetValue /
        attrs) at top level: the LOCAL apply must replay through the
        gang (so follower holders stay identical), then fan out to
        peers as usual. True when handled."""
        lex = self.cluster.local_executor if self.cluster is not None else None
        if lex is None or opt.remote:
            return False
        lex(index, c, None, opt)
        self.cluster.forward_to_all(index, c, opt)
        return True

    def _execute_set_value(self, index, c: Call, opt) -> None:
        col_id, ok = c.uint_arg("col")
        if not ok:
            raise ValueError("SetValue() col argument required")
        args = {k: v for k, v in c.args.items() if k != "col"}
        if self._gang_forward_write(index, c, opt):
            return
        for name, value in args.items():
            f = self.holder.field(index, name)
            if f is None:
                raise NotFoundError(f"field not found: {name}")
            if isinstance(value, bool) or not isinstance(value, int):
                raise ValueError("invalid BSI group value type")
            f.set_value(col_id, value)
        if self.cluster is not None and not opt.remote:
            self.cluster.forward_to_all(index, c, opt)

    def _execute_set_row_attrs(self, index, c: Call, opt) -> None:
        field_name, ok = c.string_arg("_field")
        if not ok:
            raise ValueError("SetRowAttrs() field required")
        f = self.holder.field(index, field_name)
        if f is None:
            raise NotFoundError(f"field not found: {field_name}")
        row_id, ok = c.uint_arg("_row")
        if not ok:
            raise ValueError("SetRowAttrs() row required")
        attrs = {
            k: v for k, v in c.args.items() if k not in ("_field", "_row")
        }
        if f.row_attr_store is None:
            raise ValueError("row attr store not configured")
        if self._gang_forward_write(index, c, opt):
            return
        f.row_attr_store.set_attrs(row_id, attrs)
        if self.cluster is not None and not opt.remote:
            self.cluster.forward_to_all(index, c, opt)

    def _execute_set_column_attrs(self, index, c: Call, opt) -> None:
        idx = self.holder.index(index)
        col_id, ok = c.uint_arg("_col")
        if not ok:
            raise ValueError("SetColumnAttrs() col required")
        attrs = {k: v for k, v in c.args.items() if k != "_col"}
        if idx.column_attrs is None:
            raise ValueError("column attr store not configured")
        if self._gang_forward_write(index, c, opt):
            return
        idx.column_attrs.set_attrs(col_id, attrs)
        if self.cluster is not None and not opt.remote:
            self.cluster.forward_to_all(index, c, opt)

    def _read_pool_acquire(self):
        """Check out the shared read pool (lazily built), or None while
        close() is in progress. The checkout refcount lets close()
        drain active ``pool.map`` users before shutting the pool down —
        previously close() nulled the attribute while a concurrent
        execute() held a local ref and raced ``shutdown``."""
        with self._read_pool_cv:
            if self._read_pool_closing:
                return None
            if self._read_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._read_pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="pql-read"
                )
            self._read_pool_users += 1
            return self._read_pool

    def _read_pool_release(self) -> None:
        with self._read_pool_cv:
            self._read_pool_users -= 1
            if self._read_pool_users == 0:
                self._read_pool_cv.notify_all()

    def _warm_query(self, index: str, query, shards) -> None:
        """Advisory stage-ahead warm (dispatch engine): upload the Row
        operands a QUEUED query will touch while the current wave
        computes, so staging overlaps kernel execution. Best-effort —
        every error is swallowed, staging is idempotent, and the real
        execution re-stages whatever this missed."""
        if self.device_policy == "never" or self._cpu_forced():
            return
        try:
            idx = self.holder.index(index)
            if idx is None:
                return
            if shards is None:
                shards = list(range(idx.max_shard() + 1))
            for call in query.calls:
                self._warm_call(index, call, shards)
        except BaseException:
            pass

    def _warm_call(self, index: str, c: Call, shards) -> None:
        if c.name == "Row":
            field_name = c.field_arg()
            row_id, ok = c.uint_arg(field_name)
            if not ok:
                return
            for shard in shards:
                frag = self.holder.fragment(
                    index, field_name, VIEW_STANDARD, shard
                )
                if frag is not None:
                    self.stager.row(frag, row_id)
            return
        for child in c.children:
            self._warm_call(index, child, shards)

    def close(self, drain: float = 5.0) -> None:
        """Drain the dispatch engine, then the read pool (called from
        Server.close). New read-pool checkouts are refused from here on
        (those executions run their calls serially inline); in-flight
        ``pool.map`` users get up to ``drain`` seconds to finish before
        the pool shuts down under them."""
        if self.dispatch_engine is not None:
            self.dispatch_engine.close(drain=drain)
        t0 = time.monotonic()
        with self._read_pool_cv:
            self._read_pool_closing = True
            while (
                self._read_pool_users > 0
                and time.monotonic() - t0 < drain
            ):
                self._read_pool_cv.wait(timeout=0.05)
            pool, self._read_pool = self._read_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if self.health is not None:
            self.health.close()


# Lazy-scoring chunk schedule, shared by both providers: a small head
# (the walk usually prunes inside it) then large chunks for deep walks.
# Head size is measured, not guessed: on the 1B-row bench (64 shards,
# tunneled chip) chunk-0's scores fetch dominates warm TopN latency —
# 128 cut p50 from 112 ms to 85 ms vs 512, and 64 bought nothing more
# while risking a second dispatch whenever ties run past the head.
FIRST_CHUNK = 128
SCORE_CHUNK = 4096
MAX_CHUNK = 16384


def _chunk_size(pos: int) -> int:
    """Chunk size at scored-prefix position ``pos``: a small head (most
    walks prune inside it on skewed data), then geometric growth
    SCORE_CHUNK → MAX_CHUNK so a deep/full walk over the reference's
    50k-entry ranked cache pays ~6 dispatches instead of ~13. Sizes
    stay pow2 (bounded XLA compile cache) and the schedule is a pure
    function of pos, so the chunk boundaries — and therefore the
    stager's content-derived staging keys — are identical across
    queries and the HBM cache keeps hitting."""
    if pos == 0:
        return FIRST_CHUNK
    boundary, size = FIRST_CHUNK, SCORE_CHUNK
    while boundary + size <= pos:
        boundary += size
        if size < MAX_CHUNK:
            size *= 2
    return size


def _chunk_ids(pairs, lo: int, hi: int) -> tuple[int, ...]:
    """Candidate ids for pairs[lo:hi]. Rankings snapshots memoize their
    slice tuples on themselves (core.cache.Rankings), so repeated
    queries don't rebuild multi-thousand-element tuples per shard per
    query — and the memo can never disagree with the pairs list the
    walk iterates, even across a concurrent cache recalculate."""
    chunk = getattr(pairs, "chunk_ids", None)
    if chunk is not None:
        return chunk(lo, hi)
    return tuple(p[0] for p in pairs[lo:hi])


def _chunk_arrays(pairs, lo: int, hi: int):
    """(ids int64[L], counts int64[L]) for pairs[lo:hi]; memoized on
    Rankings snapshots, built fresh for plain lists (small row_ids
    walks)."""
    chunk = getattr(pairs, "chunk_arrays", None)
    if chunk is not None:
        return chunk(lo, hi)
    return cache_pairs_arrays(pairs[lo:hi])


class _ChunkedLazyScores:
    """Shared chunk-walk skeleton for cross-shard lazy TopN scoring:
    the next pow2 chunk of every shard's candidate list is staged and
    scored the first time any shard's ranked walk reads past the
    scored prefix. Chunk staging keys are content-derived (the
    per-shard candidate id tuples), so repeated queries reuse the
    HBM-resident blocks.

    The FIRST chunk is small: on skewed data the walk prunes within the
    hot head (reference threshold break, fragment.go:969), so staging
    4096 candidates x S shards up front wastes HBM upload — at the 1B
    scale that is the difference between ~0.5 GB and ~2.3 GB of cold
    staging. Later chunks grow to amortize dispatch count on deep
    walks.

    ``srcs`` may be a thunk: it resolves only when a chunk actually
    dispatches, so a pass 2 fully covered by the cross-pass carry pays
    no device work at all (not even re-folding a compound source).
    Subclasses define _stage (host packing, memoized by the stager)
    and _score (kernel dispatch returning a (shard_i, j) -> int
    accessor)."""

    def __init__(self, ex, frags, pairs_by_shard, srcs, shards=None, carry=None) -> None:
        self._ex = ex
        self._frags = frags
        self._pairs = pairs_by_shard
        self._srcs = srcs
        self._scores: list[dict[int, int]] = [{} for _ in frags]
        self._pos = 0  # scored prefix length (per shard)
        self._max_len = max((len(p) for p in pairs_by_shard), default=0)
        # per-chunk score matrices [S, size] + their candidate ids; the
        # vectorized cross-shard walk consumes these directly, and the
        # per-id dict fanout (only needed by the scalar fallback walk)
        # happens lazily in _fanout()
        self._mats: list[np.ndarray] = []
        self._chunk_meta: list[tuple] = []  # (lo, size, ids_by_shard)
        self._fanned = 0
        self._mat_cache = None
        # cross-pass score carry: TopN pass 2 re-reads counts pass 1
        # already computed (same source bitmap, same fragment snapshot —
        # both constant within one _execute_topn) — seeding from the
        # carry makes pass 2 dispatch only for (shard, id) pairs no
        # pass-1 chunk covered
        self._shards = list(shards) if shards is not None else list(range(len(frags)))
        self._carry = carry
        self._prefetching = False  # one prefetch in flight at a time
        if carry:
            for i, s in enumerate(self._shards):
                seed = carry.seed(s, [rid for rid, _ in pairs_by_shard[i]])
                if seed:
                    self._scores[i].update(seed)

    def _stage(self, ids_by_shard, size: int):
        raise NotImplementedError

    def _score(self, staged, size: int):
        raise NotImplementedError

    def _resolved_srcs(self):
        if callable(self._srcs):
            self._srcs = self._srcs()
        return self._srcs

    def _score_next(self) -> None:
        lo = self._pos
        size = _chunk_size(lo)
        hi = lo + size
        self._pos = hi
        ids_by_shard = tuple(_chunk_ids(ps, lo, hi) for ps in self._pairs)
        staged = self._stage(ids_by_shard, size)
        # overlap: while this chunk's kernel runs + fetches, pre-stage
        # the NEXT chunk on a side thread (the stager memoizes by
        # content key, so the walk's next _score_next finds it hot).
        # Deep walks thus pipeline host packing with device compute
        # instead of alternating them serially. NOT from the head
        # chunk (lo == 0): most walks prune inside it on skewed data —
        # eagerly staging the 4096-candidate chunk behind it would
        # re-introduce exactly the cold-staging cost the small head
        # chunk was measured to avoid (class docstring).
        if lo > 0 and hi < self._max_len:
            self._prefetch(hi)
        if staged is None:  # no shard contributed blocks — all score 0
            mat = np.zeros((len(self._frags), size), dtype=np.int32)
        else:
            mat = self._score(staged, size)
        self._mats.append(mat)
        self._chunk_meta.append((lo, size, ids_by_shard))
        self._publish(ids_by_shard, mat)

    def _fanout(self) -> None:
        """Populate the per-shard id->score dicts from chunk matrices
        (scalar-walk fallback path only; zip over .tolist() is C-speed)."""
        while self._fanned < len(self._mats):
            _, _, ids_by_shard = self._chunk_meta[self._fanned]
            mat = self._mats[self._fanned]
            for i, ids in enumerate(ids_by_shard):
                if ids:
                    self._scores[i].update(zip(ids, mat[i].tolist()))
            self._fanned += 1

    def matrices(self):
        """(scores i32[S, P], ids i64[S, P], counts i64[S, P],
        valid bool[S, P]) over the scored prefix; memoized per chunk
        count. Padding columns carry id -1 / count 0 / score 0."""
        k = len(self._mats)
        if self._mat_cache is not None and self._mat_cache[0] == k:
            return self._mat_cache[1]
        S = len(self._frags)
        smat = (
            np.concatenate(self._mats, axis=1) if k > 1 else self._mats[0]
        )
        P = smat.shape[1]
        idm = np.full((S, P), -1, dtype=np.int64)
        cntm = np.zeros((S, P), dtype=np.int64)
        col = 0
        for (lo, size, ids_by_shard), m in zip(self._chunk_meta, self._mats):
            for i, ids in enumerate(ids_by_shard):
                L = len(ids)
                if L:
                    a_ids, a_cnts = _chunk_arrays(self._pairs[i], lo, lo + L)
                    idm[i, col : col + L] = a_ids
                    cntm[i, col : col + L] = a_cnts
            col += size
        out = (smat, idm, cntm, idm >= 0)
        self._mat_cache = (k, out)
        return out

    def _prefetch(self, lo: int) -> None:
        if self._prefetching:
            return
        self._prefetching = True
        size = _chunk_size(lo)
        ids_by_shard = tuple(
            _chunk_ids(ps, lo, lo + size) for ps in self._pairs
        )

        def warm():
            try:
                self._stage(ids_by_shard, size)
            except Exception:
                pass  # purely advisory; the real call surfaces errors
            finally:
                self._prefetching = False

        threading.Thread(
            target=warm, name="stage-prefetch", daemon=True
        ).start()

    def _publish(self, ids_by_shard, mat) -> None:
        if self._carry is None:
            return
        self._carry.add_stacked(self._shards, ids_by_shard, mat)

    def view(self, shard_index: int) -> "_ShardScoreView":
        return _ShardScoreView(self, shard_index)


class _StackedLazyScores(_ChunkedLazyScores):
    """Single-device form: each chunk is one merged block-sparse
    sparse_intersection_counts_stacked dispatch covering all shards
    (global segment ids), coalesced with concurrent queries through
    the BatchedScorer."""

    def _stage(self, ids_by_shard, size: int):
        return self._ex.stager.sparse_rows_stacked(self._frags, ids_by_shard, size)

    def _score(self, staged, size: int):
        blocks, brow, bslot, bshard, num_rows = staged
        # route through the coalescing scorer: key on the staged arrays'
        # identity (same live objects ⇔ same snapshot — the BatchedScorer
        # contract), so concurrent queries over this chunk share one
        # kernel launch and one fetch
        scores = self._ex.stacked_scorer.score(
            (id(blocks), id(brow)),
            (blocks, brow, bslot, bshard, num_rows),
            self._resolved_srcs(),
        )
        return _fetch(scores)[: len(self._frags) * size].reshape(
            len(self._frags), size
        )


class _ShardScoreView:
    __slots__ = ("_p", "_i")

    def __init__(self, provider: _StackedLazyScores, i: int) -> None:
        self._p = provider
        self._i = i

    def __getitem__(self, row_id: int) -> int:
        p = self._p
        sc = p._scores[self._i]
        if row_id in sc:
            return sc[row_id]
        p._fanout()
        while row_id not in sc and p._pos < p._max_len:
            p._score_next()
            p._fanout()
        return sc[row_id]


class _SpmdLazyScores(_ChunkedLazyScores):
    """Mesh form: each chunk is ONE shard_map program
    (topn_scores_sparse_spmd) over block-sparse candidate stacks
    sharded across the mesh. The eager predecessor staged EVERY
    ranked-cache candidate densely (k × S × 128 KB — tens of GB at a
    50k-candidate cache); here a skewed walk that prunes in the hot
    head pays only the head chunk, and bytes staged scale with set
    containers (reference threshold walk semantics preserved by
    _ranked_walk; fragment.go:870-1002)."""

    def _stage(self, ids_by_shard, size: int):
        return self._ex.stager.sparse_rows_stack(self._frags, ids_by_shard, size)

    def _score(self, staged, size: int):
        blocks, brow, bslot = staged
        dev = self._ex._spmd_kernel("topn_scores_sparse", size)(
            self._resolved_srcs(), blocks, brow, bslot
        )
        # trim BEFORE the fetch: the shard axis is mesh-padded, so
        # slicing on device transfers only the real shards' scores
        # instead of fetching the padded plan and slicing on host
        return _fetch(dev[: len(self._frags), :size])


class _LazyScores:
    """Chunked on-demand candidate scoring for the device TopN walk.

    The walk consumes candidates in cached-count order and breaks as
    soon as counts fall below the running threshold (reference
    fragment.go:960-1002) — on skewed data it touches only the hot
    head. Scoring every cache candidate eagerly therefore wastes both
    HBM (50k candidates × 128 KB dense) and kernel time at the 1B-row
    scale. This provider scores pow2-sized chunks of the candidate
    list the first time the walk reads past them:

      * chunk staging keys depend only on (fragment state, chunk ids),
        so repeated queries hit the stager's HBM cache;
      * each chunk independently picks block-sparse vs dense staging by
        container occupancy (sparse wins below half-full);
      * dense chunks still coalesce through the BatchedScorer;
      * the first chunk is small (the walk usually prunes within the
        hot head — see _StackedLazyScores), later ones grow.
    """

    def __init__(self, ex, frag, pairs, src_words, shard=0, carry=None) -> None:
        self._ex = ex
        self._frag = frag
        self._pairs = pairs
        self._src = src_words
        self._scores: dict[int, int] = {}
        self._next = 0
        # cross-pass carry, same contract as _StackedLazyScores: pass 2
        # reads counts pass 1 computed for this (shard, src) pair
        self._shard = shard
        self._carry = carry
        if carry:
            self._scores.update(carry.seed(shard, [rid for rid, _ in pairs]))

    def _score_chunk(self) -> None:
        # ids materialise per chunk, never as one huge tuple — on a 50k-
        # candidate cache only the chunks the walk reaches pay anything
        size = _chunk_size(self._next)
        ids = _chunk_ids(self._pairs, self._next, self._next + size)
        self._next += size
        frag = self._frag
        occupied = frag.sparse_block_count(list(ids))
        if occupied * 2 < len(ids) * (SHARD_WIDTH >> 16):
            blocks, brow, bslot, num_rows = self._ex.stager.sparse_rows(frag, ids)
            dev = ops.sparse_intersection_counts(
                self._src, blocks, brow, bslot, num_rows
            )
            # trim on device: num_rows is pow2-padded, so fetching the
            # full vector and slicing on host transfers up to 2x the
            # real candidate scores
            scores = _fetch(dev[: len(ids)])
        else:
            # pow2-padded rows bound recompiles; trailing zero rows fall
            # off the zip below. Key on the staged array identity (not
            # frag.generation, which a concurrent import may bump
            # between staging and here): same live array object ⇔ same
            # snapshot, so coalesced peers can never mix matrices.
            mat = self._ex.stager.rows(frag, ids, pad_pow2=True)
            scores = self._ex.scorer.score(
                (id(frag), id(mat)), mat, self._src, trim=len(ids)
            )
        self._scores.update(zip(ids, (int(s) for s in scores)))
        if self._carry is not None:
            self._carry.add(self._shard, ids, scores)

    def __getitem__(self, row_id: int) -> int:
        while row_id not in self._scores and self._next < len(self._pairs):
            self._score_chunk()
        return self._scores[row_id]


def _vectorized_topn_walk(pairs_by_shard, provider, opt_: TopOptions):
    """All shards' ranked walks in one numpy pass, or None when the
    scalar fallback is required (tanimoto / attr filters).

    Exactness argument (mirrors _ranked_walk below, reference
    fragment.go:870-1002): the scalar walk's heap never pops, so once
    the first n qualifying candidates are pushed the heap minimum — the
    walk's threshold T — is FIXED: later pushes require count >= T.
    The walk therefore reduces to closed form per shard:
      phase 1: the first n candidates in cache order with
               cached>=min_threshold and score>=min_threshold;
               T = min of their scores;
      break:   the first later candidate with cached<T ends the walk;
      phase 2: candidates before the break with score >= T.
    Shards with fewer than n qualifying candidates scan their whole
    pairs list (the scalar loop never leaves phase 1). The cross-shard
    merge (pairs_add + final sort_pairs) is order-insensitive, so the
    picked SETS being identical makes the result bit-identical."""
    if opt_.tanimoto_threshold > 0:
        return None
    if opt_.filter_name and opt_.filter_values:
        return None
    n = 0 if opt_.row_ids else opt_.n
    mth = max(int(opt_.min_threshold), 1)
    lengths = np.array([len(p) for p in pairs_by_shard], dtype=np.int64)
    max_len = int(lengths.max()) if lengths.size else 0
    if max_len == 0:
        return []

    if n == 0:
        # exhaustive mode (pass 2 / n=0): every eligible candidate is
        # scored; pairs lists here are the explicit id set — small —
        # and usually fully covered by the cross-pass carry, so the
        # dict lookups below dispatch nothing
        ids_out: list[int] = []
        cnts_out: list[int] = []
        for i, pairs in enumerate(pairs_by_shard):
            if not pairs:
                continue
            view = provider.view(i)
            for rid, cnt in pairs:
                if cnt < mth:
                    continue
                sc = view[rid]
                if sc >= mth:
                    ids_out.append(rid)
                    cnts_out.append(sc)
        return _merge_picked(
            np.asarray(ids_out, dtype=np.int64),
            np.asarray(cnts_out, dtype=np.int64),
        )

    big = np.int64(1) << np.int64(62)
    while True:
        if provider._pos == 0:
            provider._score_next()
        smat, idm, cntm, vmask = provider.matrices()
        P = smat.shape[1]
        elig = vmask & (cntm >= mth)
        ok = elig & (smat >= mth)
        cum = np.cumsum(ok, axis=1)
        total_ok = cum[:, -1]
        has_n = total_ok >= n
        sel = ok & (cum <= n)
        T = np.where(has_n, np.where(sel, smat, big).min(axis=1), big)
        nth_pos = np.where(has_n, np.argmax(cum >= n, axis=1), P)
        colr = np.arange(P, dtype=np.int64)[None, :]
        after = colr > nth_pos[:, None]
        brk_mask = elig & after & (cntm < T[:, None])
        has_brk = brk_mask.any(axis=1)
        exhausted = P >= lengths
        done = (has_n & has_brk) | exhausted
        if done.all():
            brk = np.where(has_brk, np.argmax(brk_mask, axis=1), P)
            phase2 = (
                elig
                & after
                & (colr < brk[:, None])
                & (smat >= T[:, None])
            )
            picked = np.where(has_n[:, None], sel | phase2, ok)
            s_idx, c_idx = np.nonzero(picked)
            return _merge_picked(
                idm[s_idx, c_idx], smat[s_idx, c_idx].astype(np.int64)
            )
        if provider._pos >= max_len:
            # unreachable (P == provider._pos >= every shard's length
            # implies exhausted.all()); bail to the scalar walk rather
            # than risk looping
            return None
        provider._score_next()


def _merge_picked(ids: np.ndarray, counts: np.ndarray) -> list[tuple[int, int]]:
    """Cross-shard merge: sum counts per id (pairs_add semantics; final
    ordering is applied by the caller's sort_pairs)."""
    if ids.size == 0:
        return []
    uids, inv = np.unique(ids, return_inverse=True)
    sums = np.bincount(inv, weights=counts.astype(np.float64))
    return list(zip(uids.tolist(), sums.astype(np.int64).tolist()))


def _ranked_walk(frag, opt_: TopOptions, pairs, score_by_id) -> list[tuple[int, int]]:
    """Replay fragment.top's ranked walk (reference fragment.go:870-1002)
    with precomputed intersection counts — identical pruning, threshold,
    tanimoto, and attr-filter behavior, so device scoring stays
    bit-identical to the CPU path."""
    import heapq
    import math

    n = 0 if opt_.row_ids else opt_.n
    filters = set(opt_.filter_values) if (opt_.filter_name and opt_.filter_values) else None
    tanimoto_threshold = 0
    min_tanimoto = max_tanimoto = 0.0
    src_count = 0
    if opt_.tanimoto_threshold > 0:
        tanimoto_threshold = opt_.tanimoto_threshold
        src_count = opt_.src.count()
        min_tanimoto = float(src_count * tanimoto_threshold) / 100
        max_tanimoto = float(src_count * 100) / float(tanimoto_threshold)

    results: list[tuple[int, int]] = []
    for row_id, cnt in pairs:
        if cnt <= 0:
            continue
        if tanimoto_threshold > 0:
            if float(cnt) <= min_tanimoto or float(cnt) >= max_tanimoto:
                continue
        elif cnt < opt_.min_threshold:
            continue
        if filters is not None:
            attr = frag.row_attr_store.attrs(row_id) if frag.row_attr_store else None
            if not attr:
                continue
            value = attr.get(opt_.filter_name)
            if value is None or value not in filters:
                continue
        if n == 0 or len(results) < n:
            count = score_by_id[row_id]
            if count == 0:
                continue
            if tanimoto_threshold > 0:
                t = math.ceil(float(count * 100) / float(cnt + src_count - count))
                if t <= float(tanimoto_threshold):
                    continue
            elif count < opt_.min_threshold:
                continue
            heapq.heappush(results, (count, row_id))
            continue
        threshold = results[0][0]
        if threshold < opt_.min_threshold or cnt < threshold:
            break
        count = score_by_id[row_id]
        if count < threshold:
            continue
        heapq.heappush(results, (count, row_id))

    out = []
    while results:
        count, row_id = heapq.heappop(results)
        out.append((row_id, count))
    out.reverse()
    return out


def _row_from_device(words, shard: int) -> Row:
    t0 = time.monotonic()
    w32 = np.asarray(words)
    w64 = np.ascontiguousarray(w32).view("<u8")
    seg = Bitmap.from_words_range(w64, start=shard * SHARD_WIDTH)
    trace.attrib_add(trace.WF_TRANSFER_DECODE, time.monotonic() - t0)
    return Row.from_segment(shard, seg)


def _pairs_result(pairs: list[tuple[int, int]]) -> list[dict]:
    """JSON-shaped Pair list (reference Pair, cache.go:360)."""
    return [{"id": p[0], "count": p[1]} for p in pairs]
